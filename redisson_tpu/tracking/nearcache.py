"""Client near cache fed by the server's CLIENT TRACKING invalidation plane.

One ``ClientTracking`` per remote facade (``RemoteRedisson`` or
``ClusterRedisson`` — ``client.enable_tracking()``).  The wiring:

  * every node's dedicated pubsub connection (it already has a background
    reader thread) doubles as the **invalidation feed**: its stable
    ``CLIENT ID`` is the REDIRECT target;
  * every pooled DATA connection arms ``CLIENT TRACKING ON REDIRECT
    <feed-id>`` at connect time (``NodeClient.conn_setup``), so any read
    through the facade registers server-side and any write — by anyone —
    pushes an ``invalidate`` frame down the feed;
  * reads of tracked handles (``get_bucket``/``get_map``/``get_set``/
    ``get_bloom_filter`` below) consult one shared bounded-LRU
    ``NearCache`` first; a hit never touches the wire at all.

Coherence disciplines:

  * **populate-vs-invalidate race**: a fetch snapshots the cache GENERATION
    of its name before going to the wire and only populates if no
    invalidation (or flush) bumped it meanwhile — the wire analog of the
    embedded localcache's read+populate-under-the-record-lock.
  * **reconnection CLEAR**: a feed that dies may have dropped invalidation
    frames.  The whole cache flushes, the plane's EPOCH bumps, and every
    data connection armed against the dead feed retires as it releases
    (``ConnectionPool.release_filter``) — a connection whose server-side
    tracking state is gone must never serve another cache-populating read.
    Node-level disconnects (events hub) flush too.
  * **bloom negatives**: a bloom ``contains`` miss is immutable-until-add,
    so negative (and positive — those are immutable outright) lookups are
    cached per (filter, key) and the filter's add stream invalidates them.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from redisson_tpu.net import commands as C
from redisson_tpu.net.client import ConnectionError_
from redisson_tpu.net.resp import RespError


class NearCache:
    """Bounded-LRU (name, subkey) -> value cache with per-name generations.

    ``gen(name)`` / ``put(..., gen)`` implement the populate guard: an
    invalidation or flush between the gen snapshot and the put bumps the
    generation, so the stale fetch result is discarded instead of cached.
    """

    def __init__(self, max_entries: int = 65536):
        self._lock = threading.Lock()
        self.max_entries = max_entries
        self._entries: "OrderedDict[Tuple[str, Any], Any]" = OrderedDict()
        self._index: Dict[str, set] = {}  # name -> subkeys present
        self._gens: Dict[str, int] = {}
        self._flush_gen = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.flushes = 0

    def gen(self, name: str) -> Tuple[int, int]:
        with self._lock:
            return (self._flush_gen, self._gens.get(name, 0))

    def get(self, name: str, sub) -> Tuple[bool, Any]:
        key = (name, sub)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return True, self._entries[key]
            self.misses += 1
            return False, None

    def put(self, name: str, sub, value, gen: Tuple[int, int]) -> bool:
        with self._lock:
            if gen != (self._flush_gen, self._gens.get(name, 0)):
                return False  # an invalidation raced the fetch: stay empty
            self._entries[(name, sub)] = value
            self._entries.move_to_end((name, sub))
            self._index.setdefault(name, set()).add(sub)
            while len(self._entries) > self.max_entries:
                (en, es), _v = self._entries.popitem(last=False)
                subs = self._index.get(en)
                if subs is not None:
                    subs.discard(es)
                    if not subs:
                        del self._index[en]
                self.evictions += 1
            return True

    def invalidate(self, name: str) -> None:
        with self._lock:
            self._gens[name] = self._gens.get(name, 0) + 1
            if len(self._gens) > 4 * max(self.max_entries, 1024):
                # generations must stay monotonic per name for the populate
                # guard, so they cannot be pruned individually — bound the
                # registry by promoting to a full flush instead
                self._flush_locked()
                return
            subs = self._index.pop(name, None)
            if subs:
                for sub in subs:
                    self._entries.pop((name, sub), None)
            self.invalidations += 1

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        self._flush_gen += 1
        self._entries.clear()
        self._index.clear()
        self._gens.clear()
        self.flushes += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "flushes": self.flushes,
            }


def _subkey(tag: str, key) -> Optional[tuple]:
    """Cacheable subkey for a method arg, or None when the arg cannot key a
    dict (unhashable user objects bypass the cache, never break it)."""
    try:
        hash(key)
    except TypeError:
        return None
    return (tag, key)


class _HubListener:
    """events-hub adapter: ANY node-level disconnect flushes the cache (the
    gap may have swallowed invalidations from that node)."""

    def __init__(self, plane: "ClientTracking"):
        self._plane = plane

    def on_connect(self, address: str) -> None:  # noqa: D401 — no-op
        pass

    def on_disconnect(self, address: str) -> None:
        self._plane.connection_lost(address)


class ClientTracking:
    """The client half of the tracking plane: feed arming + the shared
    ``NearCache`` + tracked-handle factories."""

    def __init__(self, client, cache_entries: int = 65536, noloop: bool = False):
        self.client = client
        self.cache = NearCache(cache_entries)
        self.noloop = noloop
        self._lock = threading.RLock()
        # global event counter (stats/telemetry only; the retirement logic
        # is PER-NODE — see _rtpu_feed_epoch below)
        self._epoch = 0
        self._closed = False
        self._name_listeners: Dict[str, List[Callable]] = {}
        self._hub_listener = None
        # future nodes (cluster topology refresh constructs NodeClients from
        # _node_kw) inherit the arming hook automatically
        nk = getattr(client, "_node_kw", None)
        if nk is not None:
            nk["conn_setup"] = self._conn_setup
        # each ShardEntry snapshotted _node_kw at creation: pre-enable
        # entries need the hook injected so replicas they discover LATER
        # arm too (a replica-routed read on an unarmed conn would populate
        # the cache with no server-side registration — stale forever)
        for entry in self._entries():
            entry._node_kw["conn_setup"] = self._conn_setup
        for node in self._nodes():
            self._install(node)
            # arm the feed NOW: lazy arming inside the first read's connect
            # would flush the cache mid-fetch and void that read's populate
            try:
                self._ensure_feed(node)
            except Exception:  # noqa: BLE001 — node down: armed on reconnect
                pass
        hub = getattr(client, "events_hub", None)
        if hub is not None:
            self._hub_listener = hub.add_listener(_HubListener(self))

    # -- wiring ---------------------------------------------------------------

    def _entries(self) -> list:
        entries = getattr(self.client, "entries", None)
        return list(entries()) if callable(entries) else []

    def _nodes(self) -> list:
        node = getattr(self.client, "node", None)
        if node is not None:
            return [node]
        # masters AND replicas: with read_mode=replica/master_slave, reads
        # route to replicas and populate the near cache — those reads must
        # register on the replica's tracking table (REPLPUSH apply
        # invalidates there), so replica connections arm exactly like
        # master ones
        out = []
        for e in self._entries():
            out.append(e.master)
            out.extend(e.replicas.values())
        return out

    def _install(self, node) -> None:
        node.conn_setup = self._conn_setup
        node.pool.release_filter = self._release_ok
        # existing idle connections predate the plane: retire them so every
        # pooled connection goes through the arming handshake
        node.pool.clear_idle()

    def _conn_setup(self, node, conn) -> None:
        if self._closed:
            return
        # nodes created AFTER enable (cluster topology refresh) inherit the
        # setup hook via _node_kw but not the pool filter — install it here
        # (idempotent) so their stale-epoch conns retire on release too
        # (== not `is`: each attribute access mints a fresh bound-method
        # object, so `is` never matches; bound methods compare by
        # __self__/__func__)
        if node.pool.release_filter != self._release_ok:
            node.pool.release_filter = self._release_ok
        feed = self._ensure_feed(node)
        if feed.client_id is None:
            raise ConnectionError_(
                f"tracking feed to {node.address} has no client id"
            )
        args = ["CLIENT", "TRACKING", "ON", "REDIRECT", str(feed.client_id)]
        if self.noloop:
            args.append("NOLOOP")
        # snapshot the node's feed generation AFTER _ensure_feed (which may
        # have bumped it) but BEFORE the arming round-trip: if the feed dies
        # while CLIENT TRACKING is in flight, _on_feed_down bumps the node
        # epoch and this conn — armed against the now-dead feed — must stamp
        # the OLD epoch so _release_ok retires it instead of pooling a conn
        # whose server-side push route delivers nowhere
        epoch = getattr(node, "_rtpu_feed_epoch", 0)
        reply = conn.execute(*args)
        if isinstance(reply, RespError):
            raise reply
        # release retires the conn the moment the node's feed it redirects
        # to is no longer the live one — a conn whose feed died has lost its
        # server-side tracking (redirect-broken), so pooling it would let
        # untracked reads populate the cache invisibly
        conn._rtpu_track_node = node
        conn._rtpu_track_epoch = epoch

    def _ensure_feed(self, node):
        feed = node.pubsub()  # recreated by NodeClient when the old one died
        if not getattr(feed, "_rtpu_inv_armed", False):
            with self._lock:
                if not getattr(feed, "_rtpu_inv_armed", False):
                    # a NEW feed = first enable OR the previous feed ended:
                    # the reconnection-CLEAR sequence, IN THIS ORDER —
                    # (1) bump the node's feed generation (in-use conns
                    #     armed against the old feed retire on release),
                    # (2) clear the node's idle pool (old-feed conns can no
                    #     longer be acquired),
                    # (3) flush the cache (any populate whose gen snapshot
                    #     predates this is voided).
                    # clear_idle BEFORE flush matters: a read whose gen
                    # snapshot post-dates the flush can then only acquire a
                    # freshly-armed conn — flushing first would leave a
                    # window where such a read acquires an old-feed idle
                    # conn and populates an entry no live feed can ever
                    # invalidate.  Together: every populate that survives
                    # was read on a connection whose registrations the LIVE
                    # feed serves.
                    node._rtpu_feed_epoch = getattr(node, "_rtpu_feed_epoch", 0) + 1
                    self._epoch += 1
                    node.pool.clear_idle()
                    self.cache.flush()
                    self._notify(None)
                    feed.add_invalidation_listener(self._on_invalidate)
                    feed.on_disconnect = self._on_feed_down
                    feed._rtpu_inv_armed = True
                    feed._rtpu_inv_node = node
        return feed

    def _release_ok(self, conn) -> bool:
        node = getattr(conn, "_rtpu_track_node", None)
        if node is None:
            return False  # pre-plane conn: retire, a fresh one arms properly
        return (
            getattr(conn, "_rtpu_track_epoch", -1)
            == getattr(node, "_rtpu_feed_epoch", 0)
        )

    # -- invalidation stream --------------------------------------------------

    def _on_invalidate(self, keys) -> None:
        if keys is None:
            # FLUSHALL / flush-everything frame
            self.cache.flush()
            self._notify(None)
            return
        for k in keys:
            name = k.decode() if isinstance(k, (bytes, bytearray)) else str(k)
            self.cache.invalidate(name)
            self._notify(name)

    def _on_feed_down(self, feed) -> None:
        node = getattr(feed, "_rtpu_inv_node", None)
        with self._lock:
            if self._closed:
                return
            # same ordering as the arm path: generation bump first (in-use
            # conns retire on release), then idle clear, then flush — a read
            # whose gen snapshot post-dates the flush must only be able to
            # acquire a freshly-armed conn
            if node is not None:
                node._rtpu_feed_epoch = getattr(node, "_rtpu_feed_epoch", 0) + 1
            self._epoch += 1
        if node is not None:
            node.pool.clear_idle()
        self.cache.flush()
        self._notify(None)

    def connection_lost(self, address: str) -> None:
        """Node-level disconnect (events hub): the gap may have swallowed
        pushes from that node — flush (conn retirement is owned by the
        feed-generation machinery; a data-conn blip with the feed intact
        loses nothing conn-wise)."""
        if self._closed:
            return
        with self._lock:
            self._epoch += 1
        self.cache.flush()
        self._notify(None)

    # -- name listeners (localcache TRACKING mode rides these) ----------------

    def add_name_listener(self, name: str, fn: Callable) -> Callable:
        """fn(name) on that name's invalidation; fn(None) on a full flush."""
        with self._lock:
            self._name_listeners.setdefault(name, []).append(fn)
        return fn

    def remove_name_listener(self, name: str, fn: Callable) -> None:
        with self._lock:
            fns = self._name_listeners.get(name)
            if fns is None:
                return
            try:
                fns.remove(fn)
            except ValueError:
                return
            if not fns:
                del self._name_listeners[name]

    def _notify(self, name: Optional[str]) -> None:
        with self._lock:
            if name is None:
                fns = [f for lst in self._name_listeners.values() for f in lst]
            else:
                fns = list(self._name_listeners.get(name, ()))
        for fn in fns:
            try:
                fn(name)
            except Exception:  # noqa: BLE001 — listener bugs stay contained
                pass

    # -- read-through helper --------------------------------------------------

    def cached_call(self, name: str, sub, fetch: Callable[[], Any],
                    cache_none: bool = False) -> Any:
        hit, v = self.cache.get(name, sub)
        if hit:
            return v
        gen = self.cache.gen(name)
        v = fetch()
        if v is not None or cache_none:
            self.cache.put(name, sub, v, gen)
        return v

    # -- tracked handles ------------------------------------------------------

    def get_bucket(self, name: str, codec=None) -> "TrackedBucket":
        return TrackedBucket(self, name, codec)

    def get_map(self, name: str, codec=None) -> "TrackedMap":
        return TrackedMap(self, name, codec)

    def get_set(self, name: str, codec=None) -> "TrackedSet":
        return TrackedSet(self, name, codec)

    def get_bloom_filter(self, name: str, codec=None) -> "NearBloomFilter":
        return NearBloomFilter(self, name, codec)

    # -- lifecycle ------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    def stats(self) -> Dict[str, Any]:
        out = self.cache.stats()
        out["epoch"] = self._epoch
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._name_listeners.clear()
        hub = getattr(self.client, "events_hub", None)
        if hub is not None and self._hub_listener is not None:
            hub.remove_listener(self._hub_listener)
        # == not `is` throughout: `self._conn_setup` mints a fresh bound-
        # method object per access, so identity never matches the hook we
        # installed — `is` left every hook in place after close(), and the
        # still-installed _release_ok then closed every unarmed connection
        # on release (one TCP connect per op on a closed plane)
        for node in self._nodes():
            if node.conn_setup == self._conn_setup:
                node.conn_setup = None
            if node.pool.release_filter == self._release_ok:
                node.pool.release_filter = None
        nk = getattr(self.client, "_node_kw", None)
        if nk is not None and nk.get("conn_setup") == self._conn_setup:
            nk.pop("conn_setup", None)
        for entry in self._entries():
            ek = getattr(entry, "_node_kw", None)
            if ek is not None and ek.get("conn_setup") == self._conn_setup:
                ek.pop("conn_setup", None)
        self.cache.flush()


class _TrackedProxyBase:
    """Shared shape of ALL tracked handles: explicit cached read methods +
    a generic fall-through that locally invalidates after any write-method
    (same read/write split the wire router uses).  Every mutator a handle
    does not explicitly wrap MUST land here: under NOLOOP the server
    suppresses the self-write push, so a write slipping through
    undecorated would leave the near cache permanently stale."""

    _plane: ClientTracking
    name: str

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        fn = getattr(self._proxy, method)
        if callable(fn) and C.objcall_is_write(method):
            plane, name = self._plane, self.name

            def call(*a, **kw):
                # invalidate even when the wire call raises: a timeout /
                # dropped reply may still have APPLIED server-side, and
                # under NOLOOP no push will correct the cache for us
                try:
                    return fn(*a, **kw)
                finally:
                    plane.cache.invalidate(name)

            call.__name__ = method
            return call
        return fn


class TrackedBucket(_TrackedProxyBase):
    """RBucket read path over the near cache (value keyed at (name, 'get'));
    mutators outside ``set`` (try_set, delete, compare_and_set,
    get_and_set, ...) ride the base write fall-through."""

    def __init__(self, plane: ClientTracking, name: str, codec=None):
        self._plane = plane
        self.name = name
        self._proxy = plane.client.get_bucket(name, codec)

    def get(self):
        return self._plane.cached_call(self.name, ("get",), self._proxy.get)

    def set(self, value, ttl: Optional[float] = None) -> None:
        if self._plane.noloop and ttl is None:
            # NOLOOP: the server will NOT push our own write back at us, so
            # the freshly-written value can seed our own cache (the
            # excludedId own-write discipline of the reference's localcache).
            # Gen-guarded: a concurrent writer's invalidation between the
            # snapshot and the populate voids it.
            gen = self._plane.cache.gen(self.name)
            try:
                self._proxy.set(value, ttl)
            except BaseException:
                # the write may still have APPLIED (lost reply): drop any
                # cached value — under NOLOOP no push corrects it for us
                self._plane.cache.invalidate(self.name)
                raise
            self._plane.cache.invalidate(self.name)
            gen = (gen[0], gen[1] + 1)  # our own invalidation, just issued
            self._plane.cache.put(self.name, ("get",), value, gen)
            return
        try:
            self._proxy.set(value, ttl)
        finally:
            # own-write invalidation NOW, even on a raised (possibly still
            # applied) call; the server's push also comes unless NOLOOP —
            # arriving later, it just re-invalidates
            self._plane.cache.invalidate(self.name)


class TrackedMap(_TrackedProxyBase):
    """RMap read path (get / get_all / contains_key) over the near cache."""

    def __init__(self, plane: ClientTracking, name: str, codec=None):
        self._plane = plane
        self.name = name
        self._proxy = plane.client.get_map(name, codec)

    def get(self, key):
        sub = _subkey("mget", key)
        if sub is None:
            return self._proxy.get(key)
        return self._plane.cached_call(self.name, sub, lambda: self._proxy.get(key))

    def contains_key(self, key) -> bool:
        sub = _subkey("mhas", key)
        if sub is None:
            return self._proxy.contains_key(key)
        return self._plane.cached_call(
            self.name, sub, lambda: self._proxy.contains_key(key), cache_none=True
        )

    def get_all(self, keys) -> Dict:
        out, missing = {}, []
        cache = self._plane.cache
        for k in keys:
            sub = _subkey("mget", k)
            hit, v = cache.get(self.name, sub) if sub is not None else (False, None)
            if hit and v is not None:
                out[k] = v
            else:
                missing.append(k)
        if missing:
            gen = cache.gen(self.name)
            fetched = self._proxy.get_all(list(missing))
            for k, v in fetched.items():
                sub = _subkey("mget", k)
                if sub is not None and v is not None:
                    cache.put(self.name, sub, v, gen)
            out.update(fetched)
        return out


class TrackedSet(_TrackedProxyBase):
    """RSet membership over the near cache."""

    def __init__(self, plane: ClientTracking, name: str, codec=None):
        self._plane = plane
        self.name = name
        self._proxy = plane.client.get_set(name, codec)

    def contains(self, value) -> bool:
        sub = _subkey("shas", value)
        if sub is None:
            return self._proxy.contains(value)
        return self._plane.cached_call(
            self.name, sub, lambda: self._proxy.contains(value), cache_none=True
        )


class NearBloomFilter(_TrackedProxyBase):
    """Bloom membership over the near cache (the sketch leg of the plane).

    A bloom ``contains`` answer is immutable-until-add for negatives and
    immutable outright for positives, so BOTH cache client-side keyed by
    (filter, key); the filter's add stream (every BF.ADD/MADD is a write on
    the filter name) invalidates the lot — add/add_all/add_each and any
    other mutator ride the base write fall-through.  Read-mostly membership
    traffic answers locally and only pays the wire on invalidation."""

    def __init__(self, plane: ClientTracking, name: str, codec=None):
        self._plane = plane
        self.name = name
        self._proxy = plane.client.get_bloom_filter(name, codec)

    def _sub(self, obj) -> Optional[tuple]:
        if isinstance(obj, (int, np.integer)):
            return ("bf", int(obj))
        if isinstance(obj, bytes):
            return ("bf", obj)
        if isinstance(obj, str):
            return ("bf", obj)
        return _subkey("bf", obj)

    def contains(self, obj) -> bool:
        sub = self._sub(obj)
        if sub is None:
            return self._proxy.contains(obj)
        return bool(self._plane.cached_call(
            self.name, sub, lambda: bool(self._proxy.contains(obj)),
            cache_none=True,
        ))

    def contains_each(self, objs) -> np.ndarray:
        objs = np.asarray(objs)
        if objs.dtype.kind not in "iu":
            return self._proxy.contains_each(objs)
        flat = objs.reshape(-1)
        out = np.zeros(flat.shape[0], dtype=bool)
        cache = self._plane.cache
        miss_idx: List[int] = []
        for i, k in enumerate(flat):
            hit, v = cache.get(self.name, ("bf", int(k)))
            if hit:
                out[i] = v
            else:
                miss_idx.append(i)
        if miss_idx:
            gen = cache.gen(self.name)
            wire = self._proxy.contains_each(flat[miss_idx])
            for j, i in enumerate(miss_idx):
                val = bool(wire[j])
                out[i] = val
                cache.put(self.name, ("bf", int(flat[i])), val, gen)
        return out

    def count_contains(self, objs) -> int:
        return int(self.contains_each(objs).sum())
