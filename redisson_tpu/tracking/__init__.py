"""Server-assisted client tracking: the RESP3 invalidation plane.

Two halves, one protocol (Redis 6 ``CLIENT TRACKING`` reimagined for this
wire — ISSUE 7 / ROADMAP "RESP3 client-side caching"):

  * ``tracking/table.py`` — the SERVER side: a per-node ``TrackingTable``
    remembers which connections read which keys (default mode, bounded with
    synthetic-invalidation eviction) or which prefixes they subscribed
    (BCAST mode, no per-key memory), and pushes RESP3
    ``>2 invalidate [key...]`` frames on every mutating verb, expiry,
    FLUSHALL, and slot-migration handoff.
  * ``tracking/nearcache.py`` — the CLIENT side: one ``NearCache`` per
    remote facade fed by the invalidation stream over a dedicated REDIRECT
    connection, consulted by the read paths of buckets, maps, sets, the
    generalized ``localcache`` TRACKING sync mode, and bloom negative
    lookups.
"""
from redisson_tpu.tracking.table import ConnTracking, TrackingTable  # noqa: F401
