"""Server-side key-tracking table (the Redis 6 ``CLIENT TRACKING`` role).

One ``TrackingTable`` per ``TpuServer``.  Connections opt in with
``CLIENT TRACKING ON [REDIRECT <client-id>] [BCAST [PREFIX <p>]...]
[NOLOOP]``; from then on:

  * **default mode** — every READ a tracking connection performs records
    (key -> client-id) in a bounded table.  The registration happens
    PRE-dispatch (before the read handler runs): a concurrent writer on
    another worker thread then either applied before our read (we read the
    new value) or scans the table after our registration (we get the
    invalidation) — the ordering race a single-threaded Redis never has.
    The table is bounded by ``max_keys``: overflow evicts the
    least-recently-registered key and sends its trackers a SYNTHETIC
    invalidation (the Redis ``tracking-table-max-keys`` discipline), so a
    client can never hold a stale entry the server no longer remembers.
  * **BCAST mode** — no per-key memory; the connection subscribes key
    PREFIXES and every write under a prefix broadcasts.

Every mutating verb (post-dispatch, after the handler applied), expiry,
``FLUSHALL`` and the slot-migration/failover handoff emit a RESP3
``>2\r\n$10\r\ninvalidate\r\n*1\r\n$<n>\r\n<key>\r\n`` push on the tracking
connection — or on its REDIRECT target (the RESP2-client path: the data
connection stays push-free, a dedicated connection with a reader consumes
the stream).  Pushes ride the existing per-connection writer/completion
queue (``ctx.push`` -> ``write_q``), so FIFO ordering with ``_PendingFrame``
readbacks and the proto-snapshot contract are preserved by construction.

Slot handoffs are FENCE-EPOCH-stamped: ``invalidate_slot(slot, epoch)``
records the highest epoch it emitted for each slot, so a journaled
coordinator's idempotent re-issue (same epoch) or a stale coordinator's
late write (lower epoch) cannot re-storm clients — and a ``RECOVERING``
slot invalidates BEFORE it serves again (``set_slot_recovering``).

Disconnect cleanup: a dying connection's tracked keys leave the table with
it, and a dying REDIRECT *target* breaks tracking for every connection that
pointed at it (their cached state can no longer be invalidated, so serving
it would be silently stale — tracking turns OFF and the break is counted).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Set

from redisson_tpu.net import commands as C
from redisson_tpu.net.resp import Push, RespError
from redisson_tpu.utils.crc16 import calc_slot

# default bound on the per-node tracked-key table (Redis's
# tracking-table-max-keys default is 1e6; this node also holds device state,
# so the default is tighter — CONFIG SET tracking-table-max-keys tunes it)
DEFAULT_MAX_KEYS = 65536


class ConnTracking:
    """Per-connection tracking state (lives on ``CommandContext.tracking``)."""

    __slots__ = ("on", "bcast", "prefixes", "redirect", "noloop", "nkeys")

    def __init__(self):
        self.on = False
        self.bcast = False
        self.prefixes: tuple = ()
        self.redirect: Optional[int] = None  # target client id (RESP2 path)
        self.noloop = False
        self.nkeys = 0  # keys currently tracked for this conn (default mode)

    def flags(self) -> List[bytes]:
        """CLIENT TRACKINGINFO flag list (Redis wording)."""
        out = [b"on" if self.on else b"off"]
        if self.bcast:
            out.append(b"bcast")
        if self.noloop:
            out.append(b"noloop")
        return out


class TrackingTable:
    def __init__(self, server, max_keys: int = DEFAULT_MAX_KEYS):
        self._server = server
        self._lock = threading.Lock()
        self.max_keys = max_keys
        # all registered connections (client id -> CommandContext); tracking
        # needs the id->push route for REDIRECT targets even before the
        # target itself enables anything
        self._conns: Dict[int, object] = {}
        # tracking-ENABLED connections (client id -> ConnTracking)
        self._states: Dict[int, ConnTracking] = {}
        # default-mode memory: key -> client ids, LRU by registration recency
        self._keys: "OrderedDict[str, Set[int]]" = OrderedDict()
        # slot -> tracked keys in it, maintained at registration time so a
        # slot handoff invalidates in O(keys-in-slot) instead of scanning
        # the whole table under the lock (the dispatch hot path shares it)
        self._slot_index: Dict[int, Set[str]] = {}
        # cid -> keys it registered (reverse index): disconnect purge is
        # O(keys-owned-by-conn), not O(table) — same scan-under-the-
        # dispatch-lock hazard as the slot scan
        self._client_keys: Dict[int, Set[str]] = {}
        # BCAST-enabled cids: note_write's stateless prefix match walks
        # only these (the common no-BCAST deployment pays nothing per key)
        self._bcast_cids: Set[int] = set()
        # fence-epoch memory: slot -> highest epoch already invalidated (the
        # idempotence that makes journal-resume re-issues push-storm-free)
        self._slot_epochs: Dict[int, int] = {}
        # `active` is read LOCK-FREE on the dispatch hot path (an int load);
        # it counts tracking-enabled connections so a server with no
        # tracking clients pays one attribute load + one compare per command
        self.active = 0
        self.stats = {
            "pushes": 0,            # invalidation push frames sent
            "keys_invalidated": 0,  # keys named across those frames
            "overflow_evictions": 0,
            "redirect_broken": 0,   # conns whose REDIRECT target died
            "dropped": 0,           # push had no live route (conn raced away)
            "slot_flushes": 0,      # slot-handoff invalidation sweeps
        }

    # -- connection lifecycle -------------------------------------------------

    def register_conn(self, ctx) -> None:
        with self._lock:
            self._conns[ctx.client_id] = ctx

    def unregister_conn(self, ctx) -> None:
        """Disconnect cleanup: drop the conn's tracked keys and, if it was a
        REDIRECT target, break (turn off) tracking for its dependents."""
        cid = ctx.client_id
        synth_target = None
        with self._lock:
            self._conns.pop(cid, None)
            st = self._states.pop(cid, None)
            self._bcast_cids.discard(cid)
            if st is not None and st.on:
                self.active -= 1
            owned = self._purge_client_locked(cid)
            # a dying DATA connection strands its registrations: the server
            # is about to forget them, but the client's near cache (fed
            # through a REDIRECT target that is still alive) may hold the
            # entries those registrations guarded.  Synthetic invalidation
            # through the surviving feed — the same never-silently-stale
            # rule as bounded-table overflow.  Without REDIRECT the push
            # route WAS the dead socket: nothing to tell (Redis behavior).
            if (st is not None and st.on and not st.bcast
                    and st.redirect is not None and owned):
                synth_target = self._conns.get(st.redirect)
            # a dead redirect target orphans its dependents' invalidation
            # stream: their caches can never be invalidated again, so their
            # tracking MUST break loudly (Redis sends tracking-redir-broken;
            # here the state flips off and the break is counted)
            for dep_cid, dep_st in list(self._states.items()):
                if dep_st.redirect == cid:
                    dep_st.on = False
                    dep_st.redirect = None
                    self.active -= 1
                    self._bcast_cids.discard(dep_cid)
                    self.stats["redirect_broken"] += 1
                    del self._states[dep_cid]
                    self._purge_client_locked(dep_cid)
        if synth_target is not None:
            self._push_to(synth_target, owned)

    def _purge_client_locked(self, cid: int) -> List[str]:
        """Drop every registration `cid` holds, O(keys-owned-by-conn) via
        the reverse index.  Returns the names it had registered."""
        owned = self._client_keys.pop(cid, None)
        if not owned:
            return []
        for name in owned:
            cids = self._keys.get(name)
            if cids is None:
                continue
            cids.discard(cid)
            if not cids:
                del self._keys[name]
                self._index_del_locked(name)
        return list(owned)

    # -- slot index (every _keys add/remove mirrors here) ---------------------

    def _index_add_locked(self, name: str) -> None:
        self._slot_index.setdefault(calc_slot(name.encode()), set()).add(name)

    def _index_del_locked(self, name: str) -> None:
        slot = calc_slot(name.encode())
        keys = self._slot_index.get(slot)
        if keys is not None:
            keys.discard(name)
            if not keys:
                del self._slot_index[slot]

    # -- CLIENT TRACKING ------------------------------------------------------

    def enable(self, ctx, *, bcast: bool = False, prefixes=(),
               redirect: Optional[int] = None, noloop: bool = False) -> None:
        with self._lock:
            if redirect is not None and redirect not in self._conns:
                raise RespError(
                    "ERR The client ID you want redirect to does not exist"
                )
            st = self._states.get(ctx.client_id)
            if st is None:
                st = ConnTracking()
            if not st.on:
                self.active += 1
            st.on = True
            st.bcast = bool(bcast)
            st.prefixes = tuple(prefixes) if bcast else ()
            st.redirect = redirect
            st.noloop = bool(noloop)
            self._states[ctx.client_id] = st
            if st.bcast:
                self._bcast_cids.add(ctx.client_id)
            else:
                self._bcast_cids.discard(ctx.client_id)
            ctx.tracking = st

    def disable(self, ctx) -> None:
        with self._lock:
            st = self._states.pop(ctx.client_id, None)
            self._bcast_cids.discard(ctx.client_id)
            if st is not None and st.on:
                self.active -= 1
                st.on = False
            self._purge_client_locked(ctx.client_id)
            ctx.tracking = st

    def state_of(self, ctx) -> Optional[ConnTracking]:
        with self._lock:
            return self._states.get(ctx.client_id)

    # -- dispatch hooks (server/registry.py) ----------------------------------

    def pre_dispatch(self, ctx, cmd: bytes, args) -> None:
        """READ registration, BEFORE the handler runs (see module doc for
        why pre- and not post-: the registration must be visible to any
        writer whose mutation our read missed)."""
        st = ctx.tracking
        if st is None or not st.on or st.bcast:
            return
        name = cmd.decode()
        # OBJCALLV is the transactional READ — write-classified only so it
        # routes to the committing master (the version source); here it
        # registers like any read and must never invalidate
        if name != "OBJCALLV" and C.is_write(name, args):
            return
        keys = C.command_keys(name, args)
        if keys:
            self.note_read(ctx, [self._kname(k) for k in keys])

    def post_dispatch(self, ctx, cmd: bytes, args) -> None:
        """WRITE invalidation, AFTER the handler applied successfully."""
        name = cmd.decode()
        if name in ("FLUSHALL", "FLUSHDB"):
            self.invalidate_all(ctx)
            return
        if name == "OBJCALLV" or not C.is_write(name, args):
            return
        keys = C.command_keys(name, args)
        if keys:
            names = [self._kname(k) for k in keys]
            self.note_write(names, ctx)
            self._note_search_ingest(names)

    def _note_search_ingest(self, names: List[str]) -> None:
        """A write under a search index's prefixes is that index's INGEST
        STREAM: invalidate the index's synthetic query key so tracked
        FT.SEARCH results (near-cached KNN hits) never serve stale (ISSUE
        11).  Writer NOLOOP is deliberately NOT honored — the writer's own
        cached query results are just as stale as anyone's.  Runs only when
        tracking is active (post_dispatch already gated) and only if the
        search service exists."""
        svc = self._server.engine._services.get("search")
        if svc is None:
            return
        try:
            qkeys = svc.ingest_touched(names)
        except Exception:  # noqa: BLE001 — instrumentation must not fail writes
            return
        if qkeys:
            self.note_write(qkeys, None)

    @staticmethod
    def _kname(k) -> str:
        return k.decode() if isinstance(k, (bytes, bytearray)) else str(k)

    # -- default-mode memory --------------------------------------------------

    def note_read(self, ctx, names: List[str]) -> None:
        cid = ctx.client_id
        overflow: List[tuple] = []
        with self._lock:
            st = self._states.get(cid)
            if st is None or not st.on or st.bcast:
                return
            for name in names:
                cids = self._keys.get(name)
                if cids is None:
                    cids = self._keys[name] = set()
                    self._index_add_locked(name)
                elif cid in cids:
                    self._keys.move_to_end(name)
                    continue
                cids.add(cid)
                self._client_keys.setdefault(cid, set()).add(name)
                st.nkeys += 1
                self._keys.move_to_end(name)
            overflow = self._evict_overflow_locked()
        for victim, vcids in overflow:
            targets: Dict[int, List[str]] = {vc: [victim] for vc in vcids}
            self._deliver(targets)

    def _evict_overflow_locked(self) -> List[tuple]:
        """Bounded table: evict oldest-registered keys with a SYNTHETIC
        invalidation to their trackers — the client forgets exactly what
        the server is about to forget (never silently stale).  Returns the
        (key, cids) pairs to deliver AFTER the lock drops."""
        overflow: List[tuple] = []
        while len(self._keys) > self.max_keys:
            victim, vcids = self._keys.popitem(last=False)
            self._index_del_locked(victim)
            self.stats["overflow_evictions"] += 1
            for vc in vcids:
                vst = self._states.get(vc)
                if vst is not None:
                    vst.nkeys -= 1
                ck = self._client_keys.get(vc)
                if ck is not None:
                    ck.discard(victim)
            overflow.append((victim, vcids))
        return overflow

    # -- write-side invalidation ----------------------------------------------

    def note_write(self, names: List[str], writer_ctx=None) -> None:
        """Invalidate `names` for every interested connection.  Default-mode
        entries are POPPED (one shot, like Redis); BCAST prefixes match
        statelessly.  ``writer_ctx`` with NOLOOP set is skipped."""
        if not names:
            return
        writer_cid = writer_ctx.client_id if writer_ctx is not None else None
        targets: Dict[int, List[str]] = {}
        overflow: List[tuple] = []
        with self._lock:
            if not self._states:
                return
            wst = self._states.get(writer_cid) if writer_cid is not None else None
            for name in names:
                cids = self._keys.pop(name, None)
                keep: Set[int] = set()
                if cids:
                    for cid in cids:
                        st = self._states.get(cid)
                        if st is None:
                            continue
                        if cid == writer_cid and st.noloop:
                            # NOLOOP self-write: the push is suppressed AND
                            # the registration survives (see below).  "Self"
                            # is deliberately ONE CONNECTION (Redis's own
                            # scope), NOT every conn sharing the writer's
                            # redirect feed: a same-facade write through a
                            # PLAIN (untracked) handle rides the same armed
                            # pool, and only the push keeps the facade's
                            # near cache coherent for it — widening "self"
                            # to the feed would make any mixed tracked/plain
                            # usage silently stale forever, for a cross-conn
                            # self-push saving that measures as noise
                            # (config6 13.06x -> 13.24x).
                            keep.add(cid)
                            continue
                        st.nkeys -= 1
                        ck = self._client_keys.get(cid)
                        if ck is not None:
                            ck.discard(name)
                        targets.setdefault(cid, []).append(name)
                # a NOLOOP writer's own write REGISTERS the key for it:
                # its near cache seeds the value it just wrote (tracked
                # handles' own-write discipline), so a LATER foreign write
                # must find a registration to invalidate — popping it (or
                # never having one, for a write with no prior read) would
                # leave the seeded entry silently stale forever
                if (wst is not None and wst.on and wst.noloop
                        and not wst.bcast and writer_cid not in keep):
                    keep.add(writer_cid)
                    self._client_keys.setdefault(writer_cid, set()).add(name)
                    wst.nkeys += 1
                if keep:
                    self._keys[name] = keep
                    self._keys.move_to_end(name)
                    if cids is None:
                        self._index_add_locked(name)
                elif cids is not None:
                    self._index_del_locked(name)
                # BCAST: stateless prefix match over the (usually empty)
                # BCAST subset only — not every tracking connection
                for cid in self._bcast_cids:
                    st = self._states.get(cid)
                    if st is None:
                        continue
                    if cid == writer_cid and st.noloop:
                        continue
                    if not st.prefixes or any(
                        name.startswith(p) for p in st.prefixes
                    ):
                        bucket = targets.setdefault(cid, [])
                        if not bucket or bucket[-1] != name:
                            bucket.append(name)
            # write-side registrations count against the same bound
            overflow = self._evict_overflow_locked()
        self._deliver(targets)
        for victim, vcids in overflow:
            self._deliver({vc: [victim] for vc in vcids})

    def note_expired(self, names: List[str]) -> None:
        """TTL reaper / lazy-expiry hook (DeviceStore.on_expired).  An
        expiring hash under a search index's prefixes is ingest-stream
        churn too (sync() prunes the doc), so the index query key
        invalidates exactly like a DEL's would."""
        names = list(names)
        self.note_write(names, None)
        self._note_search_ingest(names)

    def note_objcall_ops(self, ops, writer_ctx=None) -> None:
        """OBJCALLM / OBJCALLMA / TXEXEC frames are keyless on the wire —
        their (factory, name, method, ...) tuples carry the real keys."""
        names = [
            str(op[1]) for op in ops
            if op[1] and C.objcall_is_write(str(op[2]))
        ]
        if names:
            self.note_write(names, writer_ctx)
            self._note_search_ingest(names)

    def invalidate_all(self, writer_ctx=None) -> None:
        """FLUSHALL discipline: one null-payload invalidate per tracking
        connection (the 'everything you cached is gone' frame).  NOLOOP is
        NOT honored here (Redis's rule too): the writer has no way to
        enumerate-and-drop its own cached keys locally, so suppressing the
        flush frame would leave its whole near cache serving deleted data."""
        del writer_ctx  # kept for the post_dispatch call shape
        with self._lock:
            self._keys.clear()
            self._slot_index.clear()
            self._client_keys.clear()
            cids = []
            for cid, st in self._states.items():
                st.nkeys = 0
                cids.append(cid)
        self._deliver({cid: None for cid in cids})

    def invalidate_slot(self, slot: int, epoch: Optional[int] = None,
                        store_names: Optional[List[str]] = None) -> int:
        """Slot-handoff invalidation (migration finalize / RECOVERING
        fence): every tracked key hashing to `slot` invalidates, plus —
        for BCAST listeners — the store's current names in the slot.

        Fence-epoch stamped: a re-issue at the same (or a lower) epoch is a
        journaled coordinator's idempotent resume (or a stale one's late
        write) and emits NOTHING — the fencing that keeps journal replay
        from re-storming every near cache.  Epoch-less calls always emit.
        Recording at the RECOVERING fence deliberately dedupes the resumed
        migration's STABLE finalize at the same epoch: nothing can register
        in between (check_routing answers TRYAGAIN for a RECOVERING slot
        BEFORE pre-dispatch registration), so the fence's own flush already
        covered every registration the finalize would.
        Returns the number of keys invalidated."""
        with self._lock:
            if epoch is not None:
                if epoch <= self._slot_epochs.get(slot, -1):
                    return 0
                self._slot_epochs[slot] = epoch
            if not self._states:
                return 0
            names = list(self._slot_index.get(slot, ()))
        extra = [
            n for n in (store_names or [])
            if n not in names
        ]
        self.stats["slot_flushes"] += 1
        self.note_write(names, None)
        if extra:
            # tracked-table names already covered default-mode clients; the
            # store's remaining names in the slot only matter to BCAST
            # listeners (no per-key memory to consult)
            with self._lock:
                has_bcast = bool(self._bcast_cids)
            if has_bcast:
                self.note_write(extra, None)
        return len(names) + len(extra)

    # -- delivery -------------------------------------------------------------

    def _deliver(self, targets: Dict[int, Optional[List[str]]]) -> None:
        """Send one ``invalidate`` push per target connection — through its
        REDIRECT route when set.  The push rides ``ctx.push`` (the
        per-connection completion queue), so it serializes FIFO with
        pending readback frames and encodes with the TARGET connection's
        negotiated protocol (a RESP2 redirect target gets the ``*2``
        array projection of the same frame — byte-for-byte the proto-2
        encoding of the RESP3 push)."""
        if not targets:
            return
        for cid, names in targets.items():
            with self._lock:
                st = self._states.get(cid)
                route = st.redirect if (st is not None and st.redirect) else cid
                target = self._conns.get(route)
            self._push_to(target, names)

    def _push_to(self, target, names: Optional[List[str]]) -> None:
        push_fn = getattr(target, "push", None) if target is not None else None
        if push_fn is None:
            self.stats["dropped"] += 1
            return
        payload = None if names is None else [n.encode() for n in names]
        try:
            push_fn(Push([b"invalidate", payload]))
            self.stats["pushes"] += 1
            self.stats["keys_invalidated"] += len(names or ())
        except Exception:  # noqa: BLE001 — a dying loop must not fail writes
            self.stats["dropped"] += 1

    # -- introspection --------------------------------------------------------

    def census(self) -> Dict[str, float]:
        """Leak-accounting probe (chaos/census.py): sizes only — monotonic
        counters live in ``stats`` and are exposed as metrics gauges, not
        census rows (a counter that moved is not a leak)."""
        with self._lock:
            return {
                "table_keys": float(len(self._keys)),
                "slot_index_keys": float(
                    sum(len(s) for s in self._slot_index.values())
                ),
                "client_index_keys": float(
                    sum(len(s) for s in self._client_keys.values())
                ),
                "tracking_conns": float(
                    sum(1 for st in self._states.values() if not st.bcast)
                ),
                "bcast_conns": float(
                    sum(1 for st in self._states.values() if st.bcast)
                ),
            }

    def tracked_key_count(self) -> int:
        with self._lock:
            return len(self._keys)
