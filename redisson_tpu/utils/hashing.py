"""Deterministic, versioned, vectorized hashing for sketch index computation.

Role parity: the reference computes sketch indexes *client-side* with
HighwayHash128 (``org/redisson/misc/Hash.java:28-75``,
``org/redisson/misc/HighwayHash.java``) and derives Bloom bit positions as
``(h1 + i*h2) % size`` (``org/redisson/RedissonBloomFilter.java:90-97,139-151``).

TPU-first re-design: instead of a scalar 64-bit hash per key on the host, we
hash *batches* of keys on-device with uint32-lane arithmetic (TPU has no native
64-bit integer path; a pair of independent 32-bit murmur-style hashes gives the
same double-hashing scheme without x64 emulation).  The same code runs under
numpy (host) and jax.numpy (device) — callers pick the namespace.

The scheme is part of the persisted format (bloom bit layouts are only
meaningful under the hash that produced them), so it is versioned:

    HASH_VERSION = 1  — "rtpu-mur32x2/1"
      * int keys: key split into (hi, lo) uint32 words, murmur3-x86-32 chain
        over the two words, seeds SEED1/SEED2; h2 forced odd.
      * byte keys: keys padded to W uint32 little-endian words; words beyond
        ceil(len/4) are masked out of the chain; length xored in finalization.

Any change to the mixing constants or word order MUST bump HASH_VERSION and be
treated as a new on-disk/in-HBM format.
"""
from __future__ import annotations

import numpy as np

HASH_VERSION = 1
HASH_NAME = "rtpu-mur32x2/1"

SEED1 = 0x9747B28C
SEED2 = 0x3C6EF372

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_FM1 = 0x85EBCA6B
_FM2 = 0xC2B2AE35


def _u32(xp, v):
    # np.uint32 scalars carry an explicit dtype, which keeps both numpy and
    # jax (x64 disabled — python ints > 2**31 would overflow weak int32) in
    # pure uint32 modular arithmetic.
    del xp
    return np.uint32(v)


def _rotl32(xp, x, r):
    return (x << r) | (x >> (32 - r))


def fmix32(x, xp=np):
    """Murmur3 finalizer. x: uint32 array."""
    x = x ^ (x >> 16)
    x = x * _u32(xp, _FM1)
    x = x ^ (x >> 13)
    x = x * _u32(xp, _FM2)
    x = x ^ (x >> 16)
    return x


def _mur_round(xp, h, k):
    k = k * _u32(xp, _C1)
    k = _rotl32(xp, k, 15)
    k = k * _u32(xp, _C2)
    h = h ^ k
    h = _rotl32(xp, h, 13)
    h = h * _u32(xp, 5) + _u32(xp, 0xE6546B64)
    return h


def hash_words(words, nbytes, seed, xp=np):
    """Murmur3-x86-32-style hash over uint32 word lanes.

    words: sequence of uint32 arrays (the key, one array per word position,
           all the same shape) — word j is masked out for keys with
           ceil(nbytes/4) <= j.
    nbytes: uint32 array, byte length of each key (0 => only finalization).
    seed: python int.
    Returns uint32 array of hashes.
    """
    h = xp.full_like(words[0], _u32(xp, seed)) if hasattr(words[0], "shape") else _u32(xp, seed)
    nwords = (nbytes + _u32(xp, 3)) >> 2
    for j, w in enumerate(words):
        hj = _mur_round(xp, h, w)
        h = xp.where(nwords > _u32(xp, j), hj, h)
    h = h ^ nbytes
    return fmix32(h, xp)


def hash_u64_pair(lo, hi, xp=np):
    """Hash 64-bit keys given as (lo, hi) uint32 arrays -> (h1, h2) uint32.

    h2 is forced odd so that the double-hashing stride (h1 + i*h2) visits
    distinct residues (same trick as the reference's Guava-style scheme,
    RedissonBloomFilter.java:90-97 keeps h2 as an independent stride).
    """
    eight = _u32(xp, 8)
    h1 = hash_words([lo, hi], xp.full_like(lo, eight), SEED1, xp)
    h2 = hash_words([lo, hi], xp.full_like(lo, eight), SEED2, xp)
    h2 = h2 | _u32(xp, 1)
    return h1, h2


def hash_packed_bytes(words, nbytes, xp=np):
    """Hash variable-length byte keys packed as uint32 word columns.

    words: uint32 array of shape (W, N) — column j holds word j of every key.
    nbytes: uint32 array (N,).
    Returns (h1, h2) uint32 arrays of shape (N,).
    """
    if words.shape[0] == 0:  # empty batch or zero-width packing
        z = xp.zeros(nbytes.shape, xp.uint32)
        return z, z
    cols = [words[j] for j in range(words.shape[0])]
    h1 = hash_words(cols, nbytes, SEED1, xp)
    h2 = hash_words(cols, nbytes, SEED2, xp) | _u32(xp, 1)
    return h1, h2


def pack_keys(keys):
    """Host-side: pack a list of bytes keys into (words[W,N] uint32, nbytes[N]).

    W is ceil(maxlen/4); little-endian word packing, zero padding.
    """
    n = len(keys)
    if n == 0:
        return np.zeros((0, 0), np.uint32), np.zeros((0,), np.uint32)
    maxlen = max(len(k) for k in keys)
    w = max(1, (maxlen + 3) // 4)
    buf = np.zeros((n, w * 4), np.uint8)
    nbytes = np.empty((n,), np.uint32)
    for i, k in enumerate(keys):
        buf[i, : len(k)] = np.frombuffer(k, np.uint8)
        nbytes[i] = len(k)
    words = buf.view("<u4").T.copy()  # (W, N)
    return words, nbytes


def int_keys_to_u32_pair(keys):
    """Host-side: int64/uint64 numpy array -> (lo, hi) uint32 arrays."""
    k = np.asarray(keys).astype(np.uint64)
    lo = (k & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (k >> np.uint64(32)).astype(np.uint32)
    return lo, hi


def bloom_indexes(h1, h2, k, m_bits, xp=np):
    """Double-hashed bit positions: shape (..., k) int32; (h1 + i*h2) % m.

    Mirrors the reference's index derivation (RedissonBloomFilter.java:139-151)
    but on 32-bit lanes; m_bits must be < 2**31.
    """
    i = xp.arange(k, dtype=xp.uint32)
    idx = (h1[..., None] + i * h2[..., None]) % _u32(xp, m_bits)
    return idx.astype(xp.int32)
