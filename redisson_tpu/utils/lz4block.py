"""LZ4 *block* format (compress + decompress): native fast path + pure
Python fallback.

Parity: the reference ships LZ4Codec (codec/LZ4Codec.java, backed by
lz4-java's JNI block codec) as its recommended compression wrapper.  The
same split exists here: ``compress``/``decompress`` dispatch to
``rtpu_lz4_compress``/``rtpu_lz4_decompress`` (native/resp.cpp via the
net/_native loader) when the toolchain is available, and fall back to the
original pure-Python implementation of the published block format (token
nibbles, 255-run extended lengths, little-endian 2-byte match offsets,
literals-only final sequence, the 12/5-byte end-of-block match rules) —
``compress_python``/``decompress_python``, kept public as the documented
fallback and cross-validation reference.  Both implementations are
interoperable with any standard LZ4 block decoder at the byte level, and
with EACH OTHER in both directions (enforced by tests/test_native_wire.py);
``RTPU_NO_NATIVE=1`` forces the fallback.

Fallback throughput is python-speed (~5-20MB/s compress); the native path
runs at memory speed, which is what lets the codec and the replication
full-ship/delta wire (server/replication.py) compress by default.
"""
from __future__ import annotations

import ctypes

_MIN_MATCH = 4
_LAST_LITERALS = 5   # spec: the last 5 bytes are always literals
_MATCH_GUARD = 12    # spec: no match may start within the last 12 bytes
_MAX_OFFSET = 0xFFFF


def _lib():
    from redisson_tpu.net import _native

    return _native.load()


def compress(src: bytes) -> bytes:
    """LZ4 block compress — native when available, else pure Python."""
    lib = _lib()
    if lib is None:
        return compress_python(src)
    src = bytes(src)
    n = len(src)
    cap = n + n // 255 + 16  # LZ4 worst-case expansion bound
    out = ctypes.create_string_buffer(cap)
    w = lib.rtpu_lz4_compress(src, n, out, cap)
    if w < 0:  # oversized input (-3) or bound drift (-1): python handles it
        return compress_python(src)
    return ctypes.string_at(out, w)


def decompress(src: bytes, expected_size: int) -> bytes:
    """LZ4 block decompress; raises ValueError on malformed input or a size
    mismatch — native when available, else pure Python."""
    lib = _lib()
    if lib is None:
        return decompress_python(src, expected_size)
    if expected_size < 0:
        raise ValueError(f"bad LZ4 expected size {expected_size}")
    src = bytes(src)
    out = ctypes.create_string_buffer(max(1, expected_size))
    produced = ctypes.c_uint64(0)
    rc = lib.rtpu_lz4_decompress(
        src, len(src), out, expected_size, ctypes.byref(produced)
    )
    if rc == -1:
        raise ValueError("truncated or malformed LZ4 block")
    if rc != 0:
        raise ValueError(
            f"LZ4 size mismatch: got {produced.value}, expected {expected_size}"
        )
    return ctypes.string_at(out, expected_size)


def compress_python(src: bytes) -> bytes:
    """Pure-python LZ4 block compress (greedy, 4-byte hash chaining)."""
    n = len(src)
    if n == 0:
        return b"\x00"  # one empty-literal token: a valid empty block
    out = bytearray()
    table: dict = {}
    anchor = 0
    i = 0
    limit = n - _MATCH_GUARD
    find = int.from_bytes
    while i < limit:
        seq = find(src[i : i + 4], "little")
        cand = table.get(seq)
        table[seq] = i
        if cand is None or i - cand > _MAX_OFFSET or src[cand : cand + 4] != src[i : i + 4]:
            i += 1
            continue
        # extend the match forward (stop before the guard tail)
        m = i + 4
        c = cand + 4
        end = n - _LAST_LITERALS
        while m < end and src[m] == src[c]:
            m += 1
            c += 1
        lit = src[anchor:i]
        _emit(out, lit, i - cand, m - i)
        anchor = i = m
    # final literals-only sequence
    lit = src[anchor:]
    ll = len(lit)
    if ll >= 15:
        out.append(0xF0)
        _ext(out, ll - 15)
    else:
        out.append(ll << 4)
    out += lit
    return bytes(out)


def _ext(out: bytearray, v: int) -> None:
    while v >= 255:
        out.append(255)
        v -= 255
    out.append(v)


def _emit(out: bytearray, lit: bytes, offset: int, mlen: int) -> None:
    ll = len(lit)
    ml = mlen - _MIN_MATCH
    token = (min(ll, 15) << 4) | min(ml, 15)
    out.append(token)
    if ll >= 15:
        _ext(out, ll - 15)
    out += lit
    out += offset.to_bytes(2, "little")
    if ml >= 15:
        _ext(out, ml - 15)


def decompress_python(src: bytes, expected_size: int) -> bytes:
    """Pure-python LZ4 block decompress; raises ValueError on malformed
    input or a size mismatch (the codec frame carries the uncompressed
    length)."""
    out = bytearray()
    i = 0
    n = len(src)
    try:
        while i < n:
            token = src[i]
            i += 1
            ll = token >> 4
            if ll == 15:
                while True:
                    b = src[i]
                    i += 1
                    ll += b
                    if b != 255:
                        break
            if i + ll > n:
                raise ValueError("truncated literals")
            out += src[i : i + ll]
            i += ll
            if i >= n:
                break  # final sequence has no match part
            offset = int.from_bytes(src[i : i + 2], "little")
            i += 2
            if offset == 0 or offset > len(out):
                raise ValueError(f"bad match offset {offset}")
            ml = token & 0xF
            if ml == 15:
                while True:
                    b = src[i]
                    i += 1
                    ml += b
                    if b != 255:
                        break
            ml += _MIN_MATCH
            start = len(out) - offset
            if offset >= ml:
                out += out[start : start + ml]
            else:
                # overlapping copy (RLE-style): byte-at-a-time semantics
                for k in range(ml):
                    out.append(out[start + k])
    except IndexError:
        raise ValueError("truncated LZ4 block") from None
    if len(out) != expected_size:
        raise ValueError(
            f"LZ4 size mismatch: got {len(out)}, expected {expected_size}"
        )
    return bytes(out)
