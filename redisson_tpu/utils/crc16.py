"""CRC16 (CCITT/XModem) keyspace slot hashing with ``{hashtag}`` colocation.

Parity: ``org/redisson/connection/CRC16.java`` (the 256-entry table algorithm)
and ``MasterSlaveConnectionManager.calcSlot`` hashtag extraction.  The 16384
CRC16 slot model is kept verbatim so routing semantics (which keys may be
combined in one atomic compound op) match the reference; slots map to mesh
shards instead of Redis masters (SURVEY.md §2.8).
"""
from __future__ import annotations

import numpy as np

MAX_SLOT = 16384

_POLY = 0x1021
_TABLE = np.zeros(256, np.uint16)
for _i in range(256):
    _crc = _i << 8
    for _ in range(8):
        _crc = ((_crc << 1) ^ _POLY) if (_crc & 0x8000) else (_crc << 1)
        _crc &= 0xFFFF
    _TABLE[_i] = _crc


def crc16(data: bytes) -> int:
    crc = 0
    t = _TABLE
    for b in data:
        crc = ((crc << 8) & 0xFFFF) ^ int(t[((crc >> 8) ^ b) & 0xFF])
    return crc


def hashtag(key: bytes) -> bytes:
    """Extract the {hashtag} portion if present and non-empty (Redis rules)."""
    start = key.find(b"{")
    if start >= 0:
        end = key.find(b"}", start + 1)
        if end > start + 1:
            return key[start + 1 : end]
    return key


def calc_slot(key) -> int:
    if isinstance(key, str):
        key = key.encode()
    return crc16(hashtag(key)) % MAX_SLOT
