"""HashedWheelTimer: one shared timer thread for all scheduled timeouts.

Parity target: the reference runs every lock-watchdog renewal, retry timeout
and ping schedule on ONE Netty ``HashedWheelTimer`` owned by
``connection/ServiceManager.java`` — never a thread per timeout.  Round 1
spawned a ``threading.Timer`` chain per held lock (10k locks = 10k timer
threads); this replaces that with the reference's design: a wheel of buckets,
one daemon thread ticking over them, O(1) schedule and cancel.

Precision is bounded by the tick (default 100ms) — fine for watchdog renewals
(10s cadence) and lease expiries; anything needing sub-tick precision should
not ride a wheel timer in the reference either.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class Timeout:
    """Handle for one scheduled task (io.netty.util.Timeout analog)."""

    __slots__ = ("fn", "deadline", "_state", "_lock")

    _PENDING, _CANCELLED, _EXPIRED = 0, 1, 2

    def __init__(self, fn: Callable[[], None], deadline: float):
        self.fn = fn
        self.deadline = deadline
        self._state = self._PENDING
        self._lock = threading.Lock()

    def cancel(self) -> bool:
        """O(1): mark dead; the wheel skips cancelled entries at expiry."""
        with self._lock:
            if self._state != self._PENDING:
                return False
            self._state = self._CANCELLED
            return True

    def is_cancelled(self) -> bool:
        return self._state == self._CANCELLED

    def is_expired(self) -> bool:
        return self._state == self._EXPIRED

    def _try_expire(self) -> bool:
        with self._lock:
            if self._state != self._PENDING:
                return False
            self._state = self._EXPIRED
            return True


class HashedWheelTimer:
    """512-bucket wheel, 100ms tick (Netty's defaults are 512 / 100ms too)."""

    def __init__(self, tick: float = 0.1, wheel_size: int = 512):
        self.tick = tick
        self.wheel_size = wheel_size
        self._wheel: List[List[Timeout]] = [[] for _ in range(wheel_size)]
        self._cursor = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.pending = 0  # observability: live (uncancelled, unexpired) count

    def new_timeout(self, fn: Callable[[], None], delay: float) -> Timeout:
        """Schedule fn to run once after `delay` seconds (worst-case one tick
        late).  fn runs ON the wheel thread: it must be short and non-blocking
        — heavy work should hop to an executor, as in the reference."""
        t = Timeout(fn, time.monotonic() + max(0.0, delay))
        # ceil: a timeout must never fire EARLY (an early lease expiry would
        # release a lock before its lease elapsed -> two holders)
        ticks = max(1, -int(-max(0.0, delay) // self.tick))
        with self._lock:
            if self._stop.is_set():
                raise RuntimeError("timer is stopped")
            slot = (self._cursor + ticks) % self.wheel_size
            self._wheel[slot].append(t)
            self.pending += 1
            self._ensure_thread()
        return t

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="rtpu-wheel-timer", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        next_tick = time.monotonic() + self.tick
        while not self._stop.wait(max(0.0, next_tick - time.monotonic())):
            next_tick += self.tick
            with self._lock:
                self._cursor = (self._cursor + 1) % self.wheel_size
                bucket = self._wheel[self._cursor]
                self._wheel[self._cursor] = []
                due = []
                now = time.monotonic()
                for t in bucket:
                    if t.is_cancelled():
                        self.pending -= 1
                    elif t.deadline > now:
                        # not due yet: the cursor arrived early (mid-tick
                        # scheduling skew) or a wheel revolution remains.
                        # Re-place by REMAINING time — parking it in this
                        # bucket again would delay it a full revolution, and
                        # firing now would violate the never-early invariant.
                        rem = max(1, -int(-(t.deadline - now) // self.tick))
                        slot = (self._cursor + rem) % self.wheel_size
                        self._wheel[slot].append(t)
                    else:
                        due.append(t)
            for t in due:
                if t._try_expire():
                    with self._lock:
                        self.pending -= 1
                    try:
                        t.fn()
                    except Exception:  # noqa: BLE001 — a task must not kill the wheel
                        pass
                else:
                    with self._lock:
                        self.pending -= 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=2.0)
        with self._lock:
            for bucket in self._wheel:
                bucket.clear()
            self.pending = 0
