"""Shared durability primitives for the persistence planes.

Both crash-safety substrates — checkpoint snapshots (``core/checkpoint``)
and migration journals (``server/migration_journal``) — need the same
POSIX discipline: after ``os.replace``/file creation, the RENAME ITSELF
lives in the parent directory's data blocks, so only an fsync of the
directory makes it durable across power loss.
"""
from __future__ import annotations

import os


def fsync_dir(dirpath: str) -> None:
    """fsync a directory so a just-completed rename/creation is durable."""
    fd = os.open(dirpath or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
