"""Metrics registry + command-hook SPI (observability, SURVEY.md §5.1/§5.5).

Reference parity: OSS Redisson exposes no metrics registry (PRO feature);
what exists is the `NettyHook` SPI (``client/NettyHook.java``, wired at
``RedisClient.java:141``) as the sanctioned instrumentation point, plus
micrometer binders for Spring caches.  Here observability is first-class:

  * `MetricsRegistry` — counters, gauges, timers with streaming quantile
    snapshots; renders Prometheus text exposition (`prometheus_text`).
  * `CommandHook` — the NettyHook analog one layer up (exactly where the
    BASELINE north star's "CommandExecutor plugin" sits): on_start/on_end
    around every dispatched command, server- or client-side.

Zero deps: quantiles come from a bounded reservoir (ring buffer), good
enough for p50/p99 dashboards without a HDR histogram dependency.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("fn",)

    def __init__(self, fn: Callable[[], float]):
        self.fn = fn


class Timer:
    """Latency reservoir: bounded ring of recent samples + total counters."""

    __slots__ = ("count", "total_s", "_ring", "_idx", "_lock", "_size")

    def __init__(self, reservoir: int = 2048):
        self.count = 0
        self.total_s = 0.0
        self._ring = np.zeros(reservoir, np.float64)
        self._idx = 0
        self._size = reservoir
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self._ring[self._idx % self._size] = seconds
            self._idx += 1

    def quantiles(self, qs=(50, 99)) -> Dict[int, float]:
        with self._lock:
            n = min(self._idx, self._size)
            if n == 0:
                return {q: 0.0 for q in qs}
            samples = self._ring[:n].copy()
        return {q: float(np.percentile(samples, q)) for q in qs}


class MetricsRegistry:
    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._multi_gauges: Dict[str, Callable[[], Dict[str, float]]] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = Gauge(fn)

    def multi_gauge(self, key: str, fn: Callable[[], Dict[str, float]]) -> None:
        """Register a LABELED gauge family: `fn` returns {row_name: value}
        and every row lands in the snapshot verbatim.  This is how dynamic
        label sets (per-device HBM-ledger rows, ISSUE 15) ride the scrape —
        rows appear/disappear with the resource, so a torn-down shard's
        row vanishes instead of sticking at its last value."""
        with self._lock:
            self._multi_gauges[key] = fn

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            multi = dict(self._multi_gauges)
            timers = dict(self._timers)
        for name, c in counters.items():
            out[name] = c.value
        for name, g in gauges.items():
            try:
                out[name] = float(g.fn())
            except Exception:  # noqa: BLE001 — a broken gauge must not kill scrape
                continue
        for _key, fn in multi.items():
            try:
                for name, v in fn().items():
                    out[name] = float(v)
            except Exception:  # noqa: BLE001 — same scrape-safety contract
                continue
        for name, t in timers.items():
            out[f"{name}_count"] = t.count
            out[f"{name}_total_seconds"] = t.total_s
            for q, v in t.quantiles().items():
                out[f"{name}_p{q}_seconds"] = v
        return out

    def prometheus_text(self, prefix: str = "rtpu") -> str:
        lines: List[str] = []
        for name, value in sorted(self.snapshot().items()):
            metric = f"{prefix}_{name}".replace(".", "_").replace("-", "_")
            lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"


def merge_prometheus_texts(texts: Dict[str, str]) -> str:
    """Fleet-wide scrape merge (ISSUE 12): stamp each node's Prometheus
    exposition with a ``node="host:port"`` label and concatenate — one pane
    of glass for a multi-process cluster (``ClusterSupervisor.scrape()``
    and the ``METRICS CLUSTER`` verb both ride this, so the two scrape
    paths cannot diverge).  Lines that already carry a label set keep it
    (the node label is appended); malformed lines are dropped."""
    out: List[str] = []
    for node in sorted(texts):
        for line in texts[node].splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, value = line.rpartition(" ")
            if not name or not value:
                continue
            if name.endswith("}"):
                name = f'{name[:-1]},node="{node}"}}'
            else:
                name = f'{name}{{node="{node}"}}'
            out.append(f"{name} {value}")
    return "\n".join(out) + "\n"


class CommandHook:
    """SPI: subclass and override; attach via Engine.config or server/client
    hook lists (the NettyHook analog)."""

    def on_start(self, command: str, args) -> Optional[object]:
        """Called before dispatch; the return value is passed to on_end."""
        return None

    def on_end(self, command: str, token, error: Optional[BaseException]) -> None:
        """Called after the reply (error is the raised exception, if any)."""


class MetricsHook(CommandHook):
    """Default hook: per-command counters + latency timers into a registry."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry

    def on_start(self, command: str, args):
        return (command, time.perf_counter())

    def on_end(self, command: str, token, error):
        cmd, t0 = token
        self.registry.timer(f"command.{cmd.lower()}").record(time.perf_counter() - t0)
        self.registry.counter("commands.total").inc()
        if error is not None:
            self.registry.counter("commands.errors").inc()


def run_hooks_start(hooks, command: str, args) -> List[Tuple[CommandHook, object]]:
    tokens = []
    for h in hooks:
        try:
            tokens.append((h, h.on_start(command, args)))
        except Exception:  # noqa: BLE001 — instrumentation must not break dispatch
            continue
    return tokens

def run_hooks_end(tokens, command: str, error: Optional[BaseException]) -> None:
    for h, token in tokens:
        try:
            h.on_end(command, token, error)
        except Exception:  # noqa: BLE001
            continue
