"""Host drivers: WHERE a supervised ``tpu-server`` node runs (ISSUE 16).

Every fleet this repo ever killed, rolled, promoted, or resharded ran its
nodes as subprocesses of ONE operating system.  The fleet-lifecycle
machinery (``ClusterSupervisor``, journaled migration, promotion, rolling
restart) was deliberately written against an abstract node — spawn it,
learn its READY line, signal it, reap its exit code — so breaking the
single-machine wall is an *extraction*, not a rewrite: this module names
that abstract node :class:`NodeHandle` and the thing that makes one a
:class:`HostDriver`.

  * :class:`LocalHostDriver` — today's subprocess path, byte-for-byte:
    ``python -m redisson_tpu.server`` children with an inherited ready-fd
    pipe, per-node log files, signals via ``os.kill``.  Host labels are
    *logical* failure domains (anti-affinity placement and
    ``kill_host`` still mean something on one box — that is how the host
    soak runs in CI).
  * :class:`SshHostDriver` — spawns the node on a REMOTE host over ssh.
    The ready-line protocol rides the ssh channel (remote fd 3 is the
    channel's stdout, the server's own stdout/stderr are redirected to a
    remote log), signals are delivered as remote ``kill`` commands against
    the pid the READY line reported.  The transport is pluggable
    (:class:`SshTransport` for a real sshd, :class:`LoopbackTransport` to
    run the identical command pipeline through ``/bin/sh`` on this
    machine), so the whole codepath — remote spawn, ready-over-channel,
    signal-by-command, exit propagation — is CI-testable with no sshd.
  * :class:`K8sDriver` — pure codegen: emits one deterministic pod spec
    (JSON, ``kubectl apply``-able) per node, with host anti-affinity
    expressed as ``podAntiAffinity`` on master/replica labels and TLS
    certs mounted from a named secret.  It supervises nothing; it exists
    so a fleet plan renders to manifests that are golden-file tested.

Shared-filesystem note: ``SshHostDriver`` assumes the checkout, checkpoint
directories, and (when TLS is armed) the cert files are visible on the
remote host at the same paths — true for loopback CI and NFS-backed pods;
shipping artifacts to genuinely disjoint filesystems is named in the
README as what remains.
"""
from __future__ import annotations

import json
import os
import shlex
import signal as _signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def _default_repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


class NodeHandle:
    """One spawned node, however it runs: readiness fd, liveness, signals.

    The supervisor only ever talks to this interface — ``NodeProc`` holds
    one and ``ClusterSupervisor`` never touches a ``Popen`` directly, so
    the same kill/restart/promotion code drives local children and ssh'd
    remotes."""

    #: address clients should connect to; None = trust the READY line's
    #: host field (the local-subprocess convention)
    connect_host: Optional[str] = None

    def ready_fd(self) -> Optional[int]:
        """Readable fd the READY line will arrive on (None once closed)."""
        raise NotImplementedError

    def close_ready(self) -> None:
        raise NotImplementedError

    def note_ready(self, host: str, port: int, pid: int) -> None:
        """The parsed READY line — remote handles learn their signal
        target (the REMOTE pid) here."""

    @property
    def pid(self) -> Optional[int]:
        raise NotImplementedError

    def poll(self) -> Optional[int]:
        """Exit code if the node is dead, else None."""
        raise NotImplementedError

    def wait(self, timeout: float) -> Optional[int]:
        """Bounded wait; None on timeout (never raises TimeoutExpired)."""
        raise NotImplementedError

    def signal(self, sig: int) -> None:
        raise NotImplementedError

    def force_kill(self) -> None:
        """SIGKILL-equivalent, the escalation terminus."""
        raise NotImplementedError

    def release(self) -> None:
        """Close every resource this handle holds (fds, channels).  Safe
        to call twice; does not touch the process."""
        raise NotImplementedError


class HostDriver:
    """Spawns nodes on hosts.  One driver serves a whole supervisor."""

    name = "abstract"

    def spawn(self, node_name: str, host: str, args: Sequence[str],
              log_path: str, env: Dict[str, str],
              ensure_dirs: Sequence[str] = ()) -> NodeHandle:
        """Start ``tpu-server`` with ``args`` (the full CLI *except*
        ``--ready-fd``, which the driver owns) on ``host``; stdout/stderr
        go to ``log_path``; ``env`` entries are applied ON TOP of the
        host's inherited environment."""
        raise NotImplementedError

    def is_remote(self, host: str) -> bool:
        """True when nodes on ``host`` are reached over a network hop —
        the supervisor arms TLS-by-default for fleets with any remote
        host (plaintext only for loopback)."""
        return False

    def connect_address(self, host: str) -> Optional[str]:
        """Address clients use for nodes on ``host`` (None = whatever the
        READY line says, i.e. the node's own bind host)."""
        return None

    def bind_host(self, host: str) -> Optional[str]:
        """Listener bind address for nodes on ``host`` (None = the
        supervisor's per-node default, 127.0.0.1)."""
        return None

    def on_start_failure(self) -> None:
        """Called (before the raise) when a supervisor ``start()`` dies
        half-way: release driver-held resources the per-node reap cannot
        see — open channels, emitted specs (PR 6 half-started-fleet
        discipline, extended to remote resources)."""

    def close(self) -> None:
        """Terminal cleanup; every handle this driver spawned is already
        released by the supervisor's reap path."""


# -- local subprocesses (the PR 6 path, extracted verbatim) -------------------

class LocalNodeHandle(NodeHandle):
    def __init__(self, proc: subprocess.Popen, ready_rfd: int):
        self.proc = proc
        self._ready_rfd: Optional[int] = ready_rfd

    def ready_fd(self) -> Optional[int]:
        return self._ready_rfd

    def close_ready(self) -> None:
        if self._ready_rfd is not None:
            try:
                os.close(self._ready_rfd)
            except OSError:
                pass
            self._ready_rfd = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: float) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def signal(self, sig: int) -> None:
        try:
            os.kill(self.proc.pid, sig)
        except ProcessLookupError:
            pass

    def force_kill(self) -> None:
        self.proc.kill()

    def release(self) -> None:
        self.close_ready()


class LocalHostDriver(HostDriver):
    """Today's supervisor spawn path, behavior-preserving: a child
    ``python -m redisson_tpu.server`` with the ready-pipe write end
    inherited, appended-to log file, its own session (signals hit THIS
    pid only), and the repo root prepended to the child's PYTHONPATH.
    Host labels are logical failure domains only — everything runs on
    this OS."""

    name = "local"

    def __init__(self, repo_root: Optional[str] = None):
        self.repo_root = repo_root or _default_repo_root()

    def spawn(self, node_name: str, host: str, args: Sequence[str],
              log_path: str, env: Dict[str, str],
              ensure_dirs: Sequence[str] = ()) -> NodeHandle:
        for d in ensure_dirs:
            os.makedirs(d, exist_ok=True)
        child_env = dict(os.environ)
        child_env["PYTHONPATH"] = self.repo_root + (
            os.pathsep + child_env["PYTHONPATH"]
            if child_env.get("PYTHONPATH") else ""
        )
        child_env.update(env)
        rfd, wfd = os.pipe()
        try:
            cmd = [sys.executable, "-m", "redisson_tpu.server",
                   *args, "--ready-fd", str(wfd)]
            with open(log_path, "ab") as log:
                proc = subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT,
                    pass_fds=(wfd,), env=child_env,
                    start_new_session=True,  # our signals hit THIS pid only
                )
        except BaseException:
            # spawn failed before the child owned the pipe: close both ends
            # here or repeated failed restarts leak fds until EMFILE
            for fd in (rfd, wfd):
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise
        os.close(wfd)  # child holds the write end now
        return LocalNodeHandle(proc, rfd)


# -- ssh-spawned remotes ------------------------------------------------------

class SshTransport:
    """Run remote commands through a real ssh client (BatchMode: no
    interactive auth — CI keys or agent only)."""

    def argv(self, host: str, remote_cmd: str) -> List[str]:
        return ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=accept-new",
                host, remote_cmd]


class LoopbackTransport:
    """The command-transport fake: 'remote' commands run through
    ``/bin/sh -c`` on this machine, so the ENTIRE ssh codepath — spawn
    pipeline, ready-over-channel-stdout, signal-by-remote-kill, exit
    propagation — exercises in CI with no sshd.  The host label is
    ignored (everything is this box)."""

    def argv(self, host: str, remote_cmd: str) -> List[str]:
        return ["/bin/sh", "-c", remote_cmd]


class SshNodeHandle(NodeHandle):
    """A node reached through a command transport: the local child is the
    ssh client (or ``/bin/sh`` for the loopback fake), the node is the
    REMOTE process the READY line names.  Liveness tracks the transport
    child — ssh exits when the remote command does, propagating its exit
    status (128+signal becomes the Popen-style negative signal number so
    kill assertions read the same as local handles)."""

    def __init__(self, driver: "SshHostDriver", host: str,
                 proc: subprocess.Popen, connect_host: str):
        self.driver = driver
        self.host = host
        self.proc = proc
        self.connect_host = connect_host
        self.remote_pid: Optional[int] = None
        self._ready_closed = False

    def ready_fd(self) -> Optional[int]:
        if self._ready_closed or self.proc.stdout is None:
            return None
        return self.proc.stdout.fileno()

    def close_ready(self) -> None:
        if not self._ready_closed and self.proc.stdout is not None:
            try:
                self.proc.stdout.close()
            except OSError:
                pass
            self._ready_closed = True

    def note_ready(self, host: str, port: int, pid: int) -> None:
        self.remote_pid = pid

    @property
    def pid(self) -> Optional[int]:
        # the NODE's identity is the remote pid; before READY it is unknown
        return self.remote_pid if self.remote_pid is not None else self.proc.pid

    @staticmethod
    def _map_rc(rc: Optional[int]) -> Optional[int]:
        # a remote command killed by signal N surfaces as exit 128+N
        # through a real sshd; normalize to the Popen convention (-N) so
        # `rc == -SIGKILL` assertions hold on both transports
        if rc is not None and rc > 128 and rc <= 128 + 64:
            return -(rc - 128)
        return rc

    def poll(self) -> Optional[int]:
        return self._map_rc(self.proc.poll())

    def wait(self, timeout: float) -> Optional[int]:
        try:
            return self._map_rc(self.proc.wait(timeout=timeout))
        except subprocess.TimeoutExpired:
            return None

    def signal(self, sig: int) -> None:
        if self.remote_pid is None:
            return  # never became ready: nothing addressable to signal
        self.driver._run_remote(
            self.host, f"kill -{int(sig)} {self.remote_pid}"
        )

    def force_kill(self) -> None:
        if self.remote_pid is not None:
            self.driver._run_remote(
                self.host, f"kill -{int(_signal.SIGKILL)} {self.remote_pid}"
            )
            if self.wait(5.0) is not None:
                return
        # channel wedged or node never ready: last resort is the local
        # transport child (a real remote may orphan; the reap stays bounded)
        try:
            self.proc.kill()
        except OSError:
            pass

    def release(self) -> None:
        self.close_ready()
        if self.proc.stderr is not None:
            try:
                self.proc.stderr.close()
            except OSError:
                pass
        self.driver._forget(self)


class SshHostDriver(HostDriver):
    """Spawn ``tpu-server`` on remote hosts over a command transport.

    The remote pipeline (one ``sh`` line, see :meth:`_remote_script`):
    duplicate the channel's stdout onto fd 3, redirect the server's own
    stdout/stderr into a remote log file, then ``exec`` the server with
    ``--ready-fd 3`` — so the READY line is the ONLY thing that ever
    travels the channel's stdout and the protocol the supervisor reads is
    byte-identical to the local pipe's."""

    name = "ssh"

    def __init__(self, transport=None, python: Optional[str] = None,
                 repo_root: Optional[str] = None, bind_host: str = "0.0.0.0",
                 connect_addresses: Optional[Dict[str, str]] = None):
        self.transport = transport or SshTransport()
        self.python = python or sys.executable
        self.repo_root = repo_root or _default_repo_root()
        self._bind_host = bind_host
        self._connect = dict(connect_addresses or {})
        self._handles: List[SshNodeHandle] = []
        self._lock = threading.Lock()

    # loopback labels stay plaintext-eligible even through this driver
    def is_remote(self, host: str) -> bool:
        return host not in _LOOPBACK

    def connect_address(self, host: str) -> Optional[str]:
        if host in self._connect:
            return self._connect[host]
        # the loopback fake runs every "remote" node on this box: whatever
        # the host label says, the node is reachable only at 127.0.0.1
        if isinstance(self.transport, LoopbackTransport):
            return "127.0.0.1"
        return host

    def bind_host(self, host: str) -> Optional[str]:
        return self._bind_host

    def _remote_script(self, args: Sequence[str], log_path: str,
                       env: Dict[str, str],
                       ensure_dirs: Sequence[str]) -> str:
        mkdirs = " ".join(
            shlex.quote(d)
            for d in [*ensure_dirs, os.path.dirname(log_path) or "."]
        )
        envs = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in sorted({
                "PYTHONPATH": self.repo_root, **env,
            }.items())
        )
        argv = " ".join(shlex.quote(str(a)) for a in args)
        return (
            f"mkdir -p {mkdirs} && "
            # fd 3 = the channel's stdout (READY only); server output -> log
            f"exec 3>&1 && exec >>{shlex.quote(log_path)} 2>&1 && "
            f"exec env {envs} {shlex.quote(self.python)} "
            f"-m redisson_tpu.server {argv} --ready-fd 3"
        )

    def spawn(self, node_name: str, host: str, args: Sequence[str],
              log_path: str, env: Dict[str, str],
              ensure_dirs: Sequence[str] = ()) -> NodeHandle:
        script = self._remote_script(args, log_path, env, ensure_dirs)
        proc = subprocess.Popen(
            self.transport.argv(host, script),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL, start_new_session=True,
        )
        handle = SshNodeHandle(self, host, proc, self.connect_address(host))
        with self._lock:
            self._handles.append(handle)
        return handle

    def _run_remote(self, host: str, cmd: str) -> int:
        try:
            return subprocess.run(
                self.transport.argv(host, cmd),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                stdin=subprocess.DEVNULL, timeout=15.0, check=False,
            ).returncode
        except (OSError, subprocess.TimeoutExpired):
            return -1

    def _forget(self, handle: "SshNodeHandle") -> None:
        with self._lock:
            if handle in self._handles:
                self._handles.remove(handle)

    def on_start_failure(self) -> None:
        self.close()

    def close(self) -> None:
        """Release every channel still held (half-started fleets included:
        the supervisor's failure path lands here via on_start_failure)."""
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            h.close_ready()
            if h.proc.stderr is not None:
                try:
                    h.proc.stderr.close()
                except OSError:
                    pass
        with self._lock:
            self._handles.clear()


# -- kubernetes pod-spec emission (pure codegen) ------------------------------

class K8sDriver(HostDriver):
    """Render a fleet plan to Kubernetes pod specs — deterministic JSON
    (``kubectl apply -f`` accepts JSON), one pod per node, golden-file
    tested.  This driver supervises nothing: :meth:`spawn` refuses loudly.

    The failure-domain story maps onto the scheduler instead of the
    supervisor: every pod carries ``rtpu/role`` + ``rtpu/master`` labels
    and each replica pod a REQUIRED ``podAntiAffinity`` against its
    master's pod on ``kubernetes.io/hostname`` — the same invariant
    :func:`redisson_tpu.cluster.topology.assign_hosts` enforces for
    driver-placed fleets, expressed in the dialect k8s enforces natively."""

    name = "k8s"

    def __init__(self, image: str = "redisson-tpu:latest",
                 namespace: str = "default", app: str = "rtpu",
                 tls_secret: Optional[str] = None):
        self.image = image
        self.namespace = namespace
        self.app = app
        self.tls_secret = tls_secret
        self._emitted: List[str] = []

    def spawn(self, node_name, host, args, log_path, env, ensure_dirs=()):
        raise NotImplementedError(
            "K8sDriver is codegen-only: emit() pod specs and apply them; "
            "the kubelet is the process supervisor"
        )

    def pod_spec(self, name: str, role: str, port: int,
                 args: Sequence[str] = (),
                 master: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None) -> dict:
        labels = {"app": self.app, "rtpu/role": role, "rtpu/node": name}
        if master is not None:
            labels["rtpu/master"] = master
        container = {
            "name": "tpu-server",
            "image": self.image,
            "args": ["--host", "0.0.0.0", "--port", str(port), *map(str, args)],
            "ports": [{"containerPort": port, "name": "resp"}],
            # the READY-line analog: routable only once the listener binds
            "readinessProbe": {
                "tcpSocket": {"port": port},
                "periodSeconds": 1,
                "failureThreshold": 60,
            },
            "volumeMounts": [
                {"name": "ckpt", "mountPath": "/var/lib/rtpu/ckpt"},
            ],
        }
        if env:
            container["env"] = [
                {"name": k, "value": v} for k, v in sorted(env.items())
            ]
        volumes: List[dict] = [{"name": "ckpt", "emptyDir": {}}]
        if self.tls_secret:
            container["volumeMounts"].append(
                {"name": "tls", "mountPath": "/var/lib/rtpu/tls",
                 "readOnly": True}
            )
            container["args"] += [
                "--tls-cert", "/var/lib/rtpu/tls/tls.crt",
                "--tls-key", "/var/lib/rtpu/tls/tls.key",
            ]
            volumes.append(
                {"name": "tls", "secret": {"secretName": self.tls_secret}}
            )
        spec: dict = {"containers": [container], "volumes": volumes}
        if role == "replica" and master is not None:
            # host anti-affinity, REQUIRED: a replica pod never schedules
            # onto its master's kubelet host (assign_hosts' invariant in
            # the scheduler's own dialect)
            spec["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {
                            "app": self.app, "rtpu/node": master,
                        }},
                        "topologyKey": "kubernetes.io/hostname",
                    }],
                },
            }
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{self.app}-{name}",
                "namespace": self.namespace,
                "labels": labels,
            },
            "spec": spec,
        }

    def manifest(self, plan: Sequence[dict]) -> str:
        """One deterministic ``v1/List`` document for a whole fleet plan
        (rows: ``{"name", "role", "port", "args"?, "master"?, "env"?}``).
        Byte-stable for identical plans — the golden-file contract."""
        items = [
            self.pod_spec(
                row["name"], row["role"], int(row["port"]),
                args=row.get("args", ()), master=row.get("master"),
                env=row.get("env"),
            )
            for row in plan
        ]
        doc = {"apiVersion": "v1", "kind": "List", "items": items}
        return json.dumps(doc, indent=2, sort_keys=True) + "\n"

    def emit(self, plan: Sequence[dict], out_dir: str) -> List[str]:
        """Write one ``<app>-<name>.json`` per node; returns the paths.
        Emitted paths are tracked so a half-started orchestration can
        :meth:`discard` them (the boot-failure cleanup discipline)."""
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for row in plan:
            spec = self.pod_spec(
                row["name"], row["role"], int(row["port"]),
                args=row.get("args", ()), master=row.get("master"),
                env=row.get("env"),
            )
            path = os.path.join(out_dir, f"{self.app}-{row['name']}.json")
            with open(path, "w") as f:
                json.dump(spec, f, indent=2, sort_keys=True)
                f.write("\n")
            paths.append(path)
            self._emitted.append(path)
        return paths

    def discard(self) -> List[str]:
        """Remove every spec this driver emitted (partial-start cleanup);
        returns what was removed."""
        removed = []
        for path in self._emitted:
            try:
                os.unlink(path)
                removed.append(path)
            except OSError:
                pass
        self._emitted.clear()
        return removed

    def on_start_failure(self) -> None:
        self.discard()
