"""Fleet-wide tenant budget control loop (ISSUE 18).

Per-node ``WindowScheduler`` token buckets make a tenant's budget a PER-NODE
number: a tenant spraying all N masters of a fleet harvests N times the rate
an operator configured.  This module closes that hole with a CONTROL LOOP,
not consensus: a ``QosRebalancer`` periodically scrapes every node's
``CLUSTER QOS`` tenant table, measures each tenant's per-node demand (the
delta of the table's cumulative ``admitted + shed`` op counters between
sweeps — what the tenant ASKED for, not what it was granted, so a starved
node still attracts budget), and re-splits the tenant's GLOBAL rate across
nodes proportional to that demand.  The actuator is the new ``CLUSTER QOS
REBALANCE <tenant> <rate> [<burst>]`` admin verb, which lands on
``WindowScheduler.set_tenant_rate`` — the same per-tenant override hook the
tests use.

Control-loop discipline:

  * every node always keeps a minimum share (``min_share``) of the global
    rate, so a tenant going quiet on one node can always ramp back up there
    and be SEEN by the next demand measurement (a zero split would be a
    ratchet: no admitted ops -> no demand -> no budget, forever);
  * an unreachable node contributes no demand and receives no push that
    sweep — its last-pushed split keeps working locally (budgets degrade to
    the per-node behavior, never to zero);
  * the first sweep only baselines the counters; pushes start on the
    second, once a demand delta exists.

The loop runs the same way over a ``ClusterSupervisor`` fleet
(``supervisor.start_qos_rebalance``) or any driver-spawned fleet addressed
by host:port (``tools/qos_rebalance.py``).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["QosRebalancer", "parse_tenant_table", "parse_tenant_weights",
           "split_rate"]


def parse_tenant_table(reply) -> Dict[str, Tuple[int, int]]:
    """``CLUSTER QOS`` reply -> {tenant: (admitted_ops, shed_ops)}.

    Tolerates the reply growing rows (class rows, STREAM rows) — only
    ``[b"TENANT", name, level, admitted, shed_ops, shed_frames]`` rows are
    read."""
    out: Dict[str, Tuple[int, int]] = {}
    for row in reply[3:] if isinstance(reply, (list, tuple)) else ():
        if not isinstance(row, (list, tuple)) or len(row) < 6:
            continue
        tag = row[0]
        if tag not in (b"TENANT", "TENANT"):
            continue
        name = row[1]
        if isinstance(name, (bytes, bytearray)):
            name = bytes(name).decode(errors="replace")
        out[str(name)] = (int(row[3]), int(row[4]))
    return out


def parse_tenant_weights(reply) -> Dict[str, float]:
    """``CLUSTER QOS`` reply -> {tenant: weight} for TENANT rows that carry
    the trailing weight element (ISSUE 19 satellite).  Pre-weight nodes
    (6-element rows) simply contribute nothing — callers default to 1.0."""
    out: Dict[str, float] = {}
    for row in reply[3:] if isinstance(reply, (list, tuple)) else ():
        if not isinstance(row, (list, tuple)) or len(row) < 7:
            continue
        if row[0] not in (b"TENANT", "TENANT"):
            continue
        name = row[1]
        if isinstance(name, (bytes, bytearray)):
            name = bytes(name).decode(errors="replace")
        try:
            out[str(name)] = float(row[6])
        except (TypeError, ValueError):
            continue
    return out


def split_rate(global_rate: float, demand: Dict[str, float],
               min_share: float = 0.05,
               weight: float = 1.0) -> Dict[str, float]:
    """Split one tenant's global rate across nodes proportional to demand,
    with every node floored at ``min_share`` of an even split (see module
    docstring for why the floor exists).  ``weight`` is the tenant's
    service-class multiplier (gold=2.0/silver=1.0; ISSUE 19 satellite):
    the tenant's effective global budget is ``global_rate * weight``, so a
    weight of 1.0 reproduces unweighted behavior exactly.  Shares are
    normalized so the splits always sum to that effective budget — the
    fleet-wide (weighted) budget is the invariant the loop defends."""
    if not demand:
        return {}
    budget = global_rate * max(0.0, weight)
    n = len(demand)
    floor = min_share / n
    total = sum(max(0.0, d) for d in demand.values())
    if total <= 0.0:
        return {node: budget / n for node in demand}
    shares = {
        node: max(floor, max(0.0, d) / total) for node, d in demand.items()
    }
    norm = sum(shares.values())
    return {node: budget * s / norm for node, s in shares.items()}


class QosRebalancer:
    """The control loop: scrape -> measure demand -> split -> push.

    ``conn_factories`` maps a node label (host:port) to a zero-arg callable
    returning a context-managed connection whose ``execute(*args)`` speaks
    RESP — ``ClusterSupervisor.conn`` wrapped per node, or a raw
    ``net.connection.Connection`` for standalone fleets."""

    def __init__(self, conn_factories: Dict[str, Callable],
                 global_rate: float, *, global_burst: Optional[float] = None,
                 interval: float = 1.0, min_share: float = 0.05,
                 tenant_weights: Optional[Dict[str, float]] = None):
        if global_rate <= 0:
            raise ValueError("global_rate must be positive")
        self.conn_factories = dict(conn_factories)
        self.global_rate = float(global_rate)
        self.global_burst = global_burst
        self.interval = float(interval)
        self.min_share = float(min_share)
        # service-class weights (ISSUE 19 satellite): configured weights are
        # authoritative and are PUSHED to the fleet with each rebalance
        # (CLUSTER QOS REBALANCE ... WEIGHT); weights a node already
        # carries (scraped off its TENANT rows) fill in for tenants the
        # operator didn't name.  Unknown tenants weigh 1.0.
        self.tenant_weights = dict(tenant_weights or {})
        self._scraped_weights: Dict[str, float] = {}
        # node -> tenant -> cumulative demand counter at last sweep
        self._last: Dict[str, Dict[str, int]] = {}
        # tenant -> node -> rate pushed last sweep (observability + tests)
        self.last_split: Dict[str, Dict[str, float]] = {}
        self.sweeps = 0
        self.push_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one control-loop tick (synchronous, unit-testable) -------------------

    def _scrape_node(self, node: str) -> Optional[Dict[str, Tuple[int, int]]]:
        try:
            with self.conn_factories[node]() as c:
                reply = c.execute("CLUSTER", "QOS")
        except Exception:  # noqa: BLE001 — a dead node skips this sweep
            return None
        self._scraped_weights.update(parse_tenant_weights(reply))
        return parse_tenant_table(reply)

    def weight_of(self, tenant: str) -> float:
        """Configured weight wins; a weight the fleet already carries fills
        in; everyone else is 1.0."""
        w = self.tenant_weights.get(tenant)
        if w is None:
            w = self._scraped_weights.get(tenant, 1.0)
        return max(0.0, float(w))

    def _push(self, node: str, tenant: str, rate: float,
              weight: float) -> None:
        args: List[object] = ["CLUSTER", "QOS", "REBALANCE", tenant,
                             f"{rate:.6f}"]
        if self.global_burst is not None:
            # each node's burst headroom scales with its share of the
            # tenant's WEIGHTED global budget, so the fleet-wide burst stays
            # the configured global number (times the tenant's weight)
            budget = self.global_rate * max(weight, 1e-9)
            args.append(f"{self.global_burst * rate / budget:.6f}")
        if tenant in self.tenant_weights:
            # operator-configured weights are authoritative: teach the node
            args += ["WEIGHT", f"{weight:g}"]
        try:
            with self.conn_factories[node]() as c:
                c.execute(*args)
        except Exception:  # noqa: BLE001 — degrade to the last pushed split
            self.push_errors += 1

    def step(self) -> Dict[str, Dict[str, float]]:
        """One sweep: returns {tenant: {node: pushed_rate}} (empty on the
        baseline sweep and when no tenant has traffic)."""
        tables: Dict[str, Dict[str, Tuple[int, int]]] = {}
        for node in self.conn_factories:
            t = self._scrape_node(node)
            if t is not None:
                tables[node] = t
        # demand = delta of cumulative (admitted + shed) ops since the last
        # sweep: what the tenant attempted on that node, granted or not
        demand: Dict[str, Dict[str, float]] = {}
        for node, table in tables.items():
            prev = self._last.setdefault(node, {})
            for tenant, (admitted, shed) in table.items():
                cum = admitted + shed
                if tenant in prev:
                    demand.setdefault(tenant, {})[node] = float(
                        max(0, cum - prev[tenant])
                    )
                prev[tenant] = cum
        pushed: Dict[str, Dict[str, float]] = {}
        for tenant, node_demand in demand.items():
            weight = self.weight_of(tenant)
            split = split_rate(self.global_rate, node_demand, self.min_share,
                               weight=weight)
            for node, rate in split.items():
                self._push(node, tenant, rate, weight)
            pushed[tenant] = split
        if pushed:
            self.last_split = pushed
        self.sweeps += 1
        return pushed

    # -- background thread -----------------------------------------------------

    def start(self) -> "QosRebalancer":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="qos-rebalance", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must outlive a sweep
                pass
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
