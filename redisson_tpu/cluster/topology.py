"""Cluster topology wiring: the ONE slot-assignment + SETVIEW program.

Both cluster shapes — the in-process :class:`~redisson_tpu.harness.ClusterRunner`
(hermetic tests) and the process-level
:class:`~redisson_tpu.cluster.supervisor.ClusterSupervisor` (one ``tpu-server``
OS process per node, ISSUE 6) — must agree EXACTLY on how the 16384 slots map
onto masters and how that map is installed, or a soak that passes in-process
could mask a multi-process routing bug (and vice versa).  This module is that
single source of truth:

  * :func:`split_slots` — the even contiguous partition (the reference's
    create-cluster default layout, ``redis-cli --cluster create``);
  * :func:`view_tuples` / :func:`flatten_view` — the ``CLUSTER SETVIEW``
    5-tuple program built from (slot-range, master identity) pairs;
  * :func:`install_view` — push one view to every live node;
  * :func:`wire_replica` — attach a replica to its master (``REPLICAOF``).

Callers hand over *connection factories* (zero-arg callables returning a
context-managed connection with ``.execute``), so the same wiring code drives
in-process ``ServerThread.client()`` handles and the supervisor's real-TCP
admin connections without this module knowing which it is talking to.
"""
from __future__ import annotations

import warnings
from typing import (
    Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple,
)

from redisson_tpu.net.resp import RespError
from redisson_tpu.utils.crc16 import MAX_SLOT

# (slot_from, slot_to, host, port, node_id) — the SETVIEW row shape every
# layer of the system (TpuServer.cluster_view, harness, monitor) shares
ViewRow = Tuple[int, int, str, int, str]


def check_reply(reply: Any) -> Any:
    """Surface server-side errors: a RespError REPLY becomes a raise."""
    if isinstance(reply, RespError):
        raise reply
    return reply


def split_slots(n: int) -> List[Tuple[int, int]]:
    """Even contiguous slot partition for `n` masters (the reference's
    create-cluster default layout).  The last range absorbs the remainder."""
    if n < 1:
        raise ValueError(f"need at least one master, got {n}")
    per = MAX_SLOT // n
    ranges = []
    for i in range(n):
        lo = i * per
        hi = MAX_SLOT - 1 if i == n - 1 else (i + 1) * per - 1
        ranges.append((lo, hi))
    return ranges


def view_tuples(
    slot_ranges: Sequence[Tuple[int, int]],
    masters: Sequence[Optional[Tuple[str, int, str]]],
) -> List[ViewRow]:
    """Zip slot ranges with master identities ``(host, port, node_id)`` into
    SETVIEW rows.  A ``None`` master (stopped/dead node) drops its range from
    the view — exactly the hole a failover coordinator later re-points."""
    if len(slot_ranges) != len(masters):
        raise ValueError(
            f"{len(slot_ranges)} slot ranges vs {len(masters)} masters"
        )
    return [
        (lo, hi, host, int(port), node_id)
        for (lo, hi), m in zip(slot_ranges, masters)
        if m is not None
        for (host, port, node_id) in (m,)
    ]


def flatten_view(view: Iterable[ViewRow]) -> List:
    """SETVIEW wire operands: the 5-tuples flattened in row order."""
    flat: List = []
    for lo, hi, host, port, node_id in view:
        flat += [lo, hi, host, port, node_id]
    return flat


def install_view(
    conn_factories: Sequence[Callable[[], Any]],
    view: Sequence[ViewRow],
    timeout: Optional[float] = 10.0,
) -> None:
    """Push ONE view to every node.  Each factory yields a context-managed
    connection (``with factory() as c: c.execute(...)``); a node that
    rejects the view raises — topology installation is not best-effort."""
    flat = flatten_view(view)
    for factory in conn_factories:
        with factory() as c:
            check_reply(c.execute("CLUSTER", "SETVIEW", *flat, timeout=timeout))


def wire_replica(
    conn_factory: Callable[[], Any],
    master_host: str,
    master_port: int,
    timeout: Optional[float] = 120.0,
) -> None:
    """Attach one replica to its master (REPLICAOF full-sync + register).
    The generous default timeout covers the snapshot transfer."""
    with conn_factory() as c:
        check_reply(
            c.execute("REPLICAOF", master_host, master_port, timeout=timeout)
        )


class PlacementDegraded(UserWarning):
    """Host anti-affinity could not be honored (fewer failure domains than
    the replication factor needs) — the fleet still forms, but a single
    host failure can now take a master AND its replica together."""


def assign_hosts(
    hosts: Sequence[str],
    n_masters: int,
    replicas_per_master: int = 0,
) -> Tuple[List[str], Dict[Tuple[int, int], str]]:
    """Failure-domain placement (ISSUE 16): map a fleet plan onto host
    labels with HOST ANTI-AFFINITY — a replica is never placed on its
    master's host, because a replica that shares its master's failure
    domain is not a replica, it is a second copy of the same outage.

      * masters round-robin across ``hosts`` (spread, not packed);
      * replica ``r`` of master ``mi`` takes the ``(1 + r)``-th host AFTER
        its master's in ring order — off-host by construction, and
        consecutive replicas of one master land on DISTINCT hosts while
        enough domains exist;
      * one host (or ``replicas_per_master >= len(hosts)``) cannot honor
        anti-affinity for every replica: the placement DEGRADES LOUDLY —
        a :class:`PlacementDegraded` warning names every violating pair —
        rather than refusing to form (single-host CI fleets are the
        common case) or silently pretending the domain split exists.

    Returns ``(master_hosts, replica_hosts)``: ``master_hosts[mi]`` is
    master ``mi``'s host label, ``replica_hosts[(mi, r)]`` replica ``r``
    of master ``mi``'s."""
    if not hosts:
        raise ValueError("need at least one host label")
    ring = list(hosts)
    master_hosts = [ring[i % len(ring)] for i in range(n_masters)]
    replica_hosts: Dict[Tuple[int, int], str] = {}
    violations: List[str] = []
    for mi in range(n_masters):
        anchor = mi % len(ring)
        for r in range(replicas_per_master):
            host = ring[(anchor + 1 + r) % len(ring)]
            replica_hosts[(mi, r)] = host
            if host == master_hosts[mi]:
                violations.append(f"r{mi}-{r} shares host {host!r} with m{mi}")
    if violations:
        warnings.warn(
            "host anti-affinity DEGRADED — "
            f"{len(ring)} host(s) cannot separate "
            f"{replicas_per_master} replica(s) from each master: "
            + "; ".join(violations),
            PlacementDegraded,
            stacklevel=2,
        )
    return master_hosts, replica_hosts


def fetch_view(conn: Any, timeout: Optional[float] = 10.0) -> List[ViewRow]:
    """Read a node's current view back (CLUSTER SLOTS reply -> rows)."""
    rows: List[ViewRow] = []
    for row in check_reply(conn.execute("CLUSTER", "SLOTS", timeout=timeout)):
        lo, hi, (host, port, nid) = int(row[0]), int(row[1]), row[2]
        rows.append((lo, hi, _s(host), int(port), _s(nid)))
    return rows


def _s(v: Any) -> str:
    return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)
