"""Fleet-wide HBM pressure control loop (ISSUE 20).

The per-node residency sweeper keeps each device under its byte budget by
DEMOTING cold records — but demotion only helps while a device still has
demotable (clean, idle) bytes.  A device whose HOT working set itself
outgrows the budget needs records to live somewhere else, and the only safe
way to move them is the journaled fenced device rebalance (ISSUE 19's
quarantine-and-evacuate machinery).  This module closes that loop with the
same shape as :class:`~redisson_tpu.cluster.qos_control.QosRebalancer` — a
CONTROL LOOP, not consensus:

  * every sweep scrapes each node's ``CLUSTER RESIDENCY`` ledger (per-device
    hot/warm/cold bytes + the node's budget) and ``CLUSTER DEVICES``
    (placement present?);
  * a device whose HOT bytes exceed ``high_water * budget`` is PRESSURED —
    the first response is ``CLUSTER RESIDENCY SWEEP`` (demote-first: free
    relief, nothing moves across devices);
  * a device still pressured after ``shed_after`` consecutive sweeps has a
    working set demotion cannot fix — the loop issues ``CLUSTER RESIDENCY
    SHED <dev> COUNT <n>``, moving a bounded bite of the device's slots onto
    the survivors through the journaled fenced rebalance (keyed traffic on
    the moving slots rides the existing TRYAGAIN fence; acked writes cannot
    be lost to a shed);
  * an unreachable node contributes nothing and receives nothing that sweep
    — its local sweeper keeps the device bounded (degrade to per-node
    behavior, never to worse).

Runs over any fleet addressed by connection factories — the same contract
as ``QosRebalancer`` (``ClusterSupervisor.conn`` wrapped per node, or raw
``net.connection.Connection`` for driver-spawned fleets).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["ResidencyRebalancer", "parse_residency_table"]


def parse_residency_table(reply) -> Tuple[bool, int, Dict[int, Tuple[int, int, int]]]:
    """``CLUSTER RESIDENCY`` reply -> (armed, budget_bytes,
    {dev: (hot, warm, cold)}).

    Tolerates the reply growing rows — only ``[b"DEV", dev, hot, warm,
    cold]`` rows are read; the trailing CTR row is skipped."""
    armed = False
    budget = 0
    devs: Dict[int, Tuple[int, int, int]] = {}
    if not isinstance(reply, (list, tuple)) or len(reply) < 2:
        return armed, budget, devs
    armed = bool(int(reply[0]))
    budget = int(reply[1])
    for row in reply[2:]:
        if not isinstance(row, (list, tuple)) or len(row) < 5:
            continue
        if row[0] not in (b"DEV", "DEV"):
            continue
        devs[int(row[1])] = (int(row[2]), int(row[3]), int(row[4]))
    return armed, budget, devs


class ResidencyRebalancer:
    """The control loop: scrape ledgers -> detect pressure -> demote-first
    -> shed persistent pressure through the journaled device rebalance."""

    def __init__(self, conn_factories: Dict[str, Callable], *,
                 interval: float = 1.0, high_water: float = 0.9,
                 shed_after: int = 2, shed_count: int = 8,
                 journal_dir: Optional[str] = None,
                 budget_bytes: Optional[int] = None):
        if not 0.0 < high_water <= 1.0:
            raise ValueError("high_water must be in (0, 1]")
        self.conn_factories = dict(conn_factories)
        self.interval = float(interval)
        self.high_water = float(high_water)
        self.shed_after = max(1, int(shed_after))
        self.shed_count = max(1, int(shed_count))
        self.journal_dir = journal_dir
        # None = trust each node's scraped budget; an explicit number
        # overrides (the operator's fleet-wide per-device ceiling)
        self.budget_bytes = budget_bytes
        # (node, dev) -> consecutive pressured sweeps
        self._pressure: Dict[Tuple[str, int], int] = {}
        # observability + tests: what the last step actually did
        self.last_actions: List[Tuple[str, str, int]] = []
        self.sweeps = 0
        self.sweeps_issued = 0
        self.sheds_issued = 0
        self.push_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one control-loop tick (synchronous, unit-testable) -------------------

    def _scrape_node(self, node: str):
        try:
            with self.conn_factories[node]() as c:
                reply = c.execute("CLUSTER", "RESIDENCY")
        except Exception:  # noqa: BLE001 — a dead node skips this sweep
            return None
        return parse_residency_table(reply)

    def _issue(self, node: str, *args) -> bool:
        try:
            with self.conn_factories[node]() as c:
                c.execute(*args)
            return True
        except Exception:  # noqa: BLE001 — degrade to the node's own sweeper
            self.push_errors += 1
            return False

    def step(self) -> List[Tuple[str, str, int]]:
        """One sweep: returns the actions taken as (node, action, dev)
        tuples, action in {"sweep", "shed"}."""
        actions: List[Tuple[str, str, int]] = []
        for node in self.conn_factories:
            scraped = self._scrape_node(node)
            if scraped is None:
                continue
            armed, node_budget, devs = scraped
            budget = (self.budget_bytes if self.budget_bytes is not None
                      else node_budget)
            if not armed or budget <= 0:
                # nothing to defend: clear any stale pressure bookkeeping
                for key in [k for k in self._pressure if k[0] == node]:
                    del self._pressure[key]
                continue
            ceiling = self.high_water * budget
            for dev, (hot, _warm, _cold) in sorted(devs.items()):
                key = (node, dev)
                if hot <= ceiling:
                    self._pressure.pop(key, None)
                    continue
                streak = self._pressure.get(key, 0) + 1
                self._pressure[key] = streak
                if streak < self.shed_after:
                    # demote-first: ask the node to sweep before anything
                    # crosses a device boundary
                    if self._issue(node, "CLUSTER", "RESIDENCY", "SWEEP"):
                        self.sweeps_issued += 1
                        actions.append((node, "sweep", dev))
                else:
                    shed: List[object] = ["CLUSTER", "RESIDENCY", "SHED",
                                          str(dev), "COUNT",
                                          str(self.shed_count)]
                    if self.journal_dir:
                        shed += ["DIR", self.journal_dir]
                    if self._issue(node, *shed):
                        self.sheds_issued += 1
                        actions.append((node, "shed", dev))
                        self._pressure[key] = 0
        self.last_actions = actions
        self.sweeps += 1
        return actions

    # -- background thread -----------------------------------------------------

    def start(self) -> "ResidencyRebalancer":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="residency-rebalance", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must outlive a sweep
                pass
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
