"""Process-level chaos primitives for supervisor-run clusters (ISSUE 6).

The in-process chaos plane "kills" nodes by clearing a pause gate; here the
victims are real OS processes, so the primitives are real signals plus one
deterministic coordinator-death hook:

  * :func:`crash_coordinator_at` — run a journaled migration and "die" at a
    chosen journal phase (``migrate_slots(crash_after=...)`` raising
    ``CoordinatorKilled``).  Because the journal lives in the SUPERVISOR
    process and the servers are separate processes, the subsequent
    ``resume_migrations`` replays the PR 4 journal across a genuine process
    boundary — the property the in-process tier could only approximate.
  * :func:`sigkill_at_phase` — the compound storm the soak profile uses:
    crash the coordinator at a phase, then SIGKILL a server process AT that
    exact journal state, leaving both halves of the protocol dead at once.

SIGSTOP/SIGCONT freezes ride :meth:`ClusterSupervisor.pause`/``resume``
directly; SIGKILL/SIGTERM ride :meth:`ClusterSupervisor.kill`/``stop``.
"""
from __future__ import annotations

import signal
from typing import Optional, Sequence

from redisson_tpu.cluster.supervisor import ClusterSupervisor, NodeProc


def crash_coordinator_at(
    source: str,
    target: str,
    slots: Sequence[int],
    journal_dir: str,
    phase: str,
    password: Optional[str] = None,
    ssl_context=None,
) -> None:
    """Start a journaled migration and murder the coordinator right after
    `phase`'s journal entry (``PLANNED``, ``WINDOW_OPEN``,
    ``DRAINING:<sweep>``, ``VIEW_COMMITTED``).  Raises AssertionError if the
    crash point never fired (the phase was not reached) — a storm that
    silently completed is a broken storm, not a passed one."""
    from redisson_tpu.server.migration import CoordinatorKilled, migrate_slots

    try:
        migrate_slots(
            source, target, slots,
            journal_dir=journal_dir, crash_after=phase, password=password,
            ssl_context=ssl_context,
        )
    except CoordinatorKilled:
        return
    raise AssertionError(f"crash_after={phase!r} did not fire")


def kill_pair_at_phase(
    sup: ClusterSupervisor,
    source_node: NodeProc,
    target_node: NodeProc,
    slots: Sequence[int],
    phase: str,
    kill_source: bool = False,
    kill_target: bool = True,
    sig: int = signal.SIGKILL,
) -> dict:
    """The DOUBLE-kill matrix (ISSUE 13): the coordinator dies at `phase`
    (journal frozen at that exact state), then the chosen server
    process(es) — the migration TARGET by default, optionally the source
    too, i.e. every party to the protocol dead at once.  Returns
    ``{"source": rc, "target": rc}`` for the processes actually killed.
    Recovery is the caller's move: ``sup.restart(...)`` each victim (boot
    replays the target's import journal) + ``resume_migrations`` — or
    ``sup.promote_replica(target_node)`` +
    ``resume_migrations(readdress=...)`` for the failover path."""
    crash_coordinator_at(
        source_node.address, target_node.address, slots, sup.journal_dir,
        phase, password=sup.password, ssl_context=sup.client_ssl_context(),
    )
    out = {}
    if kill_target:
        out["target"] = sup.kill(target_node, sig)
    if kill_source:
        out["source"] = sup.kill(source_node, sig)
    return out


def sigkill_at_phase(
    sup: ClusterSupervisor,
    victim: NodeProc,
    source: str,
    target: str,
    slots: Sequence[int],
    phase: str,
    sig: int = signal.SIGKILL,
) -> Optional[int]:
    """The cross-process double-kill: coordinator dies at `phase` (journal
    frozen at that exact state), THEN the victim server process is killed.
    Returns the victim's exit code (negative signal number).  Recovery is
    the caller's move: ``sup.restart(victim)`` +
    ``resume_migrations(sup.journal_dir)``."""
    crash_coordinator_at(
        source, target, slots, sup.journal_dir, phase, password=sup.password,
        ssl_context=sup.client_ssl_context(),
    )
    return sup.kill(victim, sig)
