"""ClusterSupervisor: one ``tpu-server`` OS process per node, for real.

Parity target: the reference's ``RedisRunner.java`` — spawn/stop/restart
actual ``redis-server`` processes and form clusters out of them (SURVEY.md:
2,095 tests run against live server processes).  Everything this repo
previously called a "cluster" ran N :class:`ServerThread`\\ s inside ONE
Python process and one GIL; this module is the process-level shape the
ROADMAP names as the only honest production topology:

  * each node is a real subprocess (``python -m redisson_tpu.server``) with
    its own checkpoint directory, its own log file, and its own GIL;
  * readiness is a **ready-line protocol** (``--ready-fd``): the child
    writes ``READY <host> <port> <pid>`` to an inherited pipe once its
    listener is bound — no sleep-polling, and port 0 round-trips the
    kernel-chosen port back to the supervisor;
  * chaos is delivered as actual signals — ``kill(node)`` defaults to
    SIGKILL (nothing runs after it, unlike the in-process ``pause()``
    analog), SIGSTOP/SIGCONT freeze/thaw a live process, SIGTERM is the
    graceful path (AutoCheckpointer flush-on-stop, see server/server.py);
  * every reap records the exit code on the node
    (``NodeProc.exit_codes``), and ``log_tail`` surfaces the child's
    output for post-mortems;
  * topology wiring goes through :mod:`redisson_tpu.cluster.topology` —
    the SAME slot-assignment program the in-process harness uses, so the
    two cluster shapes cannot drift.

Cross-HOST fleets (ISSUE 16): WHERE a node runs is a
:class:`~redisson_tpu.cluster.hostdriver.HostDriver` decision, not the
supervisor's — :class:`LocalHostDriver` (default) is the historical
subprocess path byte-for-byte, :class:`SshHostDriver` spawns nodes on
remote machines with the SAME ready-line/signal/reap contract riding the
ssh channel, and every node carries a ``host_label`` naming its failure
domain.  ``hosts=`` activates failure-domain placement
(:func:`topology.assign_hosts` — a replica never shares its master's
host), ``kill_host`` takes a whole domain down at once, and a fleet with
any genuinely remote host arms TLS by default (the supervisor generates a
fleet cert and injects ``--tls-cert/--tls-key`` into every node; plaintext
stays the loopback-only default).

The supervisor process doubles as the migration coordinator's home: its
``journal_dir`` hosts the write-ahead migration journals
(server/migration_journal.py), so killing a *server* process mid-migration
and resuming via ``resume_migrations`` exercises the PR 4 journal across a
real process boundary — the cross-process soak profile in chaos/soak.py.
"""
from __future__ import annotations

import ipaddress
import os
import select
import signal
import subprocess
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

from redisson_tpu.cluster import topology
from redisson_tpu.cluster.hostdriver import (
    HostDriver, LocalHostDriver, NodeHandle,
)
from redisson_tpu.net.client import Connection
from redisson_tpu.net.resp import RespError
from redisson_tpu.net.retry import RetryPolicy, call_with_retry, link_policy

#: the implicit single-domain label a host-unaware supervisor places on
_LOCAL_HOST_LABEL = "local"


class NodeStartupError(RuntimeError):
    """A spawned node died (or went silent) before reporting ready; carries
    the exit code and a log tail so the failure is diagnosable."""


class NodeProc:
    """One supervised server process: identity, liveness, history.  The
    process itself lives behind a :class:`NodeHandle` — local child or
    ssh'd remote, the supervisor's contract is the same."""

    def __init__(self, name: str, role: str, base_dir: str,
                 master_index: Optional[int] = None,
                 host_label: str = _LOCAL_HOST_LABEL):
        self.name = name
        self.role = role  # "master" | "replica"
        self.master_index = master_index
        self.base_dir = base_dir
        self.host_label = host_label  # failure domain (driver-interpreted)
        self.checkpoint_path = os.path.join(base_dir, "ckpt", "head.ckpt")
        self.log_path = os.path.join(base_dir, "server.log")
        self.host = "127.0.0.1"
        self.port = 0            # learned from the first ready line, then pinned
        self.node_id: Optional[str] = None  # CLUSTER MYID (fresh per process)
        self.handle: Optional[NodeHandle] = None
        self.generation = 0      # +1 per successful spawn
        self.exit_codes: List[int] = []  # every reaped exit status, in order

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def pid(self) -> Optional[int]:
        return self.handle.pid if self.handle is not None else None

    def alive(self) -> bool:
        return self.handle is not None and self.handle.poll() is None

    def reap(self) -> Optional[int]:
        """Collect the exit code of a dead process (no-op while alive)."""
        if self.handle is None:
            return self.exit_codes[-1] if self.exit_codes else None
        rc = self.handle.poll()
        if rc is None:
            return None
        self.exit_codes.append(rc)
        self.handle.release()
        self.handle = None
        return rc


class ClusterSupervisor:
    """Spawn, wire, kill, and restart a multi-process tpu-server cluster.

    Usage::

        sup = ClusterSupervisor(masters=2).start()
        try:
            client = sup.client()          # slot-routed, real TCP
            sup.kill(sup.masters[0])       # SIGKILL — a real dead process
            sup.restart(sup.masters[0])    # same port, fresh process,
                                           # --restore from its checkpoint
        finally:
            sup.shutdown()

    Cross-host: ``ClusterSupervisor(masters=2, replicas_per_master=1,
    hosts=("hostA", "hostB"), driver=SshHostDriver(...))`` places masters
    round-robin and replicas off their master's host, spawns over ssh, and
    arms fleet TLS automatically (``tls=False`` opts out, ``tls=True``
    forces it for local fleets)."""

    def __init__(
        self,
        masters: int = 2,
        replicas_per_master: int = 0,
        base_dir: Optional[str] = None,
        password: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        server_args: Sequence[str] = (),
        platform: Optional[str] = None,
        checkpoint_interval: float = 0.0,
        ready_timeout: float = 90.0,
        driver: Optional[HostDriver] = None,
        hosts: Optional[Sequence[str]] = None,
        tls: Optional[bool] = None,
        retry_profile: Optional[str] = None,
    ):
        self.n_masters = masters
        self.replicas_per_master = replicas_per_master
        self.password = password
        self.extra_env = dict(env or {})
        self.server_args = list(server_args)
        self.platform = platform
        self.checkpoint_interval = checkpoint_interval
        self.ready_timeout = ready_timeout
        self.driver = driver if driver is not None else LocalHostDriver()
        # tpu-server --retry-profile for every node (net/retry LINK_PROFILES;
        # "wan" stretches cluster-link backoff for real networks).  The
        # COORDINATOR side (this process) follows RTPU_RETRY_PROFILE.
        self.retry_profile = retry_profile
        self.tls = tls  # None = auto: on iff any host is remote
        self._tls_cert: Optional[str] = None
        self._tls_key: Optional[str] = None
        self._client_ssl = None
        self._owns_base_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="rtpu-cluster-")
        # the COORDINATOR's migration-journal home: migrate_slots /
        # resume_migrations run in THIS process against the spawned servers
        self.journal_dir = os.path.join(self.base_dir, "journal")
        os.makedirs(self.journal_dir, exist_ok=True)
        self.slot_ranges = topology.split_slots(masters)
        # failure-domain placement: explicit hosts= engages anti-affinity
        # (loudly degraded when impossible); a host-unaware supervisor is
        # ONE implicit domain and stays silent about it — that is today's
        # single-machine fleet, not a degraded placement
        if hosts:
            self.hosts = list(hosts)
            self._master_hosts, self._replica_hosts = topology.assign_hosts(
                self.hosts, masters, replicas_per_master
            )
        else:
            self.hosts = [_LOCAL_HOST_LABEL]
            self._master_hosts = [_LOCAL_HOST_LABEL] * masters
            self._replica_hosts = {
                (mi, r): _LOCAL_HOST_LABEL
                for mi in range(masters) for r in range(replicas_per_master)
            }
        self.masters: List[NodeProc] = []
        self.replicas: List[NodeProc] = []
        # fleet-wide tenant budget control loop (ISSUE 18): armed on demand
        # via start_qos_rebalance, reaped by shutdown
        self._qos_rebalancer = None

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "ClusterSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def nodes(self) -> List[NodeProc]:
        return self.masters + self.replicas

    def nodes_on(self, host: str) -> List[NodeProc]:
        """Every node placed in failure domain ``host``."""
        return [n for n in self.nodes() if n.host_label == host]

    def start(self) -> "ClusterSupervisor":
        try:
            self._arm_tls()
            for i in range(self.n_masters):
                node = self._make_node(
                    f"m{i}", "master", host_label=self._master_hosts[i]
                )
                self.masters.append(node)
                self._spawn(node)
            for mi in range(self.n_masters):
                for r in range(self.replicas_per_master):
                    node = self._make_node(
                        f"r{mi}-{r}", "replica", master_index=mi,
                        host_label=self._replica_hosts[(mi, r)],
                    )
                    self.replicas.append(node)
                    self._spawn(node)
            for node in self.nodes():
                self.wait_ready(node)
            self.install_topology()
        except BaseException:
            # a half-started fleet must not leak OS processes OR driver-held
            # remote resources (ssh channels, emitted specs): reap everything
            # already spawned, then let the driver drop what only IT can see,
            # before surfacing the failure
            self.shutdown()
            self.driver.on_start_failure()
            raise
        return self

    def shutdown(self) -> None:
        """SIGTERM everything (graceful: checkpoint flush-on-stop), escalate
        to SIGKILL on stragglers, reap every exit code.  Bounded end to
        end: a wedged node (SIGSTOPped, hung in a flush) cannot stall the
        teardown — SIGKILL reaps even a stopped process.  Driver-held
        resources (ssh channels) are released last."""
        self.stop_qos_rebalance()
        for node in self.nodes():
            if node.alive():
                node.handle.signal(signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        for node in self.nodes():
            if node.handle is None:
                continue
            self._reap_escalating(
                node, max(0.1, deadline - time.monotonic())
            )
        self.driver.close()

    def _reap_escalating(self, node: NodeProc, grace: float) -> Optional[int]:
        """Bounded reap of a process that was just signalled: wait `grace`
        for a voluntary exit, SIGKILL on expiry, bound the post-kill wait
        too.  Records the exit code (satellite: the code still lands in
        ``exit_codes`` even on the escalated path); returns None only if
        even SIGKILL cannot reap in time (uninterruptible D-state) — the
        next ``reap()`` collects it."""
        if node.handle is None:
            return node.exit_codes[-1] if node.exit_codes else None
        if node.handle.wait(grace) is None:
            node.handle.force_kill()
            if node.handle.wait(10.0) is None:
                node.handle.close_ready()
                return None
        node.handle.close_ready()
        return node.reap()

    # -- spawning ------------------------------------------------------------

    def _make_node(self, name: str, role: str,
                   master_index: Optional[int] = None,
                   host_label: str = _LOCAL_HOST_LABEL) -> NodeProc:
        base = os.path.join(self.base_dir, name)
        os.makedirs(os.path.join(base, "ckpt"), exist_ok=True)
        return NodeProc(
            name, role, base, master_index=master_index,
            host_label=host_label,
        )

    def _server_cli(self, node: NodeProc, restore: bool) -> List[str]:
        """The full tpu-server CLI for one node — everything except
        ``--ready-fd``, which the driver owns (local: inherited pipe fd;
        ssh: fd 3 dup'd onto the channel's stdout)."""
        bind = self.driver.bind_host(node.host_label)
        cmd = [
            "--host", bind if bind is not None else node.host,
            "--port", str(node.port),
        ]
        connect = self.driver.connect_address(node.host_label)
        if connect is not None and connect != (bind or node.host):
            # cross-host nodes bind wide but are NAMED by their routable
            # address everywhere (views, journals, READY)
            cmd += ["--advertise-host", connect]
        cmd += [
            "--checkpoint", node.checkpoint_path,
            # crashed-node restart discipline: a node that died mid-
            # migration re-arms its windows from the coordinator journal
            # BEFORE serving (migration.rearm_recovery)
            "--journal-dir", self.journal_dir,
        ]
        if self.checkpoint_interval > 0:
            cmd += ["--checkpoint-interval", str(self.checkpoint_interval)]
        if restore and os.path.exists(node.checkpoint_path):
            cmd.append("--restore")
        if self.password:
            cmd += ["--password", self.password]
        if self.platform:
            cmd += ["--platform", self.platform]
        if self.tls_armed:
            # every node gets the fleet cert: the bus (client listeners AND
            # server-to-server links via link_client's TLS inheritance)
            # refuses plaintext fleet-wide, not just on the remote hops
            cmd += ["--tls-cert", self._tls_cert, "--tls-key", self._tls_key]
        if self.retry_profile:
            cmd += ["--retry-profile", self.retry_profile]
        cmd += self.server_args
        return cmd

    def _spawn(self, node: NodeProc, restore: bool = False) -> None:
        node.handle = self.driver.spawn(
            node.name, node.host_label, self._server_cli(node, restore),
            node.log_path, dict(self.extra_env),
            ensure_dirs=(os.path.dirname(node.checkpoint_path),),
        )
        node.generation += 1

    def wait_ready(self, node: NodeProc, timeout: Optional[float] = None) -> NodeProc:
        """Block until the node's ready line arrives (no sleep-polling: the
        child writes ``READY <host> <port> <pid>`` the moment its listener
        is bound).  Learns the kernel-assigned port on first boot and the
        fresh node id every boot.  A child that dies first raises
        :class:`NodeStartupError` with its exit code and log tail."""
        deadline = time.monotonic() + (timeout or self.ready_timeout)
        buf = b""
        handle = node.handle
        assert handle is not None, f"{node.name}: no spawn in flight"
        rfd = handle.ready_fd()
        assert rfd is not None, f"{node.name}: ready channel already closed"
        try:
            while b"\n" not in buf:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise NodeStartupError(
                        f"{node.name}: no ready line within "
                        f"{timeout or self.ready_timeout:.0f}s\n"
                        + self.log_tail(node)
                    )
                ready, _, _ = select.select([rfd], [], [], min(remain, 0.25))
                if not ready:
                    if not node.alive():
                        rc = node.reap()
                        raise NodeStartupError(
                            f"{node.name}: died before ready (exit {rc})\n"
                            + self.log_tail(node)
                        )
                    continue
                chunk = os.read(rfd, 4096)
                if not chunk:  # EOF without a ready line
                    rc = node.reap() if not node.alive() else None
                    raise NodeStartupError(
                        f"{node.name}: ready pipe closed before READY "
                        f"(exit {rc})\n" + self.log_tail(node)
                    )
                buf += chunk
        finally:
            handle.close_ready()
        line = buf.split(b"\n", 1)[0].decode(errors="replace").split()
        if len(line) < 3 or line[0] != "READY":
            raise NodeStartupError(f"{node.name}: bad ready line {line!r}")
        if len(line) >= 4:
            # remote handles learn their signal target (the REMOTE pid) here
            handle.note_ready(line[1], int(line[2]), int(line[3]))
        # connect address: the driver's word beats the READY line's bind
        # host (a remote node binding 0.0.0.0 is reached by its host's
        # routable address, not by what it bound)
        node.host = handle.connect_host or line[1]
        node.port = int(line[2])
        with self.conn(node) as c:
            node.node_id = topology._s(
                topology.check_reply(c.execute("CLUSTER", "MYID"))
            )
        return node

    # -- TLS (cross-host bus) -------------------------------------------------

    @property
    def tls_armed(self) -> bool:
        return self._tls_cert is not None

    def _arm_tls(self) -> None:
        """TLS-by-default for fleets that leave the machine: ``tls=None``
        arms iff the driver reports any host as remote (plaintext stays
        the loopback default), ``tls=True`` forces arming.  The supervisor
        generates ONE self-signed fleet cert (openssl CLI, the
        tests/test_tls_acl.py recipe) that every node loads — servers
        refuse plaintext at the handshake, and ``link_client``'s TLS
        inheritance carries it onto every server-to-server
        migration/replication link.  Ssh nodes read the cert over the
        shared filesystem (see hostdriver module docs)."""
        want = self.tls if self.tls is not None else any(
            self.driver.is_remote(h) for h in self.hosts
        )
        if not want:
            return
        tls_dir = os.path.join(self.base_dir, "tls")
        cert = os.path.join(tls_dir, "fleet.crt")
        key = os.path.join(tls_dir, "fleet.key")
        if not (os.path.exists(cert) and os.path.exists(key)):
            os.makedirs(tls_dir, exist_ok=True)
            sans = ["DNS:localhost", "IP:127.0.0.1"]
            for h in self.hosts:
                try:
                    ipaddress.ip_address(h)
                    sans.append(f"IP:{h}")
                except ValueError:
                    sans.append(f"DNS:{h}")
            subprocess.run(
                ["openssl", "req", "-x509", "-newkey", "rsa:2048",
                 "-keyout", key, "-out", cert, "-days", "2", "-nodes",
                 "-subj", "/CN=rtpu-fleet",
                 "-addext", "subjectAltName=" + ",".join(dict.fromkeys(sans))],
                check=True, capture_output=True,
            )
        self._tls_cert, self._tls_key = cert, key

    def client_ssl_context(self):
        """The coordinator/client-side SSL context for this fleet's bus
        (None when plaintext): trusts the fleet cert as its own root,
        hostname checks off — fleet peers are addressed by IP/labels, and
        the chain pin is what keeps plaintext and foreign certs out."""
        if not self.tls_armed:
            return None
        if self._client_ssl is None:
            from redisson_tpu.net.client import client_ssl_context

            self._client_ssl = client_ssl_context(
                ca_file=self._tls_cert, verify_hostname=False,
            )
        return self._client_ssl

    # -- chaos / process control ----------------------------------------------

    def kill(self, node: NodeProc, sig: int = signal.SIGKILL) -> Optional[int]:
        """Deliver a real signal.  SIGKILL (the default) reaps and returns
        the exit code — the process is DEAD, its GIL, sockets, and device
        state gone with it.  SIGSTOP/SIGCONT return None (still alive)."""
        if node.handle is None:
            return node.exit_codes[-1] if node.exit_codes else None
        node.handle.signal(sig)
        if sig in (signal.SIGSTOP, signal.SIGCONT):
            return None
        return self._reap_escalating(node, 30.0)

    def kill_host(self, host: str,
                  sig: int = signal.SIGKILL) -> Dict[str, Optional[int]]:
        """A whole failure domain dies AT ONCE (ISSUE 16): signal every
        node on ``host`` first — concurrently dead, the way a machine
        loses power — then reap them under one shared deadline.  Returns
        ``{node name: exit code}`` (None entries for SIGSTOP/SIGCONT,
        which leave the domain frozen/thawed rather than dead)."""
        victims = [n for n in self.nodes_on(host) if n.handle is not None]
        for n in victims:
            n.handle.signal(sig)
        if sig in (signal.SIGSTOP, signal.SIGCONT):
            return {n.name: None for n in victims}
        deadline = time.monotonic() + 30.0
        return {
            n.name: self._reap_escalating(
                n, max(0.1, deadline - time.monotonic())
            )
            for n in victims
        }

    def stop(self, node: NodeProc, timeout: float = 15.0) -> Optional[int]:
        """Graceful SIGTERM (checkpoint flush-on-stop inside the server),
        escalating to SIGKILL after the `timeout` grace period — a wedged
        node (SIGSTOPped, hung mid-flush) cannot stall a teardown or a
        rolling restart; its exit code is still recorded.  Returns the
        exit code."""
        if node.handle is None:
            return node.exit_codes[-1] if node.exit_codes else None
        node.handle.signal(signal.SIGTERM)
        return self._reap_escalating(node, timeout)

    def pause(self, node: NodeProc) -> None:
        """SIGSTOP: the real hung-but-accepting failure mode — the kernel
        keeps the listen socket, the process answers nothing."""
        self.kill(node, signal.SIGSTOP)

    def resume(self, node: NodeProc) -> None:
        self.kill(node, signal.SIGCONT)

    def wait_exit(self, node: NodeProc, timeout: float = 30.0) -> Optional[int]:
        if node.handle is not None:
            node.handle.wait(timeout)
        return node.reap()

    @staticmethod
    def _rejoin_retry_policy() -> RetryPolicy:
        """The view-learning/re-wiring schedule for a node rejoining the
        fleet: mid-roll its peers may themselves be restarting, so a
        refused connect retries instead of failing the whole restart.
        Profile-driven (net/retry LINK_PROFILES "rejoin"): "lan" is the
        historical schedule, RTPU_RETRY_PROFILE=wan stretches it."""
        return link_policy("rejoin")

    def restart(self, node: NodeProc, restore: bool = True,
                force: bool = False) -> NodeProc:
        """Bring a dead node back on the SAME address.  **Idempotent**: a
        node that is still alive is left untouched (double restart is a
        no-op — the supervisor never kills a healthy process by accident)
        unless ``force=True``, which first stops it through the escalating
        SIGTERM→SIGKILL path (the rolling-restart step, and the only way
        to recycle a wedged-but-alive process).  The fresh process
        ``--restore``\\ s its checkpoint (when one exists), relearns the
        cluster view from a live peer (the supervisor's original plan may
        be stale after migrations/failovers — retried under
        :class:`~redisson_tpu.net.retry.RetryPolicy`, because mid-roll the
        peers may be restarting too), and replica links severed by the
        death are re-wired.  Peer SELECTION retries with the install: the
        view is re-fetched inside every attempt across ALL live nodes —
        replicas included — so a peer that died between attempts (the
        common case mid-host-kill) costs one retry, not the restart."""
        if node.alive():
            if not force:
                return node
            self.stop(node)
        node.reap()  # capture the exit code before respawning
        self._spawn(node, restore=restore)
        self.wait_ready(node)
        policy = self._rejoin_retry_policy()

        def _relearn_view() -> None:
            # fetched INSIDE the retry: each attempt re-selects a live peer
            # (current_view probes every node, bounded per peer), so a dead
            # or wedged first choice degrades to the next attempt's pick
            view = self.current_view()
            if view:
                topology.install_view([self._conn_factory(node)], view)

        call_with_retry(policy, _relearn_view)
        if node.role == "replica" and node.master_index is not None:
            master = self.masters[node.master_index]
            if master.alive():
                call_with_retry(
                    policy,
                    lambda: topology.wire_replica(
                        self._conn_factory(node), master.host, master.port
                    ),
                )
        elif node.role == "master":
            # replicas of THIS master lost their push registration with the
            # old process: re-attach them
            for rep in self.replicas:
                if rep.master_index is not None \
                        and self.masters[rep.master_index] is node \
                        and rep.alive():
                    call_with_retry(
                        policy,
                        lambda rep=rep: topology.wire_replica(
                            self._conn_factory(rep), node.host, node.port
                        ),
                    )
        return node

    # -- fleet lifecycle (ISSUE 13) -------------------------------------------

    def promote_replica(self, master: NodeProc) -> Optional[NodeProc]:
        """Fail a DEAD master over onto one of its live replicas, keeping
        any in-flight import window intact: the replica is promoted
        (``REPLICAOF NO ONE``), inherits the dead master's slots in the
        fleet view, and re-arms the IMPORTING windows of every in-flight
        journaled migration that targeted the dead address — then REPLAYS
        the dead master's journaled import batches onto it
        (apply-by-version: a no-op for every batch its REPLPUSH-covered
        link already delivered, the recovery path for any it missed),
        making it the durable continuation of the import, which
        ``resume_migrations(readdress={dead: promoted})`` then drives to
        STABLE.  Only after the replay are the dead master's in-flight
        import journals terminalized (superseded), and the bookkeeping
        swaps so a later ``restart()`` of the old process brings it back
        as a replica of its successor.  Returns the promoted node, or
        None when the master has no live replica."""
        from redisson_tpu.server.migration_journal import (
            ImportJournal, MigrationJournal,
        )

        mi = self.masters.index(master)
        rep = next(
            (r for r in self.replicas
             if r.master_index == mi and r.alive()),
            None,
        )
        if rep is None:
            return None
        dead_addr = master.address
        inflight_imports = [
            ij for ij in ImportJournal.in_flight(self.journal_dir)
            if ij.target == dead_addr
        ]
        def _promote() -> None:
            # idempotent end to end (REPLICAOF NO ONE, epoch-fenced SETSLOT
            # re-issues, apply-by-version IMPORTRECORDS replays), so the
            # whole block retries as one unit — a failover must survive the
            # very transport chaos that made it necessary
            with self.conn(rep) as c:
                topology.check_reply(c.execute("REPLICAOF", "NO", "ONE"))
                # in-flight import windows move WITH the promotion: the same
                # epoch re-fences, so the resumed drain's re-issues stay
                # idempotent and a stale coordinator stays fenced out
                for j in MigrationJournal.in_flight(self.journal_dir):
                    planned = j.entry("PLANNED")
                    if not planned \
                            or planned.get("kind") == "device_rebalance":
                        continue
                    if planned["target"] == dead_addr:
                        for s in planned["slots"]:
                            topology.check_reply(c.execute(
                                "CLUSTER", "SETSLOT", int(s), "IMPORTING",
                                planned["source"], "EPOCH", j.epoch,
                            ))
                # replay the dead target's journaled batches onto the
                # promoted node BEFORE superseding the journal: the REPLPUSH
                # cover on the import ack is best-effort (a stalled shipper
                # or unhealthy replica link ships nothing and the ack still
                # authorized the source's delete), so the journal — the one
                # durability point the ack actually proved — must not be
                # retired on an assumption.  apply-by-version makes the
                # replay a no-op for every batch the replica DID receive,
                # and the EPOCH stamp re-journals the batches under the
                # promoted node's own import journal, which the resumed
                # migration's STABLE then settles.
                for ij in inflight_imports:
                    for blob in ij.batch_blobs():
                        args = ["IMPORTRECORDS", "EPOCH", ij.epoch]
                        if ij.source:
                            args += ["SOURCE", ij.source]
                        topology.check_reply(
                            c.execute(*args, blob, timeout=60.0)
                        )

        call_with_retry(self._rejoin_retry_policy(), _promote)
        for ij in inflight_imports:
            ij.append("STABLE", superseded_by=rep.address)
        new_view = [
            (lo, hi, rep.host, rep.port, rep.node_id)
            if f"{h}:{p}" == dead_addr else (lo, hi, h, p, nid)
            for lo, hi, h, p, nid in self.current_view()
        ]
        rep.role, master.role = "master", "replica"
        self.replicas.remove(rep)
        rep.master_index = None
        self.masters[mi] = rep
        master.master_index = mi
        self.replicas.append(master)
        call_with_retry(
            self._rejoin_retry_policy(),
            lambda: topology.install_view(
                [self._conn_factory(n) for n in self.nodes() if n.alive()],
                new_view,
            ),
        )
        return rep

    def rolling_restart(
        self,
        nodes: Optional[Sequence[NodeProc]] = None,
        grace: float = 15.0,
        health_timeout: float = 60.0,
    ) -> List[Dict[str, object]]:
        """Restart/upgrade a LIVE fleet one node at a time with zero acked
        loss: per node — drain (``REPLFLUSH`` ships everything dirty to its
        replicas, ``SAVE`` pins the restart's restore point), escalating
        graceful stop, respawn on the same address, then a health barrier
        (cluster routable end to end, the restarted node answering, its
        replica links re-attached) before the roll moves on.  Replicas
        roll first so no master ever loses its last replica mid-step.
        Default order covers every node; pass ``nodes`` to roll a subset
        (e.g. masters only).  Returns one summary dict per node rolled."""
        order = (
            list(nodes) if nodes is not None
            else list(self.replicas) + list(self.masters)
        )
        rolled: List[Dict[str, object]] = []
        for node in order:
            if node.alive():
                try:
                    with self.conn(node, timeout=60.0) as c:
                        c.execute("REPLFLUSH", timeout=30.0)
                        reply = c.execute("SAVE", timeout=60.0)
                        if isinstance(reply, RespError):
                            raise reply
                except Exception:  # noqa: BLE001 — wedged node: the
                    pass           # escalating stop below still bounds us
            rc = self.stop(node, timeout=grace)
            # force: if even SIGKILL could not reap in time (rc None), the
            # retried stop inside restart() keeps the roll bounded instead
            # of silently no-opping on a still-"alive" zombie
            self.restart(node, force=True)
            self._health_barrier(node, timeout=health_timeout)
            rolled.append({
                "node": node.name, "exit_code": rc,
                "generation": node.generation,
            })
        return rolled

    def _health_barrier(self, node: NodeProc, timeout: float = 60.0) -> None:
        """One roll step's gate: the fleet routes end to end again AND the
        restarted node's replication links are re-attached (a master must
        list its live replicas — replication catch-up restarts from the
        full-sync pull ``wire_replica`` triggers) before the next node goes
        down."""
        deadline = time.monotonic() + timeout
        client = self.client(scan_interval=0.5)
        try:
            if not client.wait_routable(
                timeout=max(1.0, deadline - time.monotonic())
            ):
                raise NodeStartupError(
                    f"fleet not routable after rolling {node.name}\n"
                    + self.log_tail(node)
                )
        finally:
            client.shutdown()
        want = [
            rep for rep in self.replicas
            if node.role == "master" and rep.master_index is not None
            and self.masters[rep.master_index] is node and rep.alive()
        ]
        while want:
            try:
                with self.conn(node, timeout=10.0) as c:
                    have = {
                        topology._s(a) for a in c.execute("REPLICAS") or []
                    }
                if all(rep.address in have for rep in want):
                    return
            except Exception:  # noqa: BLE001 — node still settling
                pass
            if time.monotonic() >= deadline:
                raise NodeStartupError(
                    f"replicas never re-attached to {node.name} after roll"
                )
            time.sleep(0.1)

    # -- topology -------------------------------------------------------------

    def planned_view(self) -> List[topology.ViewRow]:
        return topology.view_tuples(
            self.slot_ranges,
            [
                (m.host, m.port, m.node_id) if m.node_id else None
                for m in self.masters
            ],
        )

    def current_view(self) -> List[topology.ViewRow]:
        """The view as the LIVE cluster knows it: asked from any live node
        that has one installed (migrations move ownership underneath the
        supervisor's original plan), falling back to the plan.  Each peer
        probe is BOUNDED (5s) so one wedged-but-accepting node — SIGSTOPped
        mid-host-kill — degrades to the next peer, not a 30s stall per
        restart."""
        for node in self.nodes():
            if not node.alive():
                continue
            try:
                with self.conn(node, timeout=5.0) as c:
                    view = topology.fetch_view(c)
            except Exception:  # noqa: BLE001 — try the next node
                continue
            # a node with no installed view reports the single-node default
            # (itself owning 0..16383): not a cluster view, keep looking
            if len(view) == 1 and view[0][0] == 0 and len(self.masters) > 1 \
                    and (view[0][2], view[0][3]) == (node.host, node.port):
                continue
            if view:
                return view
        return self.planned_view()

    def install_topology(self) -> None:
        """Initial wiring: push the planned view everywhere, attach replicas
        — the same program ClusterRunner runs, through cluster/topology."""
        view = self.planned_view()
        topology.install_view(
            [self._conn_factory(n) for n in self.nodes() if n.alive()], view
        )
        for rep in self.replicas:
            master = self.masters[rep.master_index]
            if rep.alive() and master.alive():
                topology.wire_replica(
                    self._conn_factory(rep), master.host, master.port
                )

    # -- access ---------------------------------------------------------------

    def conn(self, node: NodeProc, timeout: float = 30.0):
        """Context-managed admin connection to one node (real TCP; TLS when
        the fleet bus is armed)."""
        from contextlib import closing

        return closing(Connection(
            node.host, node.port, timeout=timeout, password=self.password,
            ssl_context=self.client_ssl_context(),
        ))

    def _conn_factory(self, node: NodeProc):
        return lambda: self.conn(node)

    def seeds(self) -> List[str]:
        return [n.address for n in self.nodes() if n.alive()]

    def client(self, **kw):
        """Slot-routed cluster client over the live processes."""
        from redisson_tpu.client.cluster import ClusterRedisson

        kw.setdefault("timeout", 60.0)
        if self.password is not None:
            kw.setdefault("password", self.password)
        if self.tls_armed:
            kw.setdefault("ssl_context", self.client_ssl_context())
        return ClusterRedisson(self.seeds(), **kw)

    def start_qos_rebalance(self, global_rate: float, *,
                            global_burst: Optional[float] = None,
                            interval: float = 1.0,
                            min_share: float = 0.05,
                            tenant_weights: Optional[Dict[str, float]] = None):
        """Arm the fleet-wide tenant budget control loop (ISSUE 18,
        cluster/qos_control.py): scrape every master's ``CLUSTER QOS``
        tenant table and re-split each tenant's ``global_rate`` across
        masters proportional to observed demand, pushed via ``CLUSTER QOS
        REBALANCE``.  Masters only — replicas don't admit writes, so
        budgeting them would dilute the split.  The conn factories ride the
        fleet bus unchanged (TLS + password on cross-host driver fleets),
        so the loop runs identically over LoopbackTransport/SSH-spawned
        hosts.  ``tenant_weights`` (ISSUE 19 satellite) sizes each tenant's
        global budget by service class (gold=2.0/silver=1.0) and is pushed
        fleet-wide via the REBALANCE verb's WEIGHT operand.  Idempotent;
        stopped by ``stop_qos_rebalance`` and by ``shutdown``."""
        from redisson_tpu.cluster.qos_control import QosRebalancer

        if self._qos_rebalancer is not None:
            return self._qos_rebalancer
        factories = {
            n.address: self._conn_factory(n) for n in self.masters
        }
        self._qos_rebalancer = QosRebalancer(
            factories, global_rate, global_burst=global_burst,
            interval=interval, min_share=min_share,
            tenant_weights=tenant_weights,
        ).start()
        return self._qos_rebalancer

    def stop_qos_rebalance(self) -> None:
        rb, self._qos_rebalancer = self._qos_rebalancer, None
        if rb is not None:
            rb.stop()

    def scrape(self) -> str:
        """Fleet-wide Prometheus scrape (ISSUE 12): pull ``METRICS`` from
        every live node and merge the expositions with per-node
        ``node="host:port"`` labels — the supervisor half of the
        one-pane-of-glass (the ``METRICS CLUSTER`` verb is the wire half;
        both ride ``utils.metrics.merge_prometheus_texts``).  Dead or
        unreachable nodes contribute nothing rather than failing the
        scrape."""
        from redisson_tpu.utils.metrics import merge_prometheus_texts

        texts: Dict[str, str] = {}
        for node in self.nodes():
            if not node.alive():
                continue
            try:
                with self.conn(node, timeout=10.0) as c:
                    texts[node.address] = bytes(c.execute("METRICS")).decode()
            except Exception:  # noqa: BLE001 — scrape the rest of the fleet
                continue
        return merge_prometheus_texts(texts)

    def log_tail(self, node: NodeProc, max_bytes: int = 4096) -> str:
        try:
            with open(node.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"
