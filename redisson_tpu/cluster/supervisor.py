"""ClusterSupervisor: one ``tpu-server`` OS process per node, for real.

Parity target: the reference's ``RedisRunner.java`` — spawn/stop/restart
actual ``redis-server`` processes and form clusters out of them (SURVEY.md:
2,095 tests run against live server processes).  Everything this repo
previously called a "cluster" ran N :class:`ServerThread`\\ s inside ONE
Python process and one GIL; this module is the process-level shape the
ROADMAP names as the only honest production topology:

  * each node is a real subprocess (``python -m redisson_tpu.server``) with
    its own checkpoint directory, its own log file, and its own GIL;
  * readiness is a **ready-line protocol** (``--ready-fd``): the child
    writes ``READY <host> <port> <pid>`` to an inherited pipe once its
    listener is bound — no sleep-polling, and port 0 round-trips the
    kernel-chosen port back to the supervisor;
  * chaos is delivered as actual signals — ``kill(node)`` defaults to
    SIGKILL (nothing runs after it, unlike the in-process ``pause()``
    analog), SIGSTOP/SIGCONT freeze/thaw a live process, SIGTERM is the
    graceful path (AutoCheckpointer flush-on-stop, see server/server.py);
  * every reap records the exit code on the node
    (``NodeProc.exit_codes``), and ``log_tail`` surfaces the child's
    output for post-mortems;
  * topology wiring goes through :mod:`redisson_tpu.cluster.topology` —
    the SAME slot-assignment program the in-process harness uses, so the
    two cluster shapes cannot drift.

The supervisor process doubles as the migration coordinator's home: its
``journal_dir`` hosts the write-ahead migration journals
(server/migration_journal.py), so killing a *server* process mid-migration
and resuming via ``resume_migrations`` exercises the PR 4 journal across a
real process boundary — the cross-process soak profile in chaos/soak.py.
"""
from __future__ import annotations

import os
import select
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

from redisson_tpu.cluster import topology
from redisson_tpu.net.client import Connection
from redisson_tpu.net.resp import RespError
from redisson_tpu.net.retry import RetryPolicy, call_with_retry


class NodeStartupError(RuntimeError):
    """A spawned node died (or went silent) before reporting ready; carries
    the exit code and a log tail so the failure is diagnosable."""


class NodeProc:
    """One supervised server process: identity, liveness, history."""

    def __init__(self, name: str, role: str, base_dir: str,
                 master_index: Optional[int] = None):
        self.name = name
        self.role = role  # "master" | "replica"
        self.master_index = master_index
        self.base_dir = base_dir
        self.checkpoint_path = os.path.join(base_dir, "ckpt", "head.ckpt")
        self.log_path = os.path.join(base_dir, "server.log")
        self.host = "127.0.0.1"
        self.port = 0            # learned from the first ready line, then pinned
        self.node_id: Optional[str] = None  # CLUSTER MYID (fresh per process)
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0      # +1 per successful spawn
        self.exit_codes: List[int] = []  # every reaped exit status, in order
        self._ready_rfd: Optional[int] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def reap(self) -> Optional[int]:
        """Collect the exit code of a dead process (no-op while alive)."""
        if self.proc is None:
            return self.exit_codes[-1] if self.exit_codes else None
        rc = self.proc.poll()
        if rc is None:
            return None
        self.exit_codes.append(rc)
        self.proc = None
        return rc


class ClusterSupervisor:
    """Spawn, wire, kill, and restart a multi-process tpu-server cluster.

    Usage::

        sup = ClusterSupervisor(masters=2).start()
        try:
            client = sup.client()          # slot-routed, real TCP
            sup.kill(sup.masters[0])       # SIGKILL — a real dead process
            sup.restart(sup.masters[0])    # same port, fresh process,
                                           # --restore from its checkpoint
        finally:
            sup.shutdown()
    """

    def __init__(
        self,
        masters: int = 2,
        replicas_per_master: int = 0,
        base_dir: Optional[str] = None,
        password: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        server_args: Sequence[str] = (),
        platform: Optional[str] = None,
        checkpoint_interval: float = 0.0,
        ready_timeout: float = 90.0,
    ):
        self.n_masters = masters
        self.replicas_per_master = replicas_per_master
        self.password = password
        self.extra_env = dict(env or {})
        self.server_args = list(server_args)
        self.platform = platform
        self.checkpoint_interval = checkpoint_interval
        self.ready_timeout = ready_timeout
        self._owns_base_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="rtpu-cluster-")
        # the COORDINATOR's migration-journal home: migrate_slots /
        # resume_migrations run in THIS process against the spawned servers
        self.journal_dir = os.path.join(self.base_dir, "journal")
        os.makedirs(self.journal_dir, exist_ok=True)
        self.slot_ranges = topology.split_slots(masters)
        self.masters: List[NodeProc] = []
        self.replicas: List[NodeProc] = []

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "ClusterSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def nodes(self) -> List[NodeProc]:
        return self.masters + self.replicas

    def start(self) -> "ClusterSupervisor":
        try:
            for i in range(self.n_masters):
                node = self._make_node(f"m{i}", "master")
                self.masters.append(node)
                self._spawn(node)
            for mi in range(self.n_masters):
                for r in range(self.replicas_per_master):
                    node = self._make_node(f"r{mi}-{r}", "replica", master_index=mi)
                    self.replicas.append(node)
                    self._spawn(node)
            for node in self.nodes():
                self.wait_ready(node)
            self.install_topology()
        except BaseException:
            # a half-started fleet must not leak OS processes: reap
            # everything already spawned before surfacing the failure
            self.shutdown()
            raise
        return self

    def shutdown(self) -> None:
        """SIGTERM everything (graceful: checkpoint flush-on-stop), escalate
        to SIGKILL on stragglers, reap every exit code.  Bounded end to
        end: a wedged node (SIGSTOPped, hung in a flush) cannot stall the
        teardown — SIGKILL reaps even a stopped process."""
        for node in self.nodes():
            if node.alive():
                try:
                    os.kill(node.proc.pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 15.0
        for node in self.nodes():
            if node.proc is None:
                continue
            self._reap_escalating(
                node, max(0.1, deadline - time.monotonic())
            )

    def _reap_escalating(self, node: NodeProc, grace: float) -> Optional[int]:
        """Bounded reap of a process that was just signalled: wait `grace`
        for a voluntary exit, SIGKILL on expiry, bound the post-kill wait
        too.  Records the exit code (satellite: the code still lands in
        ``exit_codes`` even on the escalated path); returns None only if
        even SIGKILL cannot reap in time (uninterruptible D-state) — the
        next ``reap()`` collects it."""
        if node.proc is None:
            return node.exit_codes[-1] if node.exit_codes else None
        try:
            node.proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            node.proc.kill()
            try:
                node.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self._close_ready_fd(node)
                return None
        self._close_ready_fd(node)
        return node.reap()

    # -- spawning ------------------------------------------------------------

    def _make_node(self, name: str, role: str,
                   master_index: Optional[int] = None) -> NodeProc:
        base = os.path.join(self.base_dir, name)
        os.makedirs(os.path.join(base, "ckpt"), exist_ok=True)
        return NodeProc(name, role, base, master_index=master_index)

    def _child_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        # the child must import redisson_tpu from THIS checkout regardless
        # of the supervisor's cwd
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env.update(self.extra_env)
        return env

    def _spawn(self, node: NodeProc, restore: bool = False) -> None:
        rfd, wfd = os.pipe()
        try:
            self._spawn_inner(node, rfd, wfd, restore)
        except BaseException:
            # spawn failed before the child owned the pipe: close both ends
            # here or repeated failed restarts leak fds until EMFILE
            for fd in (rfd, wfd):
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise
        node._ready_rfd = rfd
        node.generation += 1

    def _spawn_inner(self, node: NodeProc, rfd: int, wfd: int,
                     restore: bool) -> None:
        cmd = [
            sys.executable, "-m", "redisson_tpu.server",
            "--host", node.host, "--port", str(node.port),
            "--ready-fd", str(wfd),
            "--checkpoint", node.checkpoint_path,
            # crashed-node restart discipline: a node that died mid-
            # migration re-arms its windows from the coordinator journal
            # BEFORE serving (migration.rearm_recovery)
            "--journal-dir", self.journal_dir,
        ]
        if self.checkpoint_interval > 0:
            cmd += ["--checkpoint-interval", str(self.checkpoint_interval)]
        if restore and os.path.exists(node.checkpoint_path):
            cmd.append("--restore")
        if self.password:
            cmd += ["--password", self.password]
        if self.platform:
            cmd += ["--platform", self.platform]
        cmd += self.server_args
        with open(node.log_path, "ab") as log:
            node.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT,
                pass_fds=(wfd,), env=self._child_env(),
                start_new_session=True,  # our signals hit THIS pid only
            )
        os.close(wfd)  # child holds the write end now

    def _close_ready_fd(self, node: NodeProc) -> None:
        if node._ready_rfd is not None:
            try:
                os.close(node._ready_rfd)
            except OSError:
                pass
            node._ready_rfd = None

    def wait_ready(self, node: NodeProc, timeout: Optional[float] = None) -> NodeProc:
        """Block until the node's ready line arrives (no sleep-polling: the
        child writes ``READY <host> <port> <pid>`` the moment its listener
        is bound).  Learns the kernel-assigned port on first boot and the
        fresh node id every boot.  A child that dies first raises
        :class:`NodeStartupError` with its exit code and log tail."""
        deadline = time.monotonic() + (timeout or self.ready_timeout)
        buf = b""
        rfd = node._ready_rfd
        assert rfd is not None, f"{node.name}: no spawn in flight"
        try:
            while b"\n" not in buf:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise NodeStartupError(
                        f"{node.name}: no ready line within "
                        f"{timeout or self.ready_timeout:.0f}s\n"
                        + self.log_tail(node)
                    )
                ready, _, _ = select.select([rfd], [], [], min(remain, 0.25))
                if not ready:
                    if not node.alive():
                        rc = node.reap()
                        raise NodeStartupError(
                            f"{node.name}: died before ready (exit {rc})\n"
                            + self.log_tail(node)
                        )
                    continue
                chunk = os.read(rfd, 4096)
                if not chunk:  # EOF without a ready line
                    rc = node.reap() if not node.alive() else None
                    raise NodeStartupError(
                        f"{node.name}: ready pipe closed before READY "
                        f"(exit {rc})\n" + self.log_tail(node)
                    )
                buf += chunk
        finally:
            self._close_ready_fd(node)
        line = buf.split(b"\n", 1)[0].decode(errors="replace").split()
        if len(line) < 3 or line[0] != "READY":
            raise NodeStartupError(f"{node.name}: bad ready line {line!r}")
        node.host, node.port = line[1], int(line[2])
        with self.conn(node) as c:
            node.node_id = topology._s(
                topology.check_reply(c.execute("CLUSTER", "MYID"))
            )
        return node

    # -- chaos / process control ----------------------------------------------

    def kill(self, node: NodeProc, sig: int = signal.SIGKILL) -> Optional[int]:
        """Deliver a real signal.  SIGKILL (the default) reaps and returns
        the exit code — the process is DEAD, its GIL, sockets, and device
        state gone with it.  SIGSTOP/SIGCONT return None (still alive)."""
        if node.proc is None:
            return node.exit_codes[-1] if node.exit_codes else None
        try:
            os.kill(node.proc.pid, sig)
        except ProcessLookupError:
            pass
        if sig in (signal.SIGSTOP, signal.SIGCONT):
            return None
        return self._reap_escalating(node, 30.0)

    def stop(self, node: NodeProc, timeout: float = 15.0) -> Optional[int]:
        """Graceful SIGTERM (checkpoint flush-on-stop inside the server),
        escalating to SIGKILL after the `timeout` grace period — a wedged
        node (SIGSTOPped, hung mid-flush) cannot stall a teardown or a
        rolling restart; its exit code is still recorded.  Returns the
        exit code."""
        if node.proc is None:
            return node.exit_codes[-1] if node.exit_codes else None
        try:
            os.kill(node.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        return self._reap_escalating(node, timeout)

    def pause(self, node: NodeProc) -> None:
        """SIGSTOP: the real hung-but-accepting failure mode — the kernel
        keeps the listen socket, the process answers nothing."""
        self.kill(node, signal.SIGSTOP)

    def resume(self, node: NodeProc) -> None:
        self.kill(node, signal.SIGCONT)

    def wait_exit(self, node: NodeProc, timeout: float = 30.0) -> Optional[int]:
        if node.proc is not None:
            try:
                node.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                return None
        return node.reap()

    @staticmethod
    def _rejoin_retry_policy() -> RetryPolicy:
        """The view-learning/re-wiring schedule for a node rejoining the
        fleet: mid-roll its peers may themselves be restarting, so a
        refused connect retries instead of failing the whole restart."""
        return RetryPolicy(
            max_attempts=5, base_delay=0.1, max_delay=1.0, jitter=0.2,
            deadline_s=20.0,
        )

    def restart(self, node: NodeProc, restore: bool = True,
                force: bool = False) -> NodeProc:
        """Bring a dead node back on the SAME address.  **Idempotent**: a
        node that is still alive is left untouched (double restart is a
        no-op — the supervisor never kills a healthy process by accident)
        unless ``force=True``, which first stops it through the escalating
        SIGTERM→SIGKILL path (the rolling-restart step, and the only way
        to recycle a wedged-but-alive process).  The fresh process
        ``--restore``\\ s its checkpoint (when one exists), relearns the
        cluster view from a live peer (the supervisor's original plan may
        be stale after migrations/failovers — retried under
        :class:`~redisson_tpu.net.retry.RetryPolicy`, because mid-roll the
        peers may be restarting too), and replica links severed by the
        death are re-wired."""
        if node.alive():
            if not force:
                return node
            self.stop(node)
        node.reap()  # capture the exit code before respawning
        self._spawn(node, restore=restore)
        self.wait_ready(node)
        policy = self._rejoin_retry_policy()
        view = self.current_view()
        if view:
            call_with_retry(
                policy,
                lambda: topology.install_view([self._conn_factory(node)], view),
            )
        if node.role == "replica" and node.master_index is not None:
            master = self.masters[node.master_index]
            if master.alive():
                call_with_retry(
                    policy,
                    lambda: topology.wire_replica(
                        self._conn_factory(node), master.host, master.port
                    ),
                )
        elif node.role == "master":
            # replicas of THIS master lost their push registration with the
            # old process: re-attach them
            for rep in self.replicas:
                if rep.master_index is not None \
                        and self.masters[rep.master_index] is node \
                        and rep.alive():
                    call_with_retry(
                        policy,
                        lambda rep=rep: topology.wire_replica(
                            self._conn_factory(rep), node.host, node.port
                        ),
                    )
        return node

    # -- fleet lifecycle (ISSUE 13) -------------------------------------------

    def promote_replica(self, master: NodeProc) -> Optional[NodeProc]:
        """Fail a DEAD master over onto one of its live replicas, keeping
        any in-flight import window intact: the replica is promoted
        (``REPLICAOF NO ONE``), inherits the dead master's slots in the
        fleet view, and re-arms the IMPORTING windows of every in-flight
        journaled migration that targeted the dead address — then REPLAYS
        the dead master's journaled import batches onto it
        (apply-by-version: a no-op for every batch its REPLPUSH-covered
        link already delivered, the recovery path for any it missed),
        making it the durable continuation of the import, which
        ``resume_migrations(readdress={dead: promoted})`` then drives to
        STABLE.  Only after the replay are the dead master's in-flight
        import journals terminalized (superseded), and the bookkeeping
        swaps so a later ``restart()`` of the old process brings it back
        as a replica of its successor.  Returns the promoted node, or
        None when the master has no live replica."""
        from redisson_tpu.server.migration_journal import (
            ImportJournal, MigrationJournal,
        )

        mi = self.masters.index(master)
        rep = next(
            (r for r in self.replicas
             if r.master_index == mi and r.alive()),
            None,
        )
        if rep is None:
            return None
        dead_addr = master.address
        inflight_imports = [
            ij for ij in ImportJournal.in_flight(self.journal_dir)
            if ij.target == dead_addr
        ]
        with self.conn(rep) as c:
            topology.check_reply(c.execute("REPLICAOF", "NO", "ONE"))
            # in-flight import windows move WITH the promotion: the same
            # epoch re-fences, so the resumed drain's re-issues stay
            # idempotent and a stale coordinator stays fenced out
            for j in MigrationJournal.in_flight(self.journal_dir):
                planned = j.entry("PLANNED")
                if not planned or planned.get("kind") == "device_rebalance":
                    continue
                if planned["target"] == dead_addr:
                    for s in planned["slots"]:
                        topology.check_reply(c.execute(
                            "CLUSTER", "SETSLOT", int(s), "IMPORTING",
                            planned["source"], "EPOCH", j.epoch,
                        ))
            # replay the dead target's journaled batches onto the promoted
            # node BEFORE superseding the journal: the REPLPUSH cover on the
            # import ack is best-effort (a stalled shipper or unhealthy
            # replica link ships nothing and the ack still authorized the
            # source's delete), so the journal — the one durability point
            # the ack actually proved — must not be retired on an
            # assumption.  apply-by-version makes the replay a no-op for
            # every batch the replica DID receive, and the EPOCH stamp
            # re-journals the batches under the promoted node's own import
            # journal, which the resumed migration's STABLE then settles.
            for ij in inflight_imports:
                for blob in ij.batch_blobs():
                    args = ["IMPORTRECORDS", "EPOCH", ij.epoch]
                    if ij.source:
                        args += ["SOURCE", ij.source]
                    topology.check_reply(
                        c.execute(*args, blob, timeout=60.0)
                    )
        for ij in inflight_imports:
            ij.append("STABLE", superseded_by=rep.address)
        new_view = [
            (lo, hi, rep.host, rep.port, rep.node_id)
            if f"{h}:{p}" == dead_addr else (lo, hi, h, p, nid)
            for lo, hi, h, p, nid in self.current_view()
        ]
        rep.role, master.role = "master", "replica"
        self.replicas.remove(rep)
        rep.master_index = None
        self.masters[mi] = rep
        master.master_index = mi
        self.replicas.append(master)
        call_with_retry(
            self._rejoin_retry_policy(),
            lambda: topology.install_view(
                [self._conn_factory(n) for n in self.nodes() if n.alive()],
                new_view,
            ),
        )
        return rep

    def rolling_restart(
        self,
        nodes: Optional[Sequence[NodeProc]] = None,
        grace: float = 15.0,
        health_timeout: float = 60.0,
    ) -> List[Dict[str, object]]:
        """Restart/upgrade a LIVE fleet one node at a time with zero acked
        loss: per node — drain (``REPLFLUSH`` ships everything dirty to its
        replicas, ``SAVE`` pins the restart's restore point), escalating
        graceful stop, respawn on the same address, then a health barrier
        (cluster routable end to end, the restarted node answering, its
        replica links re-attached) before the roll moves on.  Replicas
        roll first so no master ever loses its last replica mid-step.
        Default order covers every node; pass ``nodes`` to roll a subset
        (e.g. masters only).  Returns one summary dict per node rolled."""
        order = (
            list(nodes) if nodes is not None
            else list(self.replicas) + list(self.masters)
        )
        rolled: List[Dict[str, object]] = []
        for node in order:
            if node.alive():
                try:
                    with self.conn(node, timeout=60.0) as c:
                        c.execute("REPLFLUSH", timeout=30.0)
                        reply = c.execute("SAVE", timeout=60.0)
                        if isinstance(reply, RespError):
                            raise reply
                except Exception:  # noqa: BLE001 — wedged node: the
                    pass           # escalating stop below still bounds us
            rc = self.stop(node, timeout=grace)
            # force: if even SIGKILL could not reap in time (rc None), the
            # retried stop inside restart() keeps the roll bounded instead
            # of silently no-opping on a still-"alive" zombie
            self.restart(node, force=True)
            self._health_barrier(node, timeout=health_timeout)
            rolled.append({
                "node": node.name, "exit_code": rc,
                "generation": node.generation,
            })
        return rolled

    def _health_barrier(self, node: NodeProc, timeout: float = 60.0) -> None:
        """One roll step's gate: the fleet routes end to end again AND the
        restarted node's replication links are re-attached (a master must
        list its live replicas — replication catch-up restarts from the
        full-sync pull ``wire_replica`` triggers) before the next node goes
        down."""
        deadline = time.monotonic() + timeout
        client = self.client(scan_interval=0.5)
        try:
            if not client.wait_routable(
                timeout=max(1.0, deadline - time.monotonic())
            ):
                raise NodeStartupError(
                    f"fleet not routable after rolling {node.name}\n"
                    + self.log_tail(node)
                )
        finally:
            client.shutdown()
        want = [
            rep for rep in self.replicas
            if node.role == "master" and rep.master_index is not None
            and self.masters[rep.master_index] is node and rep.alive()
        ]
        while want:
            try:
                with self.conn(node, timeout=10.0) as c:
                    have = {
                        topology._s(a) for a in c.execute("REPLICAS") or []
                    }
                if all(rep.address in have for rep in want):
                    return
            except Exception:  # noqa: BLE001 — node still settling
                pass
            if time.monotonic() >= deadline:
                raise NodeStartupError(
                    f"replicas never re-attached to {node.name} after roll"
                )
            time.sleep(0.1)

    # -- topology -------------------------------------------------------------

    def planned_view(self) -> List[topology.ViewRow]:
        return topology.view_tuples(
            self.slot_ranges,
            [
                (m.host, m.port, m.node_id) if m.node_id else None
                for m in self.masters
            ],
        )

    def current_view(self) -> List[topology.ViewRow]:
        """The view as the LIVE cluster knows it: asked from any live node
        that has one installed (migrations move ownership underneath the
        supervisor's original plan), falling back to the plan."""
        for node in self.nodes():
            if not node.alive():
                continue
            try:
                with self.conn(node) as c:
                    view = topology.fetch_view(c)
            except Exception:  # noqa: BLE001 — try the next node
                continue
            # a node with no installed view reports the single-node default
            # (itself owning 0..16383): not a cluster view, keep looking
            if len(view) == 1 and view[0][0] == 0 and len(self.masters) > 1 \
                    and (view[0][2], view[0][3]) == (node.host, node.port):
                continue
            if view:
                return view
        return self.planned_view()

    def install_topology(self) -> None:
        """Initial wiring: push the planned view everywhere, attach replicas
        — the same program ClusterRunner runs, through cluster/topology."""
        view = self.planned_view()
        topology.install_view(
            [self._conn_factory(n) for n in self.nodes() if n.alive()], view
        )
        for rep in self.replicas:
            master = self.masters[rep.master_index]
            if rep.alive() and master.alive():
                topology.wire_replica(
                    self._conn_factory(rep), master.host, master.port
                )

    # -- access ---------------------------------------------------------------

    def conn(self, node: NodeProc, timeout: float = 30.0):
        """Context-managed admin connection to one node (real TCP)."""
        from contextlib import closing

        return closing(Connection(
            node.host, node.port, timeout=timeout, password=self.password,
        ))

    def _conn_factory(self, node: NodeProc):
        return lambda: self.conn(node)

    def seeds(self) -> List[str]:
        return [n.address for n in self.nodes() if n.alive()]

    def client(self, **kw):
        """Slot-routed cluster client over the live processes."""
        from redisson_tpu.client.cluster import ClusterRedisson

        kw.setdefault("timeout", 60.0)
        if self.password is not None:
            kw.setdefault("password", self.password)
        return ClusterRedisson(self.seeds(), **kw)

    def scrape(self) -> str:
        """Fleet-wide Prometheus scrape (ISSUE 12): pull ``METRICS`` from
        every live node and merge the expositions with per-node
        ``node="host:port"`` labels — the supervisor half of the
        one-pane-of-glass (the ``METRICS CLUSTER`` verb is the wire half;
        both ride ``utils.metrics.merge_prometheus_texts``).  Dead or
        unreachable nodes contribute nothing rather than failing the
        scrape."""
        from redisson_tpu.utils.metrics import merge_prometheus_texts

        texts: Dict[str, str] = {}
        for node in self.nodes():
            if not node.alive():
                continue
            try:
                with self.conn(node, timeout=10.0) as c:
                    texts[node.address] = bytes(c.execute("METRICS")).decode()
            except Exception:  # noqa: BLE001 — scrape the rest of the fleet
                continue
        return merge_prometheus_texts(texts)

    def log_tail(self, node: NodeProc, max_bytes: int = 4096) -> str:
        try:
            with open(node.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"
