"""Process-level cluster plane (ISSUE 6): real ``tpu-server`` OS processes,
real TCP topology wiring, real signals.

  * :mod:`~redisson_tpu.cluster.supervisor` — :class:`ClusterSupervisor`
    (spawn / wait_ready / kill / stop / restart, per-node logs + exit codes);
  * :mod:`~redisson_tpu.cluster.topology` — the single slot-assignment +
    SETVIEW program shared with the in-process harness;
  * :mod:`~redisson_tpu.cluster.chaos` — process-chaos primitives
    (coordinator crash at a journal phase, SIGKILL-at-phase storms,
    whole-host kills);
  * :mod:`~redisson_tpu.cluster.hostdriver` — where node processes RUN
    (ISSUE 16): :class:`LocalHostDriver` (today's subprocess path),
    :class:`SshHostDriver` (remote spawn over an ssh channel),
    :class:`K8sDriver` (pod-spec codegen).
"""
from redisson_tpu.cluster.hostdriver import (  # noqa: F401
    HostDriver,
    K8sDriver,
    LocalHostDriver,
    LoopbackTransport,
    NodeHandle,
    SshHostDriver,
    SshTransport,
)
from redisson_tpu.cluster.supervisor import (  # noqa: F401
    ClusterSupervisor,
    NodeProc,
    NodeStartupError,
)
from redisson_tpu.cluster.topology import (  # noqa: F401
    PlacementDegraded,
    assign_hosts,
    split_slots,
)
