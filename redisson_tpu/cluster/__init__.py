"""Process-level cluster plane (ISSUE 6): real ``tpu-server`` OS processes,
real TCP topology wiring, real signals.

  * :mod:`~redisson_tpu.cluster.supervisor` — :class:`ClusterSupervisor`
    (spawn / wait_ready / kill / stop / restart, per-node logs + exit codes);
  * :mod:`~redisson_tpu.cluster.topology` — the single slot-assignment +
    SETVIEW program shared with the in-process harness;
  * :mod:`~redisson_tpu.cluster.chaos` — process-chaos primitives
    (coordinator crash at a journal phase, SIGKILL-at-phase storms).
"""
from redisson_tpu.cluster.supervisor import (  # noqa: F401
    ClusterSupervisor,
    NodeProc,
    NodeStartupError,
)
from redisson_tpu.cluster.topology import split_slots  # noqa: F401
