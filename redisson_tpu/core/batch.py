"""Batch: the RBatch / CommandBatchService analog — op coalescing.

Parity target: ``org/redisson/command/CommandBatchService.java:87-151,211-540``
— user queues async ops against batch-scoped object proxies, `execute()`
groups everything per shard and writes ONE pipelined frame per shard.

TPU-first: grouping is per (object, op-kind); each group concatenates its key
payloads into one packed tensor and dispatches ONE kernel, then scatters
result slices back to the queued futures.  This is the north-star interception
point (BASELINE.json): the reference amortizes network round-trips, we
amortize XLA dispatches — same boundary, hardware-appropriate batching.

Execution modes (api/BatchOptions.java parity): IN_MEMORY (default — ops are
grouped and flushed on execute) and skip_result (drop result transfer).
Atomicity mode is per-object: each group runs under its record lock.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class BatchFuture:
    """Minimal completion handle (RFuture analog, misc/CompletableFutureWrapper).

    Under the overlap plane (core/ioplane) a future may complete LAZILY:
    the dispatch happened, the result is a device-side readback future, and
    the D2H transfer runs only when get() actually demands the value (or
    when execute() drains every pending readback in one grouped transfer).
    """

    __slots__ = ("_value", "_error", "_done", "_resolve")

    def __init__(self):
        self._value = None
        self._error = None
        self._done = False
        self._resolve = None

    def _complete(self, value):
        self._value = value
        self._done = True

    def _complete_lazy(self, resolve):
        """Dispatch done; `resolve()` materializes the value on demand."""
        self._resolve = resolve
        self._done = True

    def _fail(self, err):
        self._error = err
        self._resolve = None
        self._done = True

    def done(self) -> bool:
        return self._done

    def get(self):
        if not self._done:
            raise RuntimeError("batch not executed yet")
        if self._resolve is not None:
            resolve, self._resolve = self._resolve, None
            try:
                self._value = resolve()
            except Exception as e:  # noqa: BLE001 — readback failure lands here
                self._error = e
        if self._error is not None:
            raise self._error
        return self._value


@dataclass
class _QueuedOp:
    group: Tuple  # (object name, op kind, geometry discriminator)
    payload: Any
    future: BatchFuture
    n: int  # result slice width (0 = scalar result)


class BatchResult:
    def __init__(self, responses: List[Any]):
        self.responses = responses


class Batch:
    def __init__(self, engine, skip_result: bool = False, atomic: bool = False):
        self._engine = engine
        self._ops: List[_QueuedOp] = []
        self._executed = False
        self._skip_result = skip_result
        # IN_MEMORY_ATOMIC analog: every touched record's lock is held for
        # the WHOLE execute, so no other command interleaves with the batch
        # (EXEC semantics — non-interleaved, no rollback)
        self._atomic = atomic

    # -- batch-scoped object proxies ---------------------------------------

    def get_bloom_filter(self, name: str, codec=None) -> "BatchBloom":
        return BatchBloom(self, name, codec)

    def get_bloom_filter_array(self, name: str) -> "BatchBloomArray":
        return BatchBloomArray(self, name)

    def get_hyper_log_log(self, name: str, codec=None) -> "BatchHll":
        return BatchHll(self, name, codec)

    def get_bit_set(self, name: str) -> "BatchBitSet":
        return BatchBitSet(self, name)

    def get_bucket(self, name: str, codec=None) -> "BatchBucket":
        return BatchBucket(self, name, codec)

    def get_atomic_long(self, name: str) -> "BatchAtomicLong":
        return BatchAtomicLong(self, name)

    def _enqueue(self, group: Tuple, payload, n: int) -> BatchFuture:
        if self._executed:
            raise RuntimeError("batch already executed")
        fut = BatchFuture()
        self._ops.append(_QueuedOp(group, payload, fut, n))
        return fut

    # -- execution ----------------------------------------------------------

    def execute(self) -> BatchResult:
        """Group queued ops, one fused dispatch per group, scatter results.

        Overlap plane (core/ioplane, default on): groups DISPATCH in order
        but their results stay on device as readback futures — the whole
        batch then drains in ONE grouped D2H transfer (force_all) instead of
        one blocking fetch per group, so group G+1's staging and kernel
        overlap group G's readback.  With the plane off (--no-overlap /
        set_overlap(False)) every group forces its results before the next
        dispatches — the serial A/B reference.  Results are bit-identical in
        both modes: the plane reorders host WAITS, never device work (the
        device stream is in-order and mutations apply at dispatch time)."""
        from redisson_tpu.core import ioplane

        if self._executed:
            raise RuntimeError("batch already executed")
        self._executed = True
        groups: Dict[Tuple, List[_QueuedOp]] = {}
        order: List[_QueuedOp] = []
        for op in self._ops:
            groups.setdefault(op.group, []).append(op)
            order.append(op)
        # pending device readbacks (overlap mode); None = serial dispatch
        pending: Optional[List] = [] if ioplane.overlap_enabled() else None

        def run_one(group, ops):
            try:
                fn = None if pending is None else _DISPATCH_LAZY.get(group[1])
                if fn is not None:
                    fn(self._engine, group, ops, pending)
                else:
                    _DISPATCH[group[1]](self._engine, group, ops)
            except Exception as e:  # noqa: BLE001 - failures land on futures
                for op in ops:
                    if not op.future.done():
                        op.future._fail(e)

        def run_groups():
            # groups run in first-submission order of their first op, so a
            # same-name object queued under two op kinds sees its earlier-
            # submitted group applied first (documented ordering contract).
            # The coalescing plane fuses CONSECUTIVE same-verb bloom groups
            # (different filters, one stacked-bank dispatch) and the
            # add-then-contains hot pair on one filter (one fused program) —
            # run boundaries never cross a verb change, so the ordering
            # contract is untouched; ineligible runs fall back per group.
            items = list(groups.items())
            i = 0
            while i < len(items):
                group, ops = items[i]
                verb = group[1]
                if verb in ("bloom.add", "bloom.contains"):
                    j = i + 1
                    while j < len(items) and items[j][0][1] == verb:
                        j += 1
                    if j - i >= 2 and _try_fused_run(
                        self._engine, verb, items[i:j], pending
                    ):
                        i = j
                        continue
                    if (
                        verb == "bloom.add"
                        and j == i + 1
                        and j < len(items)
                        and items[j][0][1] == "bloom.contains"
                        and items[j][0][0] == group[0]
                        and _try_fused_pair(
                            self._engine, items[i], items[j], pending
                        )
                    ):
                        i = j + 1
                        continue
                run_one(group, ops)
                i += 1

        if self._atomic:
            with self._engine.locked_many({g[0] for g in groups}):
                run_groups()
        else:
            run_groups()
        if self._skip_result:
            # results were never demanded: pending readbacks stay on device
            # (a later fut.get() still resolves them individually)
            return BatchResult([])
        if pending:
            # THE one grouped D2H transfer for the whole batch's readbacks
            ioplane.force_all(pending)
        return BatchResult([op.future.get() for op in order])


# -- cross-group coalescing (core/coalesce.py fused dispatch) ----------------

def _group_int_keys(engine, ops: List[_QueuedOp]) -> Optional[np.ndarray]:
    """One group's concatenated int keys, or None when any op carries
    codec-encoded keys (the coalescer's eligibility probe)."""
    for op in ops:
        if not engine.is_int_batch(np.asarray(op.payload)):
            return None
    return _concat_int_keys(ops)


def _assign_lazy_slices(ops: List[_QueuedOp], rb, start: int = 0,
                        summed: bool = False) -> int:
    """Complete each op's future with a lazy slice of `rb.result()` —
    demand-driven readback (overlap plane).  Returns the end offset."""
    off = start
    for op in ops:
        o, w = off, op.n
        if summed:
            op.future._complete_lazy(
                lambda o=o, w=w: int(rb.result()[o : o + w].sum())
            )
        else:
            op.future._complete_lazy(lambda o=o, w=w: rb.result()[o : o + w])
        off += w
    return off


def _try_fused_run(engine, verb: str, run, pending=None) -> bool:
    """Fuse a run of >=2 consecutive same-verb bloom groups into ONE stacked
    dispatch.  True = futures completed (or failed); False = ineligible,
    caller dispatches per group.  With `pending` (overlap plane) the run's
    result stays on device as one readback future the batch drains later."""
    from redisson_tpu.core import coalesce as CO
    from redisson_tpu.core import ioplane

    names = [group[0] for group, _ops in run]
    keys_list = []
    for _group, ops in run:
        keys = _group_int_keys(engine, ops)
        if keys is None or keys.size == 0:
            return False
        keys_list.append(keys)
    try:
        if verb == "bloom.contains":
            found, _lengths = CO.fused_bloom_contains_async(engine, names, keys_list)
            if pending is not None:
                rb = ioplane.ReadbackFuture((found,))
                pending.append(rb)
                off = 0
                for _group, ops in run:
                    off = _assign_lazy_slices(ops, rb, off)
            else:
                flat = np.asarray(found)
                off = 0
                for _group, ops in run:
                    for op in ops:
                        op.future._complete(flat[off : off + op.n])
                        off += op.n
        else:
            newly, _lengths = CO.fused_bloom_add_async(engine, names, keys_list)
            if pending is not None:
                rb = ioplane.ReadbackFuture((newly,))
                pending.append(rb)
                off = 0
                for _group, ops in run:
                    off = _assign_lazy_slices(ops, rb, off, summed=True)
            else:
                flat = np.asarray(newly)
                off = 0
                for _group, ops in run:
                    for op in ops:
                        op.future._complete(int(flat[off : off + op.n].sum()))
                        off += op.n
    except CO.CoalesceIneligible:
        return False
    except Exception as e:  # noqa: BLE001 — failures land on the run's futures
        for _group, ops in run:
            for op in ops:
                if not op.future.done():
                    op.future._fail(e)
    return True


def _try_fused_pair(engine, add_item, probe_item, pending=None) -> bool:
    """Fuse the add-then-contains hot pair on ONE filter into a single
    program (kernels.bloom_fused_add_contains): the probe group observes the
    adds, exactly as the sequential group order would."""
    from redisson_tpu.core import coalesce as CO
    from redisson_tpu.core import ioplane

    (add_group, add_ops), (probe_group, probe_ops) = add_item, probe_item
    add_keys = _group_int_keys(engine, add_ops)
    probe_keys = _group_int_keys(engine, probe_ops)
    if add_keys is None or probe_keys is None:
        return False
    if add_keys.size == 0 or probe_keys.size == 0:
        return False
    try:
        newly, n_add, found, n_probe = CO.fused_bloom_pair_async(
            engine, add_group[0], add_keys, probe_keys
        )
        if pending is not None:
            rb_add = ioplane.ReadbackFuture((newly,), lambda h: h[0][:n_add])
            rb_probe = ioplane.ReadbackFuture((found,))
            pending.extend((rb_add, rb_probe))
            _assign_lazy_slices(add_ops, rb_add, summed=True)
            _assign_lazy_slices(probe_ops, rb_probe)
        else:
            newly = np.asarray(newly)[:n_add]
            off = 0
            for op in add_ops:
                op.future._complete(int(newly[off : off + op.n].sum()))
                off += op.n
            _scatter(probe_ops, np.asarray(found))
    except CO.CoalesceIneligible:
        return False
    except Exception as e:  # noqa: BLE001
        for op in add_ops + probe_ops:
            if not op.future.done():
                op.future._fail(e)
    return True


# -- per-op-kind dispatchers -------------------------------------------------

def _concat_int_keys(ops: List[_QueuedOp]) -> np.ndarray:
    """Concatenate every op's keys into ONE preallocated buffer.

    np.concatenate over a per-op list allocates an intermediate array per op
    before the final copy; at batch fan-outs (hundreds of queued ops per
    flush) that numpy churn is measurable host overhead on the hot path, so
    the buffer is sized once from the summed key counts and filled through
    views."""
    if len(ops) == 1:
        return np.ascontiguousarray(
            np.asarray(ops[0].payload, np.int64).reshape(-1)
        )
    arrs = [np.asarray(op.payload, np.int64).reshape(-1) for op in ops]
    out = np.empty(sum(a.shape[0] for a in arrs), np.int64)
    off = 0
    for a in arrs:
        out[off : off + a.shape[0]] = a
        off += a.shape[0]
    return out


def _concat_field(ops: List[_QueuedOp], index: Optional[int], dtype) -> np.ndarray:
    """Concatenate one payload field of every op into ONE preallocated
    buffer (the _concat_int_keys discipline for tuple payloads: no per-op
    intermediate array before the final copy — at batch fan-outs that numpy
    churn is measurable host overhead on the hot path).  `index` picks the
    payload tuple element; None takes the payload itself."""
    pick = (lambda op: op.payload) if index is None else (lambda op: op.payload[index])
    if len(ops) == 1:
        return np.ascontiguousarray(np.asarray(pick(ops[0]), dtype).reshape(-1))
    arrs = [np.asarray(pick(op), dtype).reshape(-1) for op in ops]
    out = np.empty(sum(a.shape[0] for a in arrs), dtype)
    off = 0
    for a in arrs:
        out[off : off + a.shape[0]] = a
        off += a.shape[0]
    return out


def _group_keys(engine, ops: List[_QueuedOp]):
    """One group's key payloads: int batches concatenate into ONE
    preallocated buffer; codec-encoded payloads flatten to a list."""
    if all(engine.is_int_batch(np.asarray(op.payload)) for op in ops):
        return _concat_int_keys(ops)
    return [
        k
        for op in ops
        for k in (op.payload if isinstance(op.payload, list) else [op.payload])
    ]


def _key_count(keys) -> int:
    """Result-slice width of a queued key payload: scalars (incl. str/bytes,
    which have misleading __len__) contribute 1 result; sequences their
    length."""
    if isinstance(keys, (str, bytes, int, float)):
        return 1
    return len(keys) if hasattr(keys, "__len__") else 1


def _scatter(ops: List[_QueuedOp], results: np.ndarray):
    # force a single host materialization up front so every per-op slice
    # below is a VIEW of one buffer, never a per-op device fetch/copy
    results = np.asarray(results)
    off = 0
    for op in ops:
        # op.n == 0 means the op contributed no keys (empty array): complete
        # with an empty slice WITHOUT advancing the offset
        op.future._complete(results[off : off + op.n])
        off += op.n


def _bloom_contains(engine, group, ops):
    from redisson_tpu.client.objects.bloom import BloomFilter

    bf = BloomFilter(engine, group[0], group[2])
    found = bf.contains_each(_group_keys(engine, ops))
    _scatter(ops, found)


def _bloom_contains_lazy(engine, group, ops, pending):
    """Dispatch-only contains: the result bitmap stays on device; each op's
    future resolves a slice when demanded (overlap plane)."""
    from redisson_tpu.client.objects.bloom import BloomFilter
    from redisson_tpu.core import ioplane
    from redisson_tpu.core import kernels as K

    bf = BloomFilter(engine, group[0], group[2])
    found, n = bf.contains_each_async(_group_keys(engine, ops))

    def finish(host):
        arr = host[0]
        if arr.dtype == np.uint32:  # packed-bitmap fast path (u64 keys)
            return K.unpack_found(arr, n)
        return arr[:n]

    rb = ioplane.ReadbackFuture((found,), finish)
    pending.append(rb)
    _assign_lazy_slices(ops, rb)


def _bloom_add(engine, group, ops):
    from redisson_tpu.client.objects.bloom import BloomFilter

    bf = BloomFilter(engine, group[0], group[2])
    # adds complete with per-op "new element" counts; one fused kernel call
    newly, n = bf.add_each_async(_group_keys(engine, ops))
    newly = np.asarray(newly)[:n]
    off = 0
    for op in ops:
        op.future._complete(int(newly[off : off + op.n].sum()))
        off += op.n


def _bloom_add_lazy(engine, group, ops, pending):
    from redisson_tpu.client.objects.bloom import BloomFilter
    from redisson_tpu.core import ioplane

    bf = BloomFilter(engine, group[0], group[2])
    newly, n = bf.add_each_async(_group_keys(engine, ops))
    rb = ioplane.ReadbackFuture((newly,), lambda host: host[0][:n])
    pending.append(rb)
    _assign_lazy_slices(ops, rb, summed=True)


def _bloom_array_op(engine, group, ops, add: bool):
    from redisson_tpu.client.objects.bloom_array import BloomFilterArray

    arr = BloomFilterArray(engine, group[0])
    tenants = _concat_field(ops, 0, np.int32)
    keys = _concat_field(ops, 1, np.int64)
    if add:
        newly = arr.add_each(tenants, keys)
        off = 0
        for op in ops:
            op.future._complete(int(newly[off : off + op.n].sum()))
            off += op.n
    else:
        found = arr.contains(tenants, keys)
        _scatter(ops, found)


def _bloom_array_op_lazy(engine, group, ops, pending, add: bool):
    from redisson_tpu.client.objects.bloom_array import BloomFilterArray
    from redisson_tpu.core import ioplane
    from redisson_tpu.core import kernels as K

    arr = BloomFilterArray(engine, group[0])
    tenants = _concat_field(ops, 0, np.int32)
    keys = _concat_field(ops, 1, np.int64)
    if add:
        newly, n = arr.add_each_async(tenants, keys)
        rb = ioplane.ReadbackFuture((newly,), lambda host: host[0][:n])
        pending.append(rb)
        _assign_lazy_slices(ops, rb, summed=True)
    else:
        packed, n = arr.contains_async(tenants, keys)
        rb = ioplane.ReadbackFuture(
            (packed,), lambda host: K.unpack_found(host[0], n)
        )
        pending.append(rb)
        _assign_lazy_slices(ops, rb)


def _hll_add(engine, group, ops):
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog

    h = HyperLogLog(engine, group[0], group[2])
    # add_all dispatches without a host sync (the register plane is donated
    # on device); PFADD-style True is the whole reply — nothing to read back
    h.add_all(_group_keys(engine, ops))
    for op in ops:
        op.future._complete(True)


def _bitset_set(engine, group, ops):
    from redisson_tpu.client.objects.bitset import BitSet

    bs = BitSet(engine, group[0])
    idx = _concat_field(ops, 0, np.int64)
    value = group[2]
    old = bs.set_each(idx, value)
    _scatter(ops, old)


def _bitset_set_lazy(engine, group, ops, pending):
    from redisson_tpu.client.objects.bitset import BitSet
    from redisson_tpu.core import ioplane

    bs = BitSet(engine, group[0])
    old, n = bs.set_each_async(_concat_field(ops, 0, np.int64), group[2])
    rb = ioplane.ReadbackFuture((old,), lambda host: host[0][:n])
    pending.append(rb)
    _assign_lazy_slices(ops, rb)


def _bitset_get(engine, group, ops):
    from redisson_tpu.client.objects.bitset import BitSet

    bs = BitSet(engine, group[0])
    idx = _concat_field(ops, 0, np.int64)
    got = bs.get_each(idx)
    _scatter(ops, got)


def _bitset_get_lazy(engine, group, ops, pending):
    from redisson_tpu.client.objects.bitset import BitSet
    from redisson_tpu.core import ioplane

    bs = BitSet(engine, group[0])
    got, n = bs.get_each_async(_concat_field(ops, 0, np.int64))
    rb = ioplane.ReadbackFuture((got,), lambda host: host[0][:n])
    pending.append(rb)
    _assign_lazy_slices(ops, rb)


def _bucket_get(engine, group, ops):
    from redisson_tpu.client.objects.bucket import Bucket

    b = Bucket(engine, group[0], group[2])
    v = b.get()
    for op in ops:
        op.future._complete(v)


def _bucket_set(engine, group, ops):
    from redisson_tpu.client.objects.bucket import Bucket

    b = Bucket(engine, group[0], group[2])
    for op in ops:
        b.set(op.payload)
        op.future._complete(None)


def _atomic_add(engine, group, ops):
    from redisson_tpu.client.objects.bucket import AtomicLong

    a = AtomicLong(engine, group[0])
    for op in ops:
        op.future._complete(a.add_and_get(op.payload))


_DISPATCH: Dict[str, Callable] = {
    "bloom.contains": _bloom_contains,
    "bloom.add": _bloom_add,
    "bloom_array.add": lambda e, g, o: _bloom_array_op(e, g, o, True),
    "bloom_array.contains": lambda e, g, o: _bloom_array_op(e, g, o, False),
    "hll.add": _hll_add,
    "bitset.set": _bitset_set,
    "bitset.get": _bitset_get,
    "bucket.get": _bucket_get,
    "bucket.set": _bucket_set,
    "atomic.add": _atomic_add,
}

# Overlap-plane dispatchers (core/ioplane): dispatch WITHOUT forcing — the
# group's device results join the batch's pending readbacks and drain in one
# grouped transfer at execute() end.  Verbs without a lazy form (host-value
# ops: buckets, atomics, hll's constant True) use _DISPATCH in both modes.
_DISPATCH_LAZY: Dict[str, Callable] = {
    "bloom.contains": _bloom_contains_lazy,
    "bloom.add": _bloom_add_lazy,
    "bloom_array.add": lambda e, g, o, p: _bloom_array_op_lazy(e, g, o, p, True),
    "bloom_array.contains": lambda e, g, o, p: _bloom_array_op_lazy(e, g, o, p, False),
    "bitset.set": _bitset_set_lazy,
    "bitset.get": _bitset_get_lazy,
}


# -- batch-scoped proxies ----------------------------------------------------

class _BatchProxy:
    def __init__(self, batch: Batch, name: str, codec=None):
        self._batch = batch
        self._name = name
        self._codec = codec


class BatchBloom(_BatchProxy):
    def contains_async(self, keys) -> BatchFuture:
        return self._batch._enqueue(
            (self._name, "bloom.contains", self._codec), keys, _key_count(keys)
        )

    def add_async(self, keys) -> BatchFuture:
        return self._batch._enqueue(
            (self._name, "bloom.add", self._codec), keys, _key_count(keys)
        )


class BatchBloomArray(_BatchProxy):
    def contains_async(self, tenant_ids, keys) -> BatchFuture:
        return self._batch._enqueue(
            (self._name, "bloom_array.contains", None), (tenant_ids, keys), len(keys)
        )

    def add_async(self, tenant_ids, keys) -> BatchFuture:
        return self._batch._enqueue(
            (self._name, "bloom_array.add", None), (tenant_ids, keys), len(keys)
        )


class BatchHll(_BatchProxy):
    def add_all_async(self, keys) -> BatchFuture:
        return self._batch._enqueue(
            (self._name, "hll.add", self._codec), keys, _key_count(keys)
        )


class BatchBitSet(_BatchProxy):
    def set_async(self, indexes, value: bool = True) -> BatchFuture:
        idx = np.asarray(indexes)
        return self._batch._enqueue((self._name, "bitset.set", bool(value)), (idx,), idx.size)

    def get_async(self, indexes) -> BatchFuture:
        idx = np.asarray(indexes)
        return self._batch._enqueue((self._name, "bitset.get", None), (idx,), idx.size)


class BatchBucket(_BatchProxy):
    def get_async(self) -> BatchFuture:
        return self._batch._enqueue((self._name, "bucket.get", self._codec), None, 0)

    def set_async(self, value) -> BatchFuture:
        return self._batch._enqueue((self._name, "bucket.set", self._codec), value, 0)


class BatchAtomicLong(_BatchProxy):
    def add_and_get_async(self, delta: int) -> BatchFuture:
        return self._batch._enqueue((self._name, "atomic.add", None), delta, 0)
