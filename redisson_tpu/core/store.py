"""DeviceStore: the registry of named device-resident states.

Role parity: in the reference, every RObject is a *stateless handle* and all
state lives in the Redis server keyed by name (SURVEY.md §1 L5).  Here the
"server state" is a process-local registry mapping object name -> a state
record holding device arrays plus metadata (kind, logical sizes, hash/format
version).  Handles stay stateless; compound mutations run under the engine's
per-record locks (core/engine.py `locked`/`locked_many`) for Lua-equivalent
atomicity — single writer per object name.

Mutation discipline: states are replaced wholesale (functional update) by
kernels jitted with donated arguments, so XLA reuses the HBM buffer in place —
the TPU analogue of Redis mutating its dict entry.
"""
from __future__ import annotations

import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from redisson_tpu.core import residency as _res


@dataclass
class StateRecord:
    kind: str                       # "bloom" | "hll" | "bitset" | "bucket" | ...
    meta: Dict[str, Any] = field(default_factory=dict)
    arrays: Dict[str, Any] = field(default_factory=dict)  # name -> jax.Array
    host: Any = None                # host-side python state (dict/list/...)
    version: int = 0                # bumped on every mutation (optimistic cc)
    expire_at: Optional[float] = None  # epoch seconds, None = persistent
    # creation identity: versions restart at 0 when a name is deleted and
    # recreated, so replication compares (nonce, version), not version alone —
    # otherwise a recreate within one ship interval is invisible to replicas
    nonce: int = field(default_factory=lambda: secrets.randbits(63))
    # residency plane (ISSUE 20): HOT = arrays in HBM (the only state before
    # this PR), WARM = arrays released with the exact host bytes in `stash`,
    # COLD = stash spilled to the verified container at `cold_path`.  Tier
    # moves only under the record lock + the manager's transition lock;
    # version does NOT bump on a tier change (content is identical, so
    # replication/migration must not re-ship a demoted record).
    tier: str = _res.HOT
    stash: Optional[Dict[str, Any]] = None   # WARM host mirror (numpy)
    stash_dev: int = -1                      # device the arrays came off
    cold_path: Optional[str] = None          # COLD spill file
    cold_bytes: int = 0                      # spilled host bytes (census)

    def expired(self, now: Optional[float] = None) -> bool:
        return self.expire_at is not None and (now or time.time()) >= self.expire_at


class DeviceStore:
    """Thread-safe name -> StateRecord registry with TTL semantics.

    TTLs mirror RExpirable (``org/redisson/RedissonExpirable.java``): any
    object can carry an expiry; expired entries are treated as absent and
    reaped lazily on access plus periodically by the EvictionScheduler analog.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._states: Dict[str, StateRecord] = {}
        # Optional hook called whenever an ABSENT name is touched — created
        # (get_or_create / put) or read/deleted as missing (get / delete).
        # The slot-migration window installs one that ASK-redirects absent
        # names in MIGRATING slots: creations must happen on the target, and
        # a record the drain just moved must redirect rather than read as
        # nil (read-your-writes across the handoff).  This is the chokepoint
        # that makes drain-vs-access races lose no acked state
        # (server/server.py _migration_absent_guard).
        self.absent_guard: Optional[Callable[[str], None]] = None
        # Hook fired with the NAMES of expired records the store just
        # reaped (lazily on access or by reap_expired) — the client-tracking
        # plane invalidates near caches through it exactly like a DEL
        # (server/server.py wires it to TrackingTable.note_expired).
        # Contract: the callback must not reenter the store (lazy-expiry
        # sites fire while the store lock is held by reentrant callers).
        self.on_expired: Optional[Callable[[list], None]] = None
        # Device-placement hook (ISSUE 8): fired with (name, record) at
        # EVERY install chokepoint (get_or_create factory result, put,
        # put_unguarded) so a placement-enabled engine commits the record's
        # device arrays to the device owning its slot — creations, restores
        # (checkpoint.load goes through put) and migration/replication
        # imports (put_unguarded) all land on the right device through this
        # ONE seam.  None (the default) keeps today's default-device
        # behavior bit for bit.
        self.placement_hook: Optional[Callable[[str, StateRecord], None]] = None
        # residency manager (ISSUE 20): set by Engine.enable_residency —
        # the armed `_res._tier_plane` guard routes getter touches here so
        # multiple engines in one process never cross-wire.  None = the
        # store has no tiering even while the process-global plane is armed.
        self.residency = None

    def _placed(self, name: str, rec: StateRecord) -> StateRecord:
        if self.placement_hook is not None:
            try:
                self.placement_hook(name, rec)
            except Exception:  # noqa: BLE001 — placement is an optimization:
                pass           # a failed placement must never fail the write
        return rec

    def _reaped(self, name: str) -> None:
        if self.on_expired is not None:
            try:
                self.on_expired([name])
            except Exception:  # noqa: BLE001 — expiry must never fail a read
                pass

    def _get_locked(self, name: str) -> Optional[StateRecord]:
        """get() body under self._lock (callers hold it) — shared by the
        public getters so the residency fault-in below fires exactly once,
        AFTER the lock is released."""
        rec = self._states.get(name)
        if rec is not None and rec.expired():
            del self._states[name]
            rec = None
            self._reaped(name)
        if rec is None and self.absent_guard is not None:
            self.absent_guard(name)
        return rec

    def get(self, name: str) -> Optional[StateRecord]:
        with self._lock:
            rec = self._get_locked(name)
        # fault-in chokepoint (ISSUE 20): a keyed command touching a
        # WARM/COLD record promotes it back to HOT *here*, OUTSIDE the
        # store lock — promotion takes the record lock and the owner
        # lane's gate, and holding the store lock across either would
        # invert every documented lock order.  Disarmed cost: one module-
        # global load + is-None (tests/test_perf_smoke.py pins it).
        plane = _res._tier_plane
        if plane is not None and rec is not None:
            plane.on_record_access(self, name, rec)
        return rec

    def get_or_create(self, name: str, kind: str, factory: Callable[[], StateRecord]) -> StateRecord:
        with self._lock:
            # raises via absent_guard in a migration window
            rec = self._get_locked(name)
            if rec is None:
                rec = factory()
                assert rec.kind == kind
                self._states[name] = self._placed(name, rec)
            elif rec.kind != kind:
                raise TypeError(
                    f"object '{name}' holds a {rec.kind}, requested {kind} "
                    "(WRONGTYPE in the reference)"
                )
        plane = _res._tier_plane
        if plane is not None and rec is not None:
            plane.on_record_access(self, name, rec)
        return rec

    def put(self, name: str, rec: StateRecord) -> None:
        with self._lock:
            # Expired entries are semantically absent: a put() recreating an
            # expired name in a MIGRATING slot must ASK-redirect exactly like
            # get/get_or_create would (same predicate peek() uses), or the
            # recreated record can slip in behind a completed drain.
            cur = self._states.get(name)
            if (cur is None or cur.expired()) and self.absent_guard is not None:
                self.absent_guard(name)
            self._states[name] = self._placed(name, rec)

    def put_unguarded(self, name: str, rec: StateRecord) -> None:
        """Install bypassing the absent guard — ONLY for migration/replication
        transfer frames, which legitimately create records in windowed slots
        (the importing side) or overwrite during a drain."""
        with self._lock:
            self._states[name] = self._placed(name, rec)

    def delete(self, name: str) -> bool:
        with self._lock:
            existed = self._states.pop(name, None) is not None
            if not existed and self.absent_guard is not None:
                self.absent_guard(name)
            return existed

    def delete_unguarded(self, name: str) -> bool:
        """Delete bypassing the absent guard (the drain's own removal)."""
        with self._lock:
            return self._states.pop(name, None) is not None

    def exists(self, name: str) -> bool:
        return self.get(name) is not None

    def get_unguarded(self, name: str) -> Optional[StateRecord]:
        """get() without the absent guard — for transfer-frame appliers
        (replication/migration) that legitimately probe absent names."""
        with self._lock:
            rec = self._states.get(name)
            if rec is not None and rec.expired():
                del self._states[name]
                self._reaped(name)
                return None
            return rec

    def peek(self, name: str) -> bool:
        """Existence WITHOUT the absent guard — for routing decisions that
        must inspect both present and absent keys (TRYAGAIN vs ASK) and for
        the drain's own bookkeeping."""
        with self._lock:
            rec = self._states.get(name)
            return rec is not None and not rec.expired()

    def rename(self, old: str, new: str) -> bool:
        with self._lock:
            rec = self._get_locked(old)  # metadata op: no fault-in needed
            if rec is None:
                return False
            if new != old:
                self._states[new] = rec
                del self._states[old]
            return True

    def expire(self, name: str, at: Optional[float]) -> bool:
        with self._lock:
            rec = self._get_locked(name)  # metadata op: no fault-in needed
            if rec is None:
                return False
            rec.expire_at = at
            return True

    def ttl(self, name: str) -> Optional[float]:
        """Remaining TTL seconds; None if absent or persistent (pttl analog)."""
        rec = self.get(name)
        if rec is None or rec.expire_at is None:
            return None
        return max(0.0, rec.expire_at - time.time())

    def census_records(self):
        """Non-expired ``(kind, record)`` pairs in one consistent snapshot —
        the residency-ledger scan (server ``_device_bytes_census``): callers
        read each record's arrays WITHOUT the store lock, so a gauge scrape
        never serializes against the write path."""
        with self._lock:
            return [
                (r.kind, r) for r in list(self._states.values())
                if not r.expired()
            ]

    def keys(self, pattern: Optional[str] = None):
        """SCAN/KEYS analog (RedissonKeys.java:545 surface)."""
        import fnmatch

        with self._lock:
            names = [n for n, r in list(self._states.items()) if not r.expired()]
        if pattern is None or pattern == "*":
            return names
        return [n for n in names if fnmatch.fnmatchcase(n, pattern)]

    def reap_expired(self) -> int:
        now = time.time()
        reaped = []
        with self._lock:
            for name in [n_ for n_, r in self._states.items() if r.expired(now)]:
                del self._states[name]
                reaped.append(name)
        if reaped and self.on_expired is not None:
            try:
                self.on_expired(reaped)
            except Exception:  # noqa: BLE001 — sweep must survive hook bugs
                pass
        return len(reaped)

    def flushall(self) -> None:
        with self._lock:
            self._states.clear()

    def __len__(self):
        with self._lock:
            return len(self._states)
