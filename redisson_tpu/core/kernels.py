"""Jitted kernel dispatch: shape bucketing + donation + compile cache.

This is the heart of the L2' execution core (SURVEY.md §7.1-2): the reference
amortizes per-command overhead by pipelining RESP frames over one connection
(``command/CommandBatchService.java:87-151`` — one CommandsData write per
shard); the TPU equivalent amortizes XLA dispatch (~10-100us) by packing a
whole batch of ops into fixed-shape tensors and dispatching ONE compiled
kernel per (op-kind, shape-bucket).

Shape discipline: batch arrays are padded up to power-of-two buckets so the
number of distinct compiled programs is O(log max_batch) per op, never
O(#batch-sizes).  A dynamic `n_valid` scalar masks padding *inside* the kernel
(padded rows index out of range -> dropped scatters / ignored gathers), so
padding never corrupts state.

State-mutating kernels donate their state argument: XLA writes the new state
into the same HBM buffer — in-place semantics without in-place ops.

Why there is no Pallas kernel here (measured decision, 2026-07): the hot ops
are random-access bit/register probes — per-key gathers/scatters over a
plane far larger than VMEM.  Pallas on TPU has no vectorized gather (only
`pl.ds` slice-style dynamic indexing), so a hand-written probe kernel
degenerates to a scalar loop or a one-hot matmul whose one-hot operand is
O(batch x plane_rows) — both strictly worse than XLA's native gather unit.
Microbenchmarks (bank contains, 114k keys x k=7 over a (1000, 96256) plane,
v5e): XLA flat gather ~21us; blocked row-gather variants 20-30us; the whole
flush is transfer-bound (~ms), not kernel-bound.  The elementwise hash chain
fuses into the gather kernel under XLA already.  Pallas remains the right
tool for the mesh collectives' custom overlap if profiling ever shows XLA's
psum/pmax lagging (see parallel/sharded.py) — not for these probes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from redisson_tpu.ops import bittensor as bt
from redisson_tpu.ops import hll as hll_ops
from redisson_tpu.utils import hashing as H

MIN_BUCKET = 256


def pow2_bucket(n: int, minimum: int = MIN_BUCKET) -> int:
    b = minimum
    while b < n:
        b <<= 1
    return b


def bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    """Padded batch size for the transfer-bound fast paths.

    Pow2 bucketing wastes up to 2x of host->device bandwidth on padding (the
    dominant cost of a flush over a tunneled chip — measured ~230MB/s vs ~50us
    of kernel).  This uses 1/8-octave steps instead: next multiple of
    (next_pow2(n) / 8) — at most 12.5% padding, at most 8 compiled programs
    per octave in the jit cache.
    """
    if n <= minimum:
        return minimum
    step = max(minimum, (1 << (int(n - 1).bit_length())) >> 3)
    return ((n + step - 1) // step) * step


def pad_to(arr: np.ndarray, size: int, axis: int = 0) -> np.ndarray:
    """Zero-pad `arr` along `axis` up to `size`."""
    if arr.shape[axis] == size:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, size - arr.shape[axis])
    return np.pad(arr, pad)


def _valid_mask(n: int, n_valid) -> jax.Array:
    return jnp.arange(n, dtype=jnp.int32) < n_valid


import threading as _threading
from collections import OrderedDict as _OrderedDict

_N_CACHE: "_OrderedDict" = _OrderedDict()
_N_CACHE_MAX = 4096
_N_CACHE_LOCK = _threading.Lock()


def valid_n(n: int):
    """Device-resident int32 scalar for `n_valid` kernel args.

    A Python int argument costs a fresh tiny host->device upload on every
    call (~100us extra per dispatch over the tunnel); flush sizes repeat, so
    a cached device scalar turns that into a one-time cost per distinct n.
    True LRU eviction: a workload cycling through >_N_CACHE_MAX distinct
    flush sizes must not silently thrash re-uploads of its hottest sizes.
    Locked: server worker threads share this cache, and the hit-path
    move_to_end would KeyError against a concurrent eviction."""
    with _N_CACHE_LOCK:
        a = _N_CACHE.get(n)
        if a is not None:
            _N_CACHE.move_to_end(n)  # touch: keep hot sizes resident
            return a
    device_scalar = jnp.asarray(np.int32(n))  # upload outside the lock
    with _N_CACHE_LOCK:
        if len(_N_CACHE) >= _N_CACHE_MAX:
            _N_CACHE.popitem(last=False)  # evict the LEAST-recently-used
        return _N_CACHE.setdefault(n, device_scalar)


# --------------------------------------------------------------------------
# Bloom filter kernels (state = expanded bit plane; k, m static per filter
# geometry — the compile cache key).  Reference behavior being replaced:
# RedissonBloomFilter.java:105-196 (k*N SETBIT/GETBIT per RBatch flush).
# --------------------------------------------------------------------------

def _bloom_add_body(bits, lo, hi, n_valid, k: int, m: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    mask = _valid_mask(lo.shape[0], n_valid)
    # sentinel = physical plane size (m alone may land in the padding lanes,
    # which must stay zero for bit_not/length_hint to be correct)
    idx = jnp.where(mask[:, None], idx, bits.shape[0])  # out of range -> dropped
    new_bits, newly = bt.set_and_report(bits, idx)
    return new_bits, newly & mask


def _bloom_contains_body(bits, lo, hi, n_valid, k: int, m: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    return bt.contains(bits, idx) & _valid_mask(lo.shape[0], n_valid)


bloom_add_u64_masked = jax.jit(_bloom_add_body, static_argnums=(4, 5), donate_argnums=(0,))
bloom_contains_u64_masked = jax.jit(_bloom_contains_body, static_argnums=(4, 5))


@functools.partial(jax.jit, static_argnums=(4, 5), donate_argnums=(0,))
def bloom_add_bytes_masked(bits, words, nbytes, n_valid, k: int, m: int):
    h1, h2 = H.hash_packed_bytes(words, nbytes, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    mask = _valid_mask(h1.shape[0], n_valid)
    idx = jnp.where(mask[:, None], idx, bits.shape[0])
    new_bits, newly = bt.set_and_report(bits, idx)
    return new_bits, newly & mask


@functools.partial(jax.jit, static_argnums=(4, 5))
def bloom_contains_bytes_masked(bits, words, nbytes, n_valid, k: int, m: int):
    h1, h2 = H.hash_packed_bytes(words, nbytes, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    return bt.contains(bits, idx) & _valid_mask(h1.shape[0], n_valid)


# --- multi-tenant bloom bank: (T, m) bit plane, ops carry a tenant row ------
# (BASELINE config 2: 1k tenants, one kernel for a mixed 100k-op flush.)
# Indexing is flattened to 1-D (tenant*m + idx): XLA lowers flat gathers/
# scatters to the fast single-dim path, ~3x faster than 2-D (row, col)
# indexing on TPU (measured on the config-2 workload).  Flat indexes are
# int32, so banks are capped at BANK_MAX_CELLS cells — enforced at try_init
# (BloomFilterArray) — beyond which the sharded mesh kernels
# (parallel/sharded.py) are the intended path.

BANK_MAX_CELLS = 2**31 - 2048  # int32 flat-index space minus sentinel headroom

def _bloom_bank_add_body(bits2d, tenant, lo, hi, n_valid, k: int, m: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    mask = _valid_mask(lo.shape[0], n_valid)
    size = bits2d.shape[0] * bits2d.shape[1]
    flat = bits2d.reshape(-1)
    # row stride is the PHYSICAL row width: for BloomFilterArray banks it
    # equals m (rows are padded_size-aligned at init), and it makes the same
    # kernels serve the coalescing plane's stacked single-filter planes,
    # whose physical size exceeds the logical hash domain m (core/coalesce)
    g = jnp.where(mask[:, None], tenant[:, None] * bits2d.shape[1] + idx, size)
    old = flat.at[g].get(mode="fill", fill_value=1)
    newly = jnp.any(old == 0, axis=-1) & mask
    new_flat = flat.at[g.reshape(-1)].set(jnp.uint8(1), mode="drop")
    return new_flat.reshape(bits2d.shape), newly


def _bloom_bank_contains_body(bits2d, tenant, lo, hi, n_valid, k: int, m: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx = H.bloom_indexes(h1, h2, k, m, jnp)
    g = tenant[:, None] * bits2d.shape[1] + idx
    got = bits2d.reshape(-1).at[g].get(mode="fill", fill_value=1)
    return jnp.all(got != 0, axis=-1) & _valid_mask(lo.shape[0], n_valid)


bloom_bank_add_u64 = jax.jit(_bloom_bank_add_body, static_argnums=(5, 6), donate_argnums=(0,))
bloom_bank_contains_u64 = jax.jit(_bloom_bank_contains_body, static_argnums=(5, 6))


# --- packed-row variants ----------------------------------------------------
# One flush = ONE contiguous uint32 buffer (rows: tenant?, lo, hi) = ONE
# host->device transfer.  Three separate device_puts of ~0.5MB each run at
# ~1/3 the tunnel bandwidth of a single 1.5MB transfer (measured), and the
# transfer IS the cost of a flush — the kernels below are identical math to
# their unpacked forms, they only change the wire layout.


# -- hot-query staged-buffer cache -------------------------------------------
# A latency-sensitive serving loop re-probes the same hot working set (the
# bench's own "hot-set serving pattern"); re-uploading an identical query
# buffer pays the tunnel's h2d cost — 25-55ms on a degraded session — every
# flush.  Content addressing (blake2b over the raw operand bytes, ~1ms/MB)
# makes the reuse EXACT: any mutation of the caller's arrays changes the
# digest, so this is never identity-cache guesswork.  Entries hold staged
# DEVICE buffers; kernels never donate their query operand, so a cached
# buffer survives any number of dispatches.
import hashlib as _hashlib

_QCACHE: "_OrderedDict[bytes, object]" = _OrderedDict()
_QCACHE_SLOTS = 8
_QCACHE_MAX_BYTES = 8 << 20  # don't pin giant one-off uploads in HBM
_QCACHE_LOCK = _threading.Lock()


def query_digest(*arrays, extra: bytes = b"") -> bytes:
    h = _hashlib.blake2b(digest_size=16)
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(memoryview(a).cast("B"))
    h.update(extra)
    return h.digest()


def query_cache_get(digest: bytes):
    with _QCACHE_LOCK:
        buf = _QCACHE.pop(digest, None)
        if buf is not None:
            _QCACHE[digest] = buf  # LRU refresh
        return buf


def query_cache_put(digest: bytes, buf) -> None:
    nbytes = getattr(buf, "nbytes", _QCACHE_MAX_BYTES + 1)
    if nbytes > _QCACHE_MAX_BYTES:
        return
    with _QCACHE_LOCK:
        _QCACHE[digest] = buf
        while len(_QCACHE) > _QCACHE_SLOTS:
            _QCACHE.popitem(last=False)


def cached_staged(build, *digest_arrays, extra: bytes = b""):
    """THE one expression of the hot-query policy: content-digest the raw
    operands, reuse the staged device buffer on a hit, else build+stage+
    cache.  `build()` runs only on a miss, so hits skip the pack AND the
    h2d upload.  Callers gate this to READ paths — caching one-shot write
    flushes would evict the hot working set for zero hits."""
    digest = query_digest(*digest_arrays, extra=extra)
    buf = query_cache_get(digest)
    if buf is None:
        buf = build()
        query_cache_put(digest, buf)
    return buf


def stage(arr):
    """Asynchronous host->device staging for kernel operands.

    Passing a raw numpy array into a jitted call makes the dispatch BLOCK on
    a synchronous transfer — a full tunnel round trip (~tens of ms) per
    flush.  An explicit device_put is asynchronous: it returns immediately
    and the upload overlaps with in-flight compute, so pipelined flushes
    actually pipeline.  Measured on the tunneled v5e (100k-key contains
    flushes, 50 pipelined): 2.4s with raw numpy operands -> 0.9s staged."""
    return jax.device_put(arr)


def pack_rows(*arrays, size: int, pool=None):
    """Stack 1-D arrays into one (R, size) uint32 transfer buffer, staged
    to the device asynchronously (see stage()) — ONE contiguous upload per
    flush instead of R small ones, and the dispatch never blocks on it.

    `pool` (core/ioplane.StagingPool) fills a double-buffered reusable host
    slot instead of a fresh allocation: refilling the next flush's buffer
    overlaps this one's in-flight upload (the overlap plane's H2D half).
    Callers pass a pool only where reuse is safe (Engine.staging_pool gates
    on the backend's copy semantics)."""
    shape = (len(arrays), size)
    if pool is None:
        out, slot = np.zeros(shape, np.uint32), None
    else:
        out, slot = pool.acquire(shape, np.uint32)
    try:
        for i, a in enumerate(arrays):
            out[i, : a.shape[0]] = a.view(np.uint32) if a.dtype == np.int32 else a
        staged = stage(out)
    except BaseException:
        if pool is not None:
            pool.release(slot)  # a leaked-busy slot would silently disable
        raise                   # the double-buffer for the pool's lifetime
    return staged if pool is None else pool.commit(slot, staged)


def _unpack_tlh(tlh):
    return tlh[0].astype(jnp.int32), tlh[1], tlh[2]


def _bloom_bank_add_packed(bits2d, tlh, n_valid, k: int, m: int):
    tenant, lo, hi = _unpack_tlh(tlh)
    return _bloom_bank_add_body(bits2d, tenant, lo, hi, n_valid, k, m)


bloom_bank_add_packed = jax.jit(
    _bloom_bank_add_packed, static_argnums=(3, 4), donate_argnums=(0,)
)


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def bloom_bank_add_packed_count(bits2d, tlh, n_valid, k: int, m: int):
    """Add variant returning only the newly-added COUNT — a 4-byte device
    scalar instead of a B-byte bool plane on the result path."""
    bits, newly = _bloom_bank_add_packed(bits2d, tlh, n_valid, k, m)
    return bits, jnp.sum(newly.astype(jnp.int32))


def _pack_bool_u32(found):
    """Device side: bool[B] -> uint32[B/32] little-bit-order bitmap.  The
    result path of a contains flush is B bool bytes otherwise — on a tunneled
    chip small d2h transfers cost ~20ms each, so results travel as bitmaps
    (64x fewer bytes) and unpack host-side (unpack_found)."""
    w = found.reshape(-1, 32).astype(jnp.uint32)
    return (w << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(axis=1, dtype=jnp.uint32)


def unpack_found(packed, n: int) -> np.ndarray:
    """Host side: uint32 bitmap (from _pack_bool_u32) -> bool[n]."""
    b = np.unpackbits(np.ascontiguousarray(packed).view(np.uint8), bitorder="little")
    return b[:n].astype(bool)


def _bloom_bank_contains_impl(bits2d, tlh, n_valid, k: int, m: int):
    tenant, lo, hi = _unpack_tlh(tlh)
    return _bloom_bank_contains_body(bits2d, tenant, lo, hi, n_valid, k, m)


bloom_bank_contains_packed = jax.jit(_bloom_bank_contains_impl, static_argnums=(3, 4))


@functools.partial(jax.jit, static_argnums=(3, 4))
def bloom_bank_contains_packed_bits(bits2d, tlh, n_valid, k: int, m: int):
    return _pack_bool_u32(_bloom_bank_contains_impl(bits2d, tlh, n_valid, k, m))


@jax.jit
def window_from_unique(uniq, idx):
    """Compose a flush window on DEVICE from its unique flushes.

    uniq: (U, 3, Bb) packed unique flushes; idx: (R,) int32 mapping window
    position -> unique slot.  Returns (3, R*Bb) laid out exactly like a
    host-packed window (flush i occupies [i*Bb, (i+1)*Bb) of each row).

    Pipelined workloads re-submit the same flush buffers (hot query sets,
    re-validation sweeps); re-uploading R identical 1.4MB operands is pure
    tunnel waste AND triggers the tunnel's h2d decay mode, while an HBM-side
    take of the same bytes is effectively free.  The dedupe is by object
    identity in _pack_flush_window — exact, zero hashing cost."""
    w = jnp.take(uniq, idx, axis=0)  # (R, 3, Bb)
    return jnp.swapaxes(w, 0, 1).reshape(3, -1)


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def bloom_bank_add_packed_bits(bits2d, tlh, n_valid, k: int, m: int):
    """Add variant returning the newly-added flags as a uint32 bitmap — the
    multi-flush (window) result path, where B bool bytes per entry would
    dominate d2h the same way they do for contains."""
    bits, newly = _bloom_bank_add_packed(bits2d, tlh, n_valid, k, m)
    return bits, _pack_bool_u32(newly)


def _bloom_add_packed(bits, lh, n_valid, k: int, m: int):
    return _bloom_add_body(bits, lh[0], lh[1], n_valid, k, m)


bloom_add_packed = jax.jit(_bloom_add_packed, static_argnums=(3, 4), donate_argnums=(0,))


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def bloom_add_packed_count(bits, lh, n_valid, k: int, m: int):
    new_bits, newly = _bloom_add_packed(bits, lh, n_valid, k, m)
    return new_bits, jnp.sum(newly.astype(jnp.int32))


def _bloom_contains_impl(bits, lh, n_valid, k: int, m: int):
    return _bloom_contains_body(bits, lh[0], lh[1], n_valid, k, m)


bloom_contains_packed = jax.jit(_bloom_contains_impl, static_argnums=(3, 4))


@functools.partial(jax.jit, static_argnums=(3, 4))
def bloom_contains_packed_bits(bits, lh, n_valid, k: int, m: int):
    return _pack_bool_u32(_bloom_contains_impl(bits, lh, n_valid, k, m))


# --- fused multi-verb hot pair ----------------------------------------------
# The bloom serving loop's hottest verb PAIR is add-then-probe on one filter
# (ingest acks + read-your-writes probes in the same pipeline window).  Run
# unfused that is two dispatches and an extra full-plane donation round trip
# through the jit boundary; fused it is ONE program — XLA keeps the bit plane
# resident in HBM between the scatter and the gather, and the probe sees the
# adds (submission order: the add group precedes the contains group, the
# same order the reference preserves inside a CommandsData frame).

def _bloom_fused_add_contains_body(bits, add_lh, n_add, probe_lh, n_probe,
                                   k: int, m: int):
    bits, newly = _bloom_add_body(bits, add_lh[0], add_lh[1], n_add, k, m)
    found = _bloom_contains_body(bits, probe_lh[0], probe_lh[1], n_probe, k, m)
    return bits, newly, found


bloom_fused_add_contains = jax.jit(
    _bloom_fused_add_contains_body, static_argnums=(5, 6), donate_argnums=(0,)
)


@functools.partial(jax.jit, static_argnums=(5, 6), donate_argnums=(0,))
def bloom_fused_add_contains_bits(bits, add_lh, n_add, probe_lh, n_probe,
                                  k: int, m: int):
    """Fused pair with bitmap result paths (the wire/window d2h discipline)."""
    bits, newly, found = _bloom_fused_add_contains_body(
        bits, add_lh, n_add, probe_lh, n_probe, k, m
    )
    return bits, _pack_bool_u32(newly), _pack_bool_u32(found)


# --------------------------------------------------------------------------
# HLL kernels (replaces server-side PFADD/PFMERGE/PFCOUNT,
# RedissonHyperLogLog.java:71-102).
# --------------------------------------------------------------------------

def _hll_add_body(regs, lo, hi, n_valid, p: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx, rho = hll_ops.idx_rho(h1, h2, p)
    idx = jnp.where(_valid_mask(lo.shape[0], n_valid), idx, regs.shape[-1])
    return hll_ops.add(regs, idx, rho)


def _hll_bank_add_body(regs2d, tenant, lo, hi, n_valid, p: int):
    h1, h2 = H.hash_u64_pair(lo, hi, jnp)
    idx, rho = hll_ops.idx_rho(h1, h2, p)
    m = regs2d.shape[1]
    size = regs2d.shape[0] * m
    mask = _valid_mask(lo.shape[0], n_valid)
    g = jnp.where(mask, tenant * m + idx, size)  # flat fast path (see bloom bank)
    new_flat = regs2d.reshape(-1).at[g].max(rho, mode="drop")
    return new_flat.reshape(regs2d.shape)


hll_add_u64 = jax.jit(_hll_add_body, static_argnums=(4,), donate_argnums=(0,))
hll_bank_add_u64 = jax.jit(_hll_bank_add_body, static_argnums=(5,), donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0,))
def hll_bank_merge_map(regs2d, src_map):
    """Batched pairwise PFMERGE as ONE dense gather + elementwise max:
    new[r] = max(old[r], old[src_map[r]]), src_map[r] = r for untouched
    rows.  A row-scatter-max (`.at[dst].max(rows[src])`) lowers to a slow
    serialized scatter on TPU; the dense-map form is a row gather + vmax —
    pure HBM-bandwidth, fused by XLA (~3 passes over the bank regardless of
    pair count).  Callers pre-build the (P,)-map host-side and split
    duplicate-dst pair lists into unique-dst rounds (hll_array.merge_rows),
    the PFMERGE role of RedissonHyperLogLog.java:71-102."""
    return jnp.maximum(regs2d, regs2d[src_map])


@functools.partial(jax.jit, donate_argnums=(0,))
def hll_bank_merge_map_from(regs2d, src_bank, src_map):
    """Round >= 2 of a duplicate-dst merge: sources gather from
    `src_bank` — the PRE-CALL snapshot — never from the partially merged
    `regs2d`, so every round folds in exactly the requested sources (a
    dst updated in round 1 must not leak ITS new sources into a later
    round's dst — scatter-max read-all-sources-from-old semantics)."""
    return jnp.maximum(regs2d, src_bank[src_map])


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def hll_bank_add_packed(regs2d, tlh, n_valid, p: int):
    tenant, lo, hi = _unpack_tlh(tlh)
    return _hll_bank_add_body(regs2d, tenant, lo, hi, n_valid, p)


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def hll_add_packed(regs, lh, n_valid, p: int):
    return _hll_add_body(regs, lh[0], lh[1], n_valid, p)


@functools.partial(jax.jit, static_argnums=(4,), donate_argnums=(0,))
def hll_add_bytes(regs, words, nbytes, n_valid, p: int):
    h1, h2 = H.hash_packed_bytes(words, nbytes, jnp)
    idx, rho = hll_ops.idx_rho(h1, h2, p)
    idx = jnp.where(_valid_mask(h1.shape[0], n_valid), idx, regs.shape[-1])
    return hll_ops.add(regs, idx, rho)


hll_merge = jax.jit(hll_ops.merge, donate_argnums=(0,))
hll_estimate = jax.jit(hll_ops.estimate)
hll_estimate_union = jax.jit(hll_ops.estimate_union)


@jax.jit
def hll_bank_estimate_union_pairs(regs2d, a, b):
    return hll_ops.estimate(jnp.maximum(regs2d[a], regs2d[b]))


# --------------------------------------------------------------------------
# BitSet kernels (RedissonBitSet.java surface).
# --------------------------------------------------------------------------

@functools.partial(jax.jit, donate_argnums=(0,))
def bitset_set(bits, idx, n_valid, value):
    mask = _valid_mask(idx.shape[0], n_valid)
    safe = jnp.where(mask, idx, bits.shape[0])
    old = bits.at[safe].get(mode="fill", fill_value=0)
    return bits.at[safe].set(value.astype(jnp.uint8), mode="drop"), old & mask.astype(jnp.uint8)


@jax.jit
def bitset_get(bits, idx):
    return bt.get_bits(bits, idx)


bitset_popcount = jax.jit(bt.popcount, static_argnums=(1,))
bitset_and = jax.jit(bt.bit_and, donate_argnums=(0,))
bitset_or = jax.jit(bt.bit_or, donate_argnums=(0,))
bitset_xor = jax.jit(bt.bit_xor, donate_argnums=(0,))
bitset_not = jax.jit(bt.bit_not, static_argnums=(1,), donate_argnums=(0,))
bitset_bitpos = jax.jit(bt.bitpos, static_argnums=(1, 2))
bitset_length = jax.jit(bt.length_hint)


# --------------------------------------------------------------------------
# Text word-count kernels (MapReduce device path, SURVEY.md §3.5 / §7.3-6).
#
# The reference word-count iterates entries in a mapper and writes one
# multimap entry per emit (mapreduce/Collector.java:56-73, MapperTask.java:
# 50-78).  The TPU path tokenizes + hashes + shuffles + reduces the WHOLE
# text in two compiled programs:
#   1. wc_extract_words: per-byte polynomial hashing via cumsum scans, then
#      per-word (hash_a, hash_b, start) read out by GATHERS at word-end
#      positions (the host supplies word ends from one vectorized C pass).
#   2. wc_sort_runs: lexicographic sort of the 64-bit word hashes (TPU sorts
#      are fast) + run-boundary compaction via a second sort — counts come
#      out as diffs of run-start positions, NO scatters.
#
# Measured design history (2026-07, tunneled v5e, 1M docs / 8M words):
#   * Python threads (r2): 6.6s — GIL-serialized, "64 mappers" was fiction.
#   * Host C single-pass (str.split + Counter): 1.5-2.6s — the 1-core bound.
#   * Per-byte scatter kernel (6 table scatters over 42M bytes): 5.4s —
#     TPU scatter costs ~21ms per 1M updates; scatters CANNOT carry this.
#   * Dual-table count sketch (IBLT peeling, 4 scatters over 10.8M words):
#     ~1.9s — better, still scatter-bound.
#   * This sort-based pipeline: sorts + scans + gathers only.
# Hash identity: words are keyed by a 64-bit (2x u32) polynomial hash of
# byte+1 values with position weights p^min(pos,63) plus a length term —
# words longer than 63 bytes that share a 63-byte prefix, length, AND the
# sum of remaining bytes collide (documented bound; astronomically unlikely
# for natural tokens).
# --------------------------------------------------------------------------

_WC_POW = 64


def _wc_pow_table(p: int) -> np.ndarray:
    out = np.zeros(_WC_POW, np.uint32)
    v = 1
    for i in range(_WC_POW):
        out[i] = v
        v = (v * p) & 0xFFFFFFFF
    return out


_WC_POW_A = _wc_pow_table(0x01000193)  # FNV-32 prime
_WC_POW_B = _wc_pow_table(40503)


@jax.jit
def wc_extract_words(buf, end_deltas, n_words, base):
    """buf: (N,) uint8 text, whitespace normalized to 0x20, ws-padded.
    end_deltas: (E,) uint16 DELTA-encoded word-end positions (ends =
    cumsum(deltas) - 1; zero padding past n_words) — u16 halves the
    per-word upload vs raw i32 indexes, and the upload is what bounds this
    path on a tunneled chip (~95MB/s effective during a compute flush).
    n_words: int32 scalar count of real words.
    base: uint32 global offset of this chunk inside the full text.
    Returns per-word (hash_a, hash_b, global_start) uint32 arrays; padding
    rows carry hash 0xFFFFFFFF so they sort after every real word."""
    n = buf.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    ws = buf == 32
    last_ws = jax.lax.cummax(jnp.where(ws, idx, jnp.int32(-1)))
    pos = idx - last_ws - 1
    cap = jnp.minimum(pos, _WC_POW - 1)
    b1 = buf.astype(jnp.uint32) + 1
    ca = jnp.where(ws, jnp.uint32(0), b1 * jnp.asarray(_WC_POW_A)[cap])
    cb = jnp.where(ws, jnp.uint32(0), b1 * jnp.asarray(_WC_POW_B)[cap])
    cum_a = jnp.cumsum(ca)  # u32 wraparound == polynomial sum mod 2^32
    cum_b = jnp.cumsum(cb)
    ends = jnp.cumsum(end_deltas.astype(jnp.int32)) - 1
    valid = jnp.arange(end_deltas.shape[0], dtype=jnp.int32) < n_words
    e = jnp.where(valid, jnp.minimum(ends, n - 1), 0)
    lw = last_ws[e]
    ha = cum_a[e] - jnp.where(lw >= 0, cum_a[jnp.maximum(lw, 0)], 0)
    hb = cum_b[e] - jnp.where(lw >= 0, cum_b[jnp.maximum(lw, 0)], 0)
    ln = (e - lw).astype(jnp.uint32)
    ha = ha ^ (ln * jnp.uint32(2654435761))
    hb = hb + (ln * jnp.uint32(0x9E3779B9))
    sentinel = jnp.uint32(0xFFFFFFFF)
    ha = jnp.where(valid, ha, sentinel)
    hb = jnp.where(valid, hb, sentinel)
    start = jnp.where(valid, (lw + 1).astype(jnp.uint32) + base, sentinel)
    return ha, hb, start


# --------------------------------------------------------------------------
# Vector-search kernels (FT VECTOR / KNN, ISSUE 11).
#
# FLAT (exact) KNN is a dense score matrix + a top-k: queries (Q, d) against
# a bank (C, d) is ONE (Q, d) x (d, C) matmul — the MXU's native shape — and
# jax.lax.top_k over the masked score rows.  Same no-Pallas rationale as the
# probe kernels above: XLA already lowers dot_general to the systolic array
# and top_k to the tuned sort unit; a hand kernel could only re-derive them.
#
# Distance conventions (lower = better, the RediSearch FLAT shapes):
#   L2     — squared euclidean ||q - b||^2 (expanded form so the matmul
#            carries the whole cross term)
#   COSINE — 1 - cos(q, b)  (zero-norm rows score distance 1: orthogonal)
#   IP     — 1 - <q, b>
# Rows at index >= n_rows (padding / unfilled capacity) and rows whose
# `bias` is +inf (deleted docs, prefilter exclusions) never reach the top-k:
# bias adds into the distance row before selection, so a hybrid query's
# host-built mask is just an additive bias operand — no second kernel.
# Ties break toward the LOWER row index (lax.top_k is stable), which the
# NumPy fallback (services/vector.py) mirrors with a stable argsort: the
# armed and disarmed paths return identical orderings.
# --------------------------------------------------------------------------


def _knn_distances(bank, bias, q, n_rows, metric: str):
    dots = jnp.dot(q, bank.T, preferred_element_type=jnp.float32)  # (Q, C)
    if metric == "L2":
        q_sq = jnp.sum(q * q, axis=1, dtype=jnp.float32)
        b_sq = jnp.sum(bank * bank, axis=1, dtype=jnp.float32)
        dist = q_sq[:, None] - 2.0 * dots + b_sq[None, :]
    elif metric == "COSINE":
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, dtype=jnp.float32))
        bn = jnp.sqrt(jnp.sum(bank * bank, axis=1, dtype=jnp.float32))
        denom = qn[:, None] * bn[None, :]
        dist = 1.0 - jnp.where(denom > 0.0, dots / denom, 0.0)
    elif metric == "IP":
        dist = 1.0 - dots
    else:  # pragma: no cover — metric validated at FT.CREATE
        raise ValueError(f"unknown metric {metric!r}")
    dist = dist + bias[None, :]
    live = jnp.arange(bank.shape[0], dtype=jnp.int32) < n_rows
    return jnp.where(live[None, :], dist, jnp.inf)


def _bank_f32(bank, scale):
    """Decompress-in-kernel seam (ISSUE 14): quantized banks (FLOAT16, or
    INT8 + symmetric per-row scale) widen to float32 INSIDE the scoring
    program, so the MXU still sees one fused matmul and the decompressed
    plane never round-trips HBM as a separate buffer.  The trace
    specializes on the bank dtype — float32 banks pay nothing."""
    if bank.dtype == jnp.float32:
        return bank
    rows = bank.astype(jnp.float32)
    if scale is not None:
        rows = rows * scale[..., None]
    return rows


def _knn_topk_body(bank, scale, bias, q, n_rows, k: int, metric: str):
    dist = _knn_distances(_bank_f32(bank, scale), bias, q, n_rows, metric)
    neg, idx = jax.lax.top_k(-dist, k)
    return -neg, idx.astype(jnp.int32)


def _knn_topk_masked_body(bank, scale, bias, qbias, q, n_rows, k: int,
                          metric: str):
    """Hybrid prefilter: per-query additive bias (Q, C) — 0 keeps a row,
    +inf drops it (the planner's host mask lowered onto the score matrix)."""
    dist = (
        _knn_distances(_bank_f32(bank, scale), bias, q, n_rows, metric)
        + qbias
    )
    neg, idx = jax.lax.top_k(-dist, k)
    return -neg, idx.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(4, 5))
def knn_topk(bank, bias, q, n_rows, k: int, metric: str):
    return _knn_topk_body(bank, None, bias, q, n_rows, k, metric)


@functools.partial(jax.jit, static_argnums=(5, 6))
def knn_topk_q(bank, scale, bias, q, n_rows, k: int, metric: str):
    """INT8 banks: per-row symmetric scale dequantizes inside the kernel."""
    return _knn_topk_body(bank, scale, bias, q, n_rows, k, metric)


@functools.partial(jax.jit, static_argnums=(5, 6))
def knn_topk_masked(bank, bias, qbias, q, n_rows, k: int, metric: str):
    return _knn_topk_masked_body(bank, None, bias, qbias, q, n_rows, k,
                                 metric)


@functools.partial(jax.jit, static_argnums=(6, 7))
def knn_topk_masked_q(bank, scale, bias, qbias, q, n_rows, k: int,
                      metric: str):
    return _knn_topk_masked_body(bank, scale, bias, qbias, q, n_rows, k,
                                 metric)


# -- IVF (inverted-file) KNN: sub-linear scoring (ISSUE 14) -------------------
#
# A coarse k-means quantizer routes each query through ONE small
# (Q, d) x (d, nlist) matmul; only the rows of the top-`nprobe` cells are
# then gathered and scored, so candidate work is O(nprobe * cell_cap) per
# query instead of O(N).  The per-cell row lists arrive as a CSR-style
# device index with a UNIFORM stride (`cells`: (nlist, cell_cap) int32,
# ragged rows padded with an out-of-range sentinel) — uniform stride keeps
# the candidate gather ONE fixed-shape XLA gather; the recall gate
# (config7 floors) keeps the approximation honest.  Ties break toward the
# earlier candidate position (probe order, then cell position), which the
# NumPy fallback in services/vector.py mirrors with a stable argsort.

# (padded `cells` entries carry services/vector._IVF_SENTINEL — any value
# >= n_rows works here, validity is the `cand < n_rows` mask below)


def _ivf_candidate_dists(rows_f32, q, metric: str):
    """Distances of gathered candidate rows (Q, M, W) against their own
    query (Q, W) — the _knn_distances conventions, batched per query."""
    dots = jnp.einsum(
        "qmw,qw->qm", rows_f32, q, preferred_element_type=jnp.float32
    )
    if metric == "L2":
        q_sq = jnp.sum(q * q, axis=1, dtype=jnp.float32)
        r_sq = jnp.sum(rows_f32 * rows_f32, axis=2, dtype=jnp.float32)
        return q_sq[:, None] - 2.0 * dots + r_sq
    if metric == "COSINE":
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, dtype=jnp.float32))
        rn = jnp.sqrt(jnp.sum(rows_f32 * rows_f32, axis=2, dtype=jnp.float32))
        denom = qn[:, None] * rn
        return 1.0 - jnp.where(denom > 0.0, dots / denom, 0.0)
    if metric == "IP":
        return 1.0 - dots
    raise ValueError(f"unknown metric {metric!r}")  # pragma: no cover


def _ivf_route(centroids, q, nprobe: int, metric: str):
    """Top-`nprobe` coarse cells per query: ONE (Q, d) x (d, nlist) matmul
    + top_k — the sub-linear plane's whole routing cost."""
    cdots = jnp.dot(q, centroids.T, preferred_element_type=jnp.float32)
    if metric == "L2":
        cd = (
            jnp.sum(q * q, axis=1, dtype=jnp.float32)[:, None]
            - 2.0 * cdots
            + jnp.sum(centroids * centroids, axis=1,
                      dtype=jnp.float32)[None, :]
        )
    elif metric == "COSINE":
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, dtype=jnp.float32))
        cn = jnp.sqrt(jnp.sum(centroids * centroids, axis=1,
                              dtype=jnp.float32))
        denom = qn[:, None] * cn[None, :]
        cd = 1.0 - jnp.where(denom > 0.0, cdots / denom, 0.0)
    else:  # IP
        cd = 1.0 - cdots
    _neg, probe = jax.lax.top_k(-cd, nprobe)
    return probe  # (Q, nprobe) cell ids


def _knn_ivf_body(bank, scale, bias, qmask, centroids, cells, q, n_rows,
                  k: int, nprobe: int, metric: str):
    probe = _ivf_route(centroids, q, nprobe, metric)
    cand = cells[probe].reshape(q.shape[0], -1)   # (Q, nprobe*cap) rowids
    valid = cand < n_rows                         # sentinel + padding out
    safe = jnp.where(valid, cand, 0)
    rows = _bank_f32(bank[safe], None if scale is None else scale[safe])
    dist = _ivf_candidate_dists(rows, q, metric) + bias[safe]
    if qmask is not None:  # hybrid prefilter: (C,) additive 0/+inf plane
        dist = dist + qmask[safe]
    dist = jnp.where(valid, dist, jnp.inf)
    neg, pos = jax.lax.top_k(-dist, k)
    idx = jnp.take_along_axis(cand, pos, axis=1)  # +inf rows carry garbage
    return -neg, idx.astype(jnp.int32)            # ids; callers drop them


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def knn_ivf_topk(bank, bias, centroids, cells, q, n_rows, k: int,
                 nprobe: int, metric: str):
    return _knn_ivf_body(bank, None, bias, None, centroids, cells, q,
                         n_rows, k, nprobe, metric)


@functools.partial(jax.jit, static_argnums=(7, 8, 9))
def knn_ivf_topk_q(bank, scale, bias, centroids, cells, q, n_rows, k: int,
                   nprobe: int, metric: str):
    return _knn_ivf_body(bank, scale, bias, None, centroids, cells, q,
                         n_rows, k, nprobe, metric)


@functools.partial(jax.jit, static_argnums=(7, 8, 9))
def knn_ivf_topk_masked(bank, bias, qmask, centroids, cells, q, n_rows,
                        k: int, nprobe: int, metric: str):
    return _knn_ivf_body(bank, None, bias, qmask, centroids, cells, q,
                         n_rows, k, nprobe, metric)


@functools.partial(jax.jit, static_argnums=(8, 9, 10))
def knn_ivf_topk_masked_q(bank, scale, bias, qmask, centroids, cells, q,
                          n_rows, k: int, nprobe: int, metric: str):
    return _knn_ivf_body(bank, scale, bias, qmask, centroids, cells, q,
                         n_rows, k, nprobe, metric)


# -- mesh-sharded KNN merge (ISSUE 15) ----------------------------------------
#
# Row-parallel banks (services/vector.ShardedEmbeddingBank) reuse the whole
# knn_topk / knn_ivf_topk family above AS the per-shard variants — each shard
# is a full bank on its own device, so the per-shard leg is literally the
# single-device program.  What sharding adds is the REDUCE: every shard's
# (Q, k_s) local top-k d2d-colocates onto one device and this kernel picks
# the global top-k as concat + lax.top_k — the FAISS shard-then-merge shape
# on the repo's psum/merge discipline (never a host gather).  Ties break
# toward the earlier concatenated position: lower shard id first, then the
# shard's own tie order — which the NumPy fallback mirrors with a stable
# argsort over the identical concat layout.


def knn_sharded_merge(dists, idxs, shard_of_pos, k: int):
    """dists/idxs: tuples of per-shard (Q, k_s) top-k outputs (all on ONE
    device by the time this runs); shard_of_pos: (sum k_s,) int32 mapping a
    concat position to its shard id (static per constellation, staged
    once).  Returns (dist (Q, k), shard (Q, k), local_idx (Q, k)) — the
    host decodes (shard, local) back to global rowids off the readback
    path (resolve_hits), so no global-id plane ever ships to the device.

    Deliberately NOT jitted here: the serving jit instances are minted per
    mesh geometry by MeshManager.knn_merge_kernel, whose cross-epoch warm
    pool is what makes a 4->8->4 reshard land back on the already-built
    program — a module-level jit would be a second, unpooled compile path."""
    dist_cat = jnp.concatenate(list(dists), axis=1)
    idx_cat = jnp.concatenate(list(idxs), axis=1)
    neg, pos = jax.lax.top_k(-dist_cat, k)
    sid = shard_of_pos[pos]
    lidx = jnp.take_along_axis(idx_cat, pos, axis=1)
    return -neg, sid.astype(jnp.int32), lidx.astype(jnp.int32)


@jax.jit
def kmeans_step(points, weights, centroids):
    """One Lloyd iteration over the host mirror staged once per training
    run: L2 assignment (the classic IVF coarse quantizer, whatever the
    field's query metric) + weighted mean update.  `weights` zeroes dead
    rows out of both the assignment result (-1) and the centroid update;
    empty cells keep their previous centroid.  Returns
    (new_centroids f32 (L, W), assign int32 (N,))."""
    d = (
        jnp.sum(points * points, axis=1, dtype=jnp.float32)[:, None]
        - 2.0 * jnp.dot(points, centroids.T,
                        preferred_element_type=jnp.float32)
        + jnp.sum(centroids * centroids, axis=1, dtype=jnp.float32)[None, :]
    )
    assign = jnp.argmin(d, axis=1).astype(jnp.int32)
    sums = jnp.zeros_like(centroids).at[assign].add(
        points * weights[:, None]
    )
    counts = jnp.zeros((centroids.shape[0],), jnp.float32).at[assign].add(
        weights
    )
    new_c = jnp.where(
        counts[:, None] > 0.0,
        sums / jnp.maximum(counts, 1.0)[:, None],
        centroids,
    )
    return new_c, jnp.where(weights > 0.0, assign, jnp.int32(-1))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def rowbank_write_packed(bank, bias, packed, n_valid):
    """Block-append/overwrite rows of a (C, W) f32 device bank from ONE
    packed uint32 transfer buffer — the embedding/numeric ingest path's
    single H2D per flush (ISSUE 11; the pack_rows bandwidth discipline).

    packed: (P, W+2) uint32 — col 0 = row index, col 1 = the row's new bias
    bits (f32: 0.0 live, +inf dead), cols 2.. = the row data bitcast to
    uint32.  Rows past n_valid scatter out of range (dropped)."""
    idx = packed[:, 0].astype(jnp.int32)
    newbias = jax.lax.bitcast_convert_type(packed[:, 1], jnp.float32)
    rows = jax.lax.bitcast_convert_type(packed[:, 2:], jnp.float32)
    mask = _valid_mask(packed.shape[0], n_valid)
    safe = jnp.where(mask, idx, bank.shape[0])
    return (
        bank.at[safe].set(rows, mode="drop"),
        bias.at[safe].set(newbias, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnums=(0, 1))
def rowbank_write_packed_f16(bank, bias, packed, n_valid):
    """rowbank_write_packed for FLOAT16 banks: cols 2.. carry TWO f16 lanes
    per uint32 word (numpy ``.view(uint32)`` packing; XLA's bitcast orders
    the trailing lane dim from the least-significant bits, which matches) —
    the compressed upload is HALF the f32 transfer for the same rows."""
    idx = packed[:, 0].astype(jnp.int32)
    newbias = jax.lax.bitcast_convert_type(packed[:, 1], jnp.float32)
    halves = jax.lax.bitcast_convert_type(packed[:, 2:], jnp.float16)
    rows = halves.reshape(packed.shape[0], -1)
    mask = _valid_mask(packed.shape[0], n_valid)
    safe = jnp.where(mask, idx, bank.shape[0])
    return (
        bank.at[safe].set(rows, mode="drop"),
        bias.at[safe].set(newbias, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def rowbank_write_packed_i8(bank, scale, bias, packed, n_valid):
    """rowbank_write_packed for INT8 banks: col 2 = the row's symmetric
    dequant scale (f32 bits), cols 3.. = FOUR int8 lanes per uint32 word —
    a quarter of the f32 transfer; the scoring kernels dequantize in-
    program (``_bank_f32``)."""
    idx = packed[:, 0].astype(jnp.int32)
    newbias = jax.lax.bitcast_convert_type(packed[:, 1], jnp.float32)
    newscale = jax.lax.bitcast_convert_type(packed[:, 2], jnp.float32)
    quads = jax.lax.bitcast_convert_type(packed[:, 3:], jnp.int8)
    rows = quads.reshape(packed.shape[0], -1)
    mask = _valid_mask(packed.shape[0], n_valid)
    safe = jnp.where(mask, idx, bank.shape[0])
    return (
        bank.at[safe].set(rows, mode="drop"),
        scale.at[safe].set(newscale, mode="drop"),
        bias.at[safe].set(newbias, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnums=(2, 3))
def rowbank_grow(bank, bias, grown_bank, grown_bias):
    """Device-side capacity growth: copy the old bank into the zero-filled
    larger plane (HBM copy — growth never re-uploads host rows).  The grown
    planes are donated: XLA writes the copy into their buffers in place.
    dtype-agnostic: the jit re-specializes for f16/int8 banks."""
    c = bank.shape[0]
    return (
        grown_bank.at[:c].set(bank),
        grown_bias.at[:c].set(bias),
    )


@functools.partial(jax.jit, donate_argnums=(1,))
def rowbank_grow_plane(plane, grown):
    """Grow ONE auxiliary per-row plane (the INT8 scale column) the same
    HBM-copy way."""
    return grown.at[: plane.shape[0]].set(plane)


def _wc_hash_prelude(buf):
    n = buf.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    ws = buf == 32
    last_ws = jax.lax.cummax(jnp.where(ws, idx, jnp.int32(-1)))
    pos = idx - last_ws - 1
    cap = jnp.minimum(pos, _WC_POW - 1)
    b1 = buf.astype(jnp.uint32) + 1
    ca = jnp.where(ws, jnp.uint32(0), b1 * jnp.asarray(_WC_POW_A)[cap])
    cb = jnp.where(ws, jnp.uint32(0), b1 * jnp.asarray(_WC_POW_B)[cap])
    return ws, idx, last_ws, jnp.cumsum(ca), jnp.cumsum(cb)


def _wc_gather_words(cum_a, cum_b, last_ws, e, valid, base, n):
    lw = last_ws[e]
    ha = cum_a[e] - jnp.where(lw >= 0, cum_a[jnp.maximum(lw, 0)], 0)
    hb = cum_b[e] - jnp.where(lw >= 0, cum_b[jnp.maximum(lw, 0)], 0)
    ln = (e - lw).astype(jnp.uint32)
    ha = ha ^ (ln * jnp.uint32(2654435761))
    hb = hb + (ln * jnp.uint32(0x9E3779B9))
    sentinel = jnp.uint32(0xFFFFFFFF)
    ha = jnp.where(valid, ha, sentinel)
    hb = jnp.where(valid, hb, sentinel)
    start = jnp.where(valid, (lw + 1).astype(jnp.uint32) + base, sentinel)
    return ha, hb, start


@functools.partial(jax.jit, static_argnums=(2,))
def wc_extract_words_auto(buf, n_words, eb: int, base):
    """wc_extract_words with DEVICE-side word-end discovery: the host ships
    only the text bytes + a word count; end positions come from a mask +
    sort compaction in HBM.  Kills the (E,) delta upload entirely — ~16MB
    per 1M-doc scan on a path where upload bandwidth is the binding cost —
    and the host's delta-encode pass with it.  eb is the static output
    bucket (>= n_words)."""
    n = buf.shape[0]
    ws, idx, last_ws, cum_a, cum_b = _wc_hash_prelude(buf)
    # word end = non-ws byte followed by ws (buf is ws-padded, so the final
    # word's end is always visible)
    end_mask = (~ws) & jnp.concatenate([ws[1:], jnp.ones((1,), bool)])
    ends = jnp.sort(jnp.where(end_mask, idx, jnp.int32(0x7FFFFFFF)))[:eb]
    valid = jnp.arange(eb, dtype=jnp.int32) < n_words
    e = jnp.where(valid, jnp.minimum(ends, n - 1), 0)
    return _wc_gather_words(cum_a, cum_b, last_ws, e, valid, base, n)


@functools.partial(jax.jit, static_argnums=(3,))
def wc_sort_runs(ha, hb, start, d_max: int):
    """Count words by sorting.  (ha, hb) 64-bit keys sort lexicographically;
    equal words become adjacent runs.  A second sort compacts each run's
    first position to the front — counts are host-side diffs of those
    positions.  Returns (firstpos[d_max] i32, offset[d_max] u32); rows at or
    beyond the distinct-word count hold sentinel 0x7FFFFFFF/0xFFFFFFFF."""
    n = ha.shape[0]
    sh_a, sh_b, sh_off = jax.lax.sort((ha, hb, start), num_keys=2)
    prev_a = jnp.concatenate([jnp.full((1,), ~sh_a[0], sh_a.dtype), sh_a[:-1]])
    prev_b = jnp.concatenate([jnp.zeros((1,), sh_b.dtype), sh_b[:-1]])
    first = (sh_a != prev_a) | (sh_b != prev_b)
    idx = jnp.arange(n, dtype=jnp.int32)
    BIG = jnp.int32(0x7FFFFFFF)
    fp = jnp.where(first, idx, BIG)
    c_fp, c_off = jax.lax.sort((fp, sh_off), num_keys=1)
    # ONE (2, d_max) result instead of two arrays: the reduce fetches it in
    # a single d2h round trip (each sync costs a fixed ~66ms on the tunnel;
    # uint32 offsets travel bit-exact through the int32 bitcast)
    return jnp.stack(
        [c_fp[:d_max], jax.lax.bitcast_convert_type(c_off[:d_max], jnp.int32)]
    )
