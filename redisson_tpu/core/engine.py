"""Embedded execution engine: the CommandAsyncExecutor analog.

The reference routes every object operation through `CommandAsyncExecutor`
(``command/CommandAsyncService.java:538-566`` -> RedisExecutor state machine);
object handles are stateless and share one executor.  Here, handles share one
`Engine`, which owns:

  * the DeviceStore (the "server state"),
  * key packing (codec bytes / int64 -> padded device index tensors),
  * the shape-bucketing policy (compile-cache discipline, core/kernels.py),
  * per-record mutual exclusion (the Lua-atomicity equivalent: every compound
    mutation of one object runs under its record lock — single-writer per
    object, SURVEY.md §7.1 item 5),
  * the in-process pub/sub hub used by synchronizer wakeups and topics.

Remote mode (server/) wraps the same Engine behind the RESP protocol.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Iterable, Optional, Sequence, Tuple

import numpy as np

from redisson_tpu.client.codec import Codec, DEFAULT_CODEC
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import DeviceStore, StateRecord
from redisson_tpu.utils import hashing as H


class Engine:
    def __init__(self, config=None):
        from redisson_tpu.config import Config
        from redisson_tpu.core.pubsub import PubSubHub

        self.config = config if config is not None else Config()
        self.store = DeviceStore()
        self.pubsub = PubSubHub()
        self.default_codec: Codec = DEFAULT_CODEC
        self._record_locks: dict[str, threading.RLock] = {}
        self._locks_guard = threading.Lock()
        self._wait_entries: dict[str, "object"] = {}
        self._holder_override = threading.local()
        self._closed = False
        self._eviction = None
        self._services: dict = {}

    def service(self, key: str, factory):
        """Engine-scoped lazy singleton (script cache, search indexes, ...)
        — one instance per engine regardless of which handle asks first."""
        with self._locks_guard:
            svc = self._services.get(key)
            if svc is None:
                svc = self._services[key] = factory()
            return svc

    @property
    def eviction(self):
        """Lazily-started EvictionScheduler (eviction/EvictionScheduler.java
        analog); the sweep thread only exists once something registers."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._eviction is None:
                from redisson_tpu.core.eviction import EvictionScheduler

                self._eviction = EvictionScheduler(
                    min_delay=self.config.min_cleanup_delay,
                    max_delay=self.config.max_cleanup_delay,
                )
                # global TTL reaper: RExpirable whole-object expiries
                self._eviction.schedule("__store__", self.store.reap_expired)
            return self._eviction

    @contextmanager
    def impersonate(self, holder_id: Optional[str]):
        """Execute with an explicit synchronizer-holder identity — the server
        runs remote calls under the CLIENT's uuid:threadId (the reference's
        LockName travels from client to Lua the same way,
        RedissonBaseLock.getLockName)."""
        if holder_id is None:
            yield
            return
        prev = getattr(self._holder_override, "value", None)
        self._holder_override.value = holder_id
        try:
            yield
        finally:
            self._holder_override.value = prev

    def holder_override(self) -> Optional[str]:
        return getattr(self._holder_override, "value", None)

    def wait_entry(self, key: str):
        """Shared per-key wait latch (the RedissonLockEntry registry of
        pubsub/PublishSubscribeService — one latch per waiting object)."""
        from redisson_tpu.core.pubsub import WaitEntry

        with self._locks_guard:
            we = self._wait_entries.get(key)
            if we is None:
                we = self._wait_entries[key] = WaitEntry()
            return we

    # -- locking ------------------------------------------------------------

    def record_lock(self, name: str) -> threading.RLock:
        with self._locks_guard:
            lock = self._record_locks.get(name)
            if lock is None:
                lock = self._record_locks[name] = threading.RLock()
            return lock

    @contextmanager
    def locked(self, name: str):
        lock = self.record_lock(name)
        with lock:
            yield

    @contextmanager
    def locked_many(self, names: Iterable[str]):
        """Acquire several record locks in sorted-name order (deadlock-free
        for concurrent multi-object ops like PFMERGE / BITOP)."""
        ordered = sorted(set(names))
        locks = [self.record_lock(n) for n in ordered]
        for lk in locks:
            lk.acquire()
        try:
            yield
        finally:
            for lk in reversed(locks):
                lk.release()

    # -- key packing --------------------------------------------------------

    @staticmethod
    def is_int_batch(objs) -> bool:
        if isinstance(objs, np.ndarray) and objs.dtype.kind in "iu":
            return True
        return False

    def pack_keys(self, objs, codec: Optional[Codec]) -> Tuple[str, tuple, int]:
        """Normalize a key batch for the hash kernels.

        Returns (kind, padded_arrays, n_valid):
          kind="u64":   arrays = ONE (2, B) uint32 buffer (rows lo, hi) — a
                        single contiguous host->device transfer per flush
                        (kernels.pack_rows bandwidth note)
          kind="bytes": arrays = (words[W,N], nbytes[N]) padded on both axes

        Fast path: numpy integer arrays are hashed as int64 directly (no codec
        round-trip) — the vectorized analog of the reference's
        codec-encode-then-hash (RedissonBloomFilter.java:90-97), which this
        deliberately skips for machine-width keys.
        """
        codec = codec or self.default_codec
        if self.is_int_batch(objs):
            arr = np.ascontiguousarray(objs, dtype=np.int64)
            n = arr.shape[0]
            b = K.bucket_size(max(1, n))
            lo, hi = H.int_keys_to_u32_pair(arr)
            return "u64", K.pack_rows(lo, hi, size=b), n
        if isinstance(objs, (bytes, str, int, float)) or not isinstance(objs, (list, tuple, np.ndarray)):
            objs = [objs]
        encoded = [o if isinstance(o, bytes) else codec.encode(o) for o in objs]
        n = len(encoded)
        words, nbytes = H.pack_keys(encoded)
        b = K.pow2_bucket(max(1, n))
        w = max(4, K.pow2_bucket(max(1, words.shape[0]), minimum=4))
        words = K.pad_to(K.pad_to(words, b, axis=1), w, axis=0)
        nbytes = K.pad_to(nbytes, b)
        return "bytes", (words, nbytes), n

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self):
        with self._locks_guard:
            self._closed = True
            eviction, self._eviction = self._eviction, None
        if eviction is not None:
            eviction.close()
        self.pubsub.close()
        self.store.flushall()


def require(rec: Optional[StateRecord], name: str) -> StateRecord:
    if rec is None:
        raise KeyError(f"object '{name}' does not exist")
    return rec
