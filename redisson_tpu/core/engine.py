"""Embedded execution engine: the CommandAsyncExecutor analog.

The reference routes every object operation through `CommandAsyncExecutor`
(``command/CommandAsyncService.java:538-566`` -> RedisExecutor state machine);
object handles are stateless and share one executor.  Here, handles share one
`Engine`, which owns:

  * the DeviceStore (the "server state"),
  * key packing (codec bytes / int64 -> padded device index tensors),
  * the shape-bucketing policy (compile-cache discipline, core/kernels.py),
  * per-record mutual exclusion (the Lua-atomicity equivalent: every compound
    mutation of one object runs under its record lock — single-writer per
    object, SURVEY.md §7.1 item 5),
  * the in-process pub/sub hub used by synchronizer wakeups and topics.

Remote mode (server/) wraps the same Engine behind the RESP protocol.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from redisson_tpu.client.codec import Codec, DEFAULT_CODEC
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import DeviceStore, StateRecord
from redisson_tpu.utils import hashing as H


class Engine:
    def __init__(self, config=None):
        import redisson_tpu
        from redisson_tpu.config import Config
        from redisson_tpu.core.pubsub import PubSubHub

        # engines are where device work starts: configure the persistent
        # XLA compile cache before the first kernel compiles (lazy — a
        # wire-only client never constructs an Engine and never pays the
        # jax import)
        redisson_tpu._enable_persistent_compile_cache()
        self.config = config if config is not None else Config()
        self.store = DeviceStore()
        self.pubsub = PubSubHub()
        self.default_codec: Codec = DEFAULT_CODEC
        # name -> [RLock, refcount]: entries exist only while someone holds or
        # waits on them, so object churn can't grow the registry unboundedly
        self._record_locks: dict[str, list] = {}
        self._locks_guard = threading.Lock()
        self._wait_entries: dict[str, "object"] = {}
        self._holder_override = threading.local()
        self._closed = False
        self._eviction = None
        self._timer = None
        self._timer_pool = None
        self._renewal_pool_ = None
        self._events_pool_ = None
        # (name, holder) -> Timeout: active lock-watchdog renewals, all on
        # the ONE shared wheel timer (ServiceManager's HashedWheelTimer role)
        self._renewals: dict[tuple, Any] = {}
        self._services: dict = {}
        # overlapped device I/O plane (core/ioplane): double-buffered host
        # staging shared by every flush packer of this engine
        from redisson_tpu.core import ioplane

        self.staging = ioplane.StagingPool()
        # device-sharded serving (ISSUE 8): slot -> local-device placement +
        # one serving lane per device.  None (the default) = single-device
        # behavior, bit for bit; enable_placement() opts in.
        self.placement = None
        self.lanes = None
        # tiered HBM residency (ISSUE 20): None until enable_residency()
        # arms the HOT/WARM/COLD plane for THIS engine's store
        self.residency = None

    def service(self, key: str, factory):
        """Engine-scoped lazy singleton (script cache, search indexes, ...)
        — one instance per engine regardless of which handle asks first."""
        with self._locks_guard:
            svc = self._services.get(key)
            if svc is None:
                svc = self._services[key] = factory()
            return svc

    @property
    def eviction(self):
        """Lazily-started EvictionScheduler (eviction/EvictionScheduler.java
        analog); the sweep thread only exists once something registers."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._eviction is None:
                from redisson_tpu.core.eviction import EvictionScheduler

                self._eviction = EvictionScheduler(
                    min_delay=self.config.min_cleanup_delay,
                    max_delay=self.config.max_cleanup_delay,
                )
                # global TTL reaper: RExpirable whole-object expiries
                self._eviction.schedule("__store__", self.store.reap_expired)
            return self._eviction

    @contextmanager
    def impersonate(self, holder_id: Optional[str]):
        """Execute with an explicit synchronizer-holder identity — the server
        runs remote calls under the CLIENT's uuid:threadId (the reference's
        LockName travels from client to Lua the same way,
        RedissonBaseLock.getLockName)."""
        if holder_id is None:
            yield
            return
        prev = getattr(self._holder_override, "value", None)
        self._holder_override.value = holder_id
        try:
            yield
        finally:
            self._holder_override.value = prev

    def holder_override(self) -> Optional[str]:
        return getattr(self._holder_override, "value", None)

    def wait_entry(self, key: str):
        """Shared per-key wait latch (the RedissonLockEntry registry of
        pubsub/PublishSubscribeService — one latch per waiting object).

        Idle entries (no waiters, no buffered signal, untouched for 60s) are
        pruned by a background sweep; every park in the codebase is a bounded
        retry loop, so a signal lost to a prune costs one park timeout, never
        a hang."""
        from redisson_tpu.core.pubsub import WaitEntry

        with self._locks_guard:
            we = self._wait_entries.get(key)
            if we is None:
                we = self._wait_entries[key] = WaitEntry()
        we.touch()  # a fetched entry is in use: restart its idle clock
        # the sweep rides the shared eviction thread; first use starts it
        try:
            self.eviction.schedule("__wait_entry_gc__", self._gc_wait_entries)
        except RuntimeError:
            # engine shut down between the entry fetch and the schedule; the
            # caller's park loop is bounded, so skipping the GC is harmless
            pass
        return we

    def _gc_wait_entries(self, max_idle: float = 60.0) -> int:
        with self._locks_guard:
            stale = [
                k for k, we in self._wait_entries.items() if we.idle(max_idle)
            ]
            for k in stale:
                del self._wait_entries[k]
        return len(stale)

    # -- timers --------------------------------------------------------------

    @property
    def timer(self):
        """ONE shared wheel timer for all watchdogs/renewals — never a thread
        per timeout (connection/ServiceManager.java HashedWheelTimer role)."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._timer is None:
                from redisson_tpu.utils.timer import HashedWheelTimer

                self._timer = HashedWheelTimer()
            return self._timer

    @property
    def timer_pool(self):
        """Small shared pool that RUNS timed tasks (the reference pairs its
        wheel timer with the ServiceManager executor the same way): wheel
        ticks only enqueue, so a task blocking on a contended record lock
        can never stall every other timeout in the process."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._timer_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._timer_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="rtpu-timer-task"
                )
            return self._timer_pool

    def queue_wait_entry(self, name: str):
        """The wait entry blocking-queue-family consumers park on — the ONE
        authority for the __q_wait__ key format (paired with
        signal_queue_waiters; hand-built keys at park sites would silently
        strand waiters if the format ever moved)."""
        return self.wait_entry(f"__q_wait__:{name}")

    def signal_queue_waiters(self, name: str) -> None:
        """Wake queue-family waiters parked on `name` WITHOUT materializing
        a wait entry when nobody waits."""
        e = self._wait_entries.get(f"__q_wait__:{name}")
        if e is not None:
            e.signal(all_=True)

    def schedule_timeout(self, fn, delay: float):
        """Run `fn` ~`delay` seconds from now on the shared timer pool.
        Returns the wheel Timeout (cancellable until it fires)."""
        pool = self.timer_pool
        return self.timer.new_timeout(lambda: pool.submit(fn), delay)

    @property
    def events_pool(self):
        """SINGLE-worker pool delivering entry/eviction events
        (MapCache listeners etc.).  One worker on purpose: events for one
        object must arrive in mutation order (created before updated before
        removed), which a multi-worker pool cannot guarantee.  Deliveries
        are async so a mutator never runs user listeners while holding the
        record lock (the reference gets the same decoupling from Redis
        pubsub delivery)."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._events_pool_ is None:
                from concurrent.futures import ThreadPoolExecutor

                self._events_pool_ = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rtpu-events"
                )
            return self._events_pool_

    @property
    def _renewal_pool(self):
        """Dedicated pool for lease renewals.  Renewals are lease-CRITICAL:
        sharing a pool with arbitrary user work (MapWriter flushes,
        scheduled-task fires) would let a blocked writer starve renewals
        past lease expiry — two holders of a mutual-exclusion lock.
        Multiple workers for the same reason INTERNALLY: one renew() stuck
        on a contended record lock (held across a device sync or a
        migration serialize) must not delay every other lock's renewal
        tick past its lease."""
        with self._locks_guard:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._renewal_pool_ is None:
                from concurrent.futures import ThreadPoolExecutor

                self._renewal_pool_ = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="rtpu-renewal"
                )
            return self._renewal_pool_

    def start_renewal(self, name: str, holder: str, renew, interval: float) -> None:
        """Register a watchdog renewal for (lock name, holder) — the
        EXPIRATION_RENEWAL_MAP discipline of RedissonBaseLock.java:127-189:
        one renewal per (entry, holder) regardless of reentrancy; `renew()`
        returns True to keep renewing, False to stop."""
        key = (name, holder)

        def tick():
            # runs on the timer POOL (renew takes record locks and must not
            # block the wheel thread — schedule_timeout enforces the hop)
            try:
                keep = bool(renew())
            except Exception:  # noqa: BLE001 — a failing renew stops renewing
                keep = False
            with self._locks_guard:
                if key not in self._renewals or not keep or self._closed:
                    self._renewals.pop(key, None)
                    return
            nxt = self._schedule_renewal_tick(tick, interval)
            with self._locks_guard:
                if key in self._renewals:
                    self._renewals[key] = nxt
                else:
                    nxt.cancel()  # cancel_renewal raced the reschedule

        with self._locks_guard:
            if key in self._renewals:
                return  # reentrant re-acquire keeps the existing renewal
            self._renewals[key] = None  # claim the slot before scheduling
        first = self._schedule_renewal_tick(tick, interval)
        with self._locks_guard:
            if key in self._renewals:
                self._renewals[key] = first
            else:
                first.cancel()  # cancelled between claim and schedule

    def _schedule_renewal_tick(self, tick, interval: float):
        pool = self._renewal_pool
        return self.timer.new_timeout(lambda: pool.submit(tick), interval)

    def cancel_renewal(self, name: str, holder: Optional[str] = None) -> None:
        """Stop renewals for a lock (all holders when holder is None — the
        force_unlock path)."""
        with self._locks_guard:
            keys = [
                k
                for k in self._renewals
                if k[0] == name and (holder is None or k[1] == holder)
            ]
            for k in keys:
                t = self._renewals.pop(k)
                if t is not None:  # None = start_renewal's claim placeholder
                    t.cancel()

    # -- locking ------------------------------------------------------------

    @contextmanager
    def locked(self, name: str):
        with self._locks_guard:
            entry = self._record_locks.get(name)
            if entry is None:
                entry = self._record_locks[name] = [threading.RLock(), 0]
            entry[1] += 1
        try:
            with entry[0]:
                yield
        finally:
            with self._locks_guard:
                entry[1] -= 1
                if entry[1] == 0:
                    # nobody holds or waits: drop the registry entry (churny
                    # short-lived objects must not leak host memory)
                    self._record_locks.pop(name, None)

    def try_locked(self, name: str):
        """Non-blocking record lock: a held context manager, or None when
        some other thread holds the lock RIGHT NOW.  The residency demoter
        uses it so releasing cold arrays can never stall a serving path —
        a busy record simply stays HOT this sweep."""
        with self._locks_guard:
            entry = self._record_locks.get(name)
            if entry is None:
                entry = self._record_locks[name] = [threading.RLock(), 0]
            entry[1] += 1
        if not entry[0].acquire(blocking=False):
            with self._locks_guard:
                entry[1] -= 1
                if entry[1] == 0:
                    self._record_locks.pop(name, None)
            return None

        @contextmanager
        def _held():
            try:
                yield
            finally:
                entry[0].release()
                with self._locks_guard:
                    entry[1] -= 1
                    if entry[1] == 0:
                        self._record_locks.pop(name, None)

        return _held()

    @contextmanager
    def locked_many(self, names: Iterable[str]):
        """Acquire several record locks in sorted-name order (deadlock-free
        for concurrent multi-object ops like PFMERGE / BITOP)."""
        ordered = sorted(set(names))
        entries = []
        with self._locks_guard:
            for n in ordered:
                entry = self._record_locks.get(n)
                if entry is None:
                    entry = self._record_locks[n] = [threading.RLock(), 0]
                entry[1] += 1
                entries.append((n, entry))
        acquired = []
        try:
            for _n, entry in entries:
                entry[0].acquire()
                acquired.append(entry)
            yield
        finally:
            for entry in reversed(acquired):
                entry[0].release()
            with self._locks_guard:
                for n, entry in entries:
                    entry[1] -= 1
                    if entry[1] == 0:
                        self._record_locks.pop(n, None)

    # -- device-sharded placement (ISSUE 8) -----------------------------------

    def enable_placement(self, devices=None, n_devices: Optional[int] = None):
        """Map the 16384-slot table onto the local device mesh: every
        record created/installed from here on commits its device arrays to
        the device owning its slot, frames routed to different devices
        dispatch down per-device lanes (ioplane.LaneSet), and coalesced
        runs fuse PER DEVICE.  Returns the SlotPlacement (rebalanceable
        online via fenced slot handoffs — server/migration.rebalance_devices).
        """
        from redisson_tpu.core import ioplane
        from redisson_tpu.server.placement import SlotPlacement

        placement = SlotPlacement(devices=devices, n_devices=n_devices)
        with self._locks_guard:
            self.placement = placement
            self.lanes = ioplane.LaneSet(placement.devices)
        self.store.placement_hook = self._place_record
        return placement

    def device_for_name(self, name: str):
        """Owner device of `name`'s slot, or None with placement off."""
        p = self.placement
        return None if p is None else p.device_for_name(name)

    # -- tiered HBM residency (ISSUE 20) --------------------------------------

    def enable_residency(self, budget_bytes: Optional[int] = None,
                         spill_dir: Optional[str] = None,
                         sweep_interval: float = 0.0, **kw):
        """Arm the HOT/WARM/COLD residency plane for this engine's store:
        getters fault WARM/COLD records back in on first touch, and the
        (optional) background sweeper demotes least-recently-touched clean
        records whenever a device exceeds ``device-budget-bytes``.
        Idempotent; returns the ResidencyManager."""
        from redisson_tpu.core import residency as _residency

        if self.residency is None:
            self.residency = _residency.ResidencyManager(
                self, spill_dir=spill_dir, sweep_interval=sweep_interval,
                **kw,
            )
            self.store.residency = self.residency
        if budget_bytes is not None:
            _residency.set_device_budget_bytes(budget_bytes)
        return self.residency

    def disable_residency(self) -> None:
        """Detach the residency plane from this store.  Every WARM/COLD
        record is promoted back to HOT FIRST — once the getters stop
        routing to the manager nothing would ever fault a demoted record
        back in, and its (correct, host-side) state would read as empty."""
        mgr = self.residency
        if mgr is None:
            return
        with self.store._lock:
            demoted = [
                (n, r) for n, r in self.store._states.items()
                if r.tier != "hot"
            ]
        for name, rec in demoted:
            mgr.fault_in(name, rec)
        self.residency = None
        self.store.residency = None
        mgr.stop()

    def _place_record(self, name: str, rec) -> None:
        """DeviceStore placement hook: commit the record's single-device
        arrays to the slot's owner.  Multi-device (mesh-sharded) planes are
        never touched — the parallel/ layer owns their layout."""
        p = self.placement
        if p is None:
            return
        device = p.device_for_name(name)
        import jax

        for key, arr in list(rec.arrays.items()):
            devs = getattr(arr, "devices", None)
            if devs is not None:
                try:
                    ds = devs()
                except TypeError:  # pragma: no cover
                    continue
                if len(ds) != 1 or ds == {device}:
                    continue  # sharded plane, or already home
            elif not isinstance(arr, np.ndarray):
                continue  # host-side state (lists/dicts) never places
            rec.arrays[key] = jax.device_put(arr, device)

    @staticmethod
    def _move_record_to(rec, device) -> bool:
        """Commit a record's movable arrays to `device`; True iff anything
        actually hopped.  Sharded (multi-device) planes and host-side state
        never move; single-device jax arrays and numpy values do."""
        import jax

        changed = False
        for key, arr in list(rec.arrays.items()):
            devs = getattr(arr, "devices", None)
            if devs is None:
                if not isinstance(arr, np.ndarray):
                    continue
            else:
                try:
                    ds = devs()
                except TypeError:  # pragma: no cover
                    continue
                if len(ds) != 1 or ds == {device}:
                    continue
            rec.arrays[key] = jax.device_put(arr, device)
            changed = True
        return changed

    def move_slots_records(self, targets: Dict[int, int],
                           epoch: Optional[int] = None,
                           skip_stale: bool = False) -> Tuple[int, int]:
        """BULK fenced slot -> device handoff: fence + repoint every slot
        in ``targets`` ({slot: device_index}), then move the affected
        records in ONE store scan (a full 8->4 rebalance repoints ~8192
        owners; per-slot scans would be O(slots x keys)).  Each record
        moves under its record lock: an in-flight dispatch holds the lock
        and finishes on the old device; the next dispatch finds the plane
        committed to the new one.  Returns (records_moved, stale_slots);
        a stale coordinator's epoch raises PlacementStaleEpoch unless
        ``skip_stale`` (the resume path) counts it instead."""
        from redisson_tpu.server.placement import PlacementStaleEpoch
        from redisson_tpu.utils.crc16 import calc_slot

        p = self.placement
        if p is None:
            raise RuntimeError("placement is not enabled on this engine")
        fenced: Dict[int, int] = {}
        stale = 0
        for slot, dev_index in targets.items():
            try:
                p.assign(slot, dev_index, epoch)  # fences + repoints routing
                fenced[slot] = dev_index
            except PlacementStaleEpoch:
                if not skip_stale:
                    raise
                stale += 1  # a newer rebalance owns this slot now
        if not fenced:
            return 0, stale
        moving = [
            (n, fenced[s])
            for n in self.store.keys()
            for s in (calc_slot(n.encode()),)
            if s in fenced
        ]
        moved = 0
        for name, dev_index in moving:
            device = p.devices[dev_index]
            with self.locked(name):
                rec = self.store.get_unguarded(name)
                if rec is not None and self._move_record_to(rec, device):
                    moved += 1
        return moved, stale

    def move_slot_records(self, slot: int, dev_index: int,
                          epoch: Optional[int] = None) -> int:
        """One fenced slot -> device handoff (CLUSTER DEVMOVE's unit);
        see move_slots_records for the bulk form and the contract."""
        moved, _stale = self.move_slots_records({slot: dev_index}, epoch)
        return moved

    # -- kernel warm pool ----------------------------------------------------

    @property
    def warm_pool(self):
        """The process-global kernel warm-pool (core/warmpool.py)."""
        from redisson_tpu.core import warmpool

        return warmpool.POOL

    def prewarm(self, names=None, buckets=(0,), all_devices: Optional[bool] = None) -> int:
        """Precompile the hot kernels of live records at the given batch
        buckets (TasksRunnerService warm-pool analog) — run at boot or
        before a timed serving phase, never on the hot path.  Returns the
        number of programs actually compiled/loaded this call.

        With placement enabled (device-sharded serving) the default warms
        every record's geometry on EVERY local device — jit specializes per
        device placement, so a slot handoff onto a cold device would
        otherwise pay a first-dispatch compile mid-serving.  Pass
        ``all_devices=False`` to warm only each record's current owner."""
        from redisson_tpu.core import warmpool

        if all_devices is None:
            all_devices = self.placement is not None
        return warmpool.prewarm_store(
            self, names=names, buckets=buckets,
            devices=(self.placement.devices
                     if (all_devices and self.placement is not None) else None),
        )

    # -- overlapped device I/O ----------------------------------------------

    def staging_pool(self, device=None):
        """The engine's double-buffered host staging pool — or None when the
        overlap plane is off (--no-overlap: serial A/B reference) or the
        backend zero-copy-aliases host memory (CPU jax), where slot reuse
        would corrupt a staged value (ioplane.staging_reuse_safe).

        With placement enabled and a `device` given, the DEVICE'S lane pool
        is returned instead of the shared one: each device's uploads double-
        buffer independently, so two lanes' flush packing never contends on
        one slot pair (the per-chip lane discipline, ISSUE 8)."""
        from redisson_tpu.core import ioplane

        if not (ioplane.overlap_enabled() and ioplane.staging_reuse_safe()):
            return None
        if device is not None and self.lanes is not None:
            lane = self.lanes.lane(device)
            # interactive device stream (ISSUE 18): the holding thread's
            # occupancy marks itself in TLS, so its flush packing stages
            # through the lane's interactive slot instead of contending on
            # the bulk stream's double buffer
            if ioplane.current_stream() == "interactive":
                return lane.ipool
            return lane.pool
        return self.staging

    # -- key packing --------------------------------------------------------

    @staticmethod
    def is_int_batch(objs) -> bool:
        if isinstance(objs, np.ndarray) and objs.dtype.kind in "iu":
            return True
        return False

    def pack_keys(self, objs, codec: Optional[Codec],
                  cache_hot: bool = False) -> Tuple[str, tuple, int]:
        """Normalize a key batch for the hash kernels.

        Returns (kind, padded_arrays, n_valid):
          kind="u64":   arrays = ONE (2, B) uint32 buffer (rows lo, hi) — a
                        single contiguous host->device transfer per flush
                        (kernels.pack_rows bandwidth note)
          kind="bytes": arrays = (words[W,N], nbytes[N]) padded on both axes

        Fast path: numpy integer arrays are hashed as int64 directly (no codec
        round-trip) — the vectorized analog of the reference's
        codec-encode-then-hash (RedissonBloomFilter.java:90-97), which this
        deliberately skips for machine-width keys.
        """
        codec = codec or self.default_codec
        if self.is_int_batch(objs):
            arr = np.ascontiguousarray(objs, dtype=np.int64)
            n = arr.shape[0]
            b = K.bucket_size(max(1, n))

            def build():
                lo, hi = H.int_keys_to_u32_pair(arr)
                return K.pack_rows(lo, hi, size=b, pool=self.staging_pool())

            if cache_hot and n >= 4096:
                # hot-set reuse, READ paths only (kernels.cached_staged): a
                # serving loop re-probing the same working set skips the
                # pack and the h2d upload entirely
                return "u64", K.cached_staged(build, arr, extra=b"u64%d" % b), n
            return "u64", build(), n
        if isinstance(objs, (bytes, str, int, float)) or not isinstance(objs, (list, tuple, np.ndarray)):
            objs = [objs]
        encoded = [o if isinstance(o, bytes) else codec.encode(o) for o in objs]
        n = len(encoded)
        words, nbytes = H.pack_keys(encoded)
        b = K.pow2_bucket(max(1, n))
        w = max(4, K.pow2_bucket(max(1, words.shape[0]), minimum=4))
        words = K.stage(K.pad_to(K.pad_to(words, b, axis=1), w, axis=0))
        nbytes = K.stage(K.pad_to(nbytes, b))
        return "bytes", (words, nbytes), n

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self):
        with self._locks_guard:
            self._closed = True
            eviction, self._eviction = self._eviction, None
            timer, self._timer = self._timer, None
            pool, self._timer_pool = self._timer_pool, None
            rpool, self._renewal_pool_ = self._renewal_pool_, None
            epool, self._events_pool_ = self._events_pool_, None
            renewals = list(self._renewals.values())
            self._renewals.clear()
        for t in renewals:
            if t is not None:
                t.cancel()
        if timer is not None:
            timer.stop()
        for p in (pool, rpool, epool):
            if p is not None:
                p.shutdown(wait=False, cancel_futures=True)
        if eviction is not None:
            eviction.close()
        if self.residency is not None:
            self.residency.stop()
            self.residency = None
            self.store.residency = None
        self.pubsub.close()
        self.staging.clear()
        if self.lanes is not None:
            self.lanes.clear()
        self.store.flushall()


def require(rec: Optional[StateRecord], name: str) -> StateRecord:
    if rec is None:
        raise KeyError(f"object '{name}' does not exist")
    return rec
