"""EvictionScheduler: self-tuning background expiry sweeps.

Role parity: `eviction/EvictionScheduler.java:33-63` registers one cleanup
task per expiring object (MapCache, SetCache, TimeSeries, JCache, multimap
cache); each `EvictionTask` reschedules itself with a delay that adapts to how
much it actually removed — frequent sweeps while entries are expiring, backing
off toward the max delay when sweeps come up empty (`config/Config.java:83-87`
knobs: minCleanUpDelay=5s, maxCleanUpDelay=30min).

Design here: one daemon thread + a time-ordered heap of tasks instead of a
wheel timer (the sweep cadence is seconds-to-minutes; a heap is exact and
cheap at this rate).  The sweep callables run entirely on the host — they
must never touch the device dispatch path (SURVEY.md §7.3 hard-part 3).

Tuning rule (mirror of EvictionTask.getNextDelay logic): a sweep that removes
at least `keys_limit` entries halves the delay (more work likely pending); a
sweep that removes nothing multiplies it by 1.5; anything in between keeps
the current cadence. Always clamped to [min_delay, max_delay].
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, Optional


class _Task:
    __slots__ = ("name", "sweep", "delay", "dead")

    def __init__(self, name: str, sweep: Callable[[], int], delay: float):
        self.name = name
        self.sweep = sweep
        self.delay = delay
        self.dead = False


class EvictionScheduler:
    KEYS_LIMIT = 100  # removals per sweep that signal "sweep again soon"
    DROP = -1         # sweep return value meaning "unschedule me"

    def __init__(
        self,
        min_delay: float = 5.0,
        max_delay: float = 1800.0,
        start_delay: Optional[float] = None,
    ):
        self.min_delay = min_delay
        self.max_delay = max_delay
        self.start_delay = start_delay if start_delay is not None else min_delay
        self._tasks: Dict[str, _Task] = {}
        self._heap: list = []  # (fire_at, seq, task)
        self._seq = 0
        self._cv = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self.sweeps = 0          # observability counters
        self.total_removed = 0

    # -- registration --------------------------------------------------------

    def schedule(self, name: str, sweep: Callable[[], int]) -> None:
        """Register (or refresh) a cleanup task for object `name`.

        `sweep()` must return the number of entries it removed.  Idempotent:
        re-registering an object keeps the existing cadence (the reference
        also keys tasks by object name, EvictionScheduler.java:44-52).
        """
        with self._cv:
            if self._closed or name in self._tasks:
                return
            task = _Task(name, sweep, self.start_delay)
            self._tasks[name] = task
            self._push(task, time.time() + task.delay)
            self._ensure_thread()
            self._cv.notify()

    def schedule_for_record(self, engine, name: str, sweep: Callable[[], int]) -> None:
        """Register a sweep tied to a store record's lifetime: once the record
        has existed and is later deleted, the task unschedules itself —
        otherwise per-name tasks for dynamic object names leak forever.
        Recreating the object re-registers through the factory path."""
        seen = [False]

        def guarded() -> int:
            exists = engine.store.exists(name)
            if exists:
                seen[0] = True
                return sweep()
            return self.DROP if seen[0] else 0

        self.schedule(name, guarded)

    def unschedule(self, name: str) -> None:
        with self._cv:
            task = self._tasks.pop(name, None)
            if task is not None:
                task.dead = True

    def _push(self, task: _Task, fire_at: float) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (fire_at, self._seq, task))

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="rtpu-eviction", daemon=True
            )
            self._thread.start()

    # -- the sweep loop ------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                    not self._heap or self._heap[0][0] > time.time()
                ):
                    wait = (
                        self._heap[0][0] - time.time() if self._heap else None
                    )
                    self._cv.wait(timeout=wait)
                if self._closed:
                    return
                _, _, task = heapq.heappop(self._heap)
                if task.dead:
                    continue
            try:
                removed = int(task.sweep() or 0)
            except Exception:  # noqa: BLE001 - a failing sweep must not kill the loop
                removed = 0
            if removed == self.DROP:
                self.unschedule(task.name)
                continue
            self.sweeps += 1
            self.total_removed += removed
            if removed >= self.KEYS_LIMIT:
                task.delay = max(self.min_delay, task.delay / 2.0)
            elif removed == 0:
                task.delay = min(self.max_delay, task.delay * 1.5)
            with self._cv:
                if not task.dead and not self._closed:
                    self._push(task, time.time() + task.delay)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
