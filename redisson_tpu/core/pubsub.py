"""In-process pub/sub hub: the PublishSubscribeService analog.

Parity target: ``org/redisson/pubsub/PublishSubscribeService.java`` (~900 LoC,
SURVEY.md §2.2) — a subscription registry that (a) fans published messages out
to listeners and (b) wakes blocked synchronizer waiters (LockPubSub /
SemaphorePubSub / CountDownLatchPubSub wire per-object latches to channel
messages, SURVEY.md §3.3).

In embedded mode this is a thread-safe registry + condition variables; in
server mode the same hub backs SUBSCRIBE/PUBLISH across connections.  Message
ordering per channel is preserved under the hub lock (the reference's
`keepPubSubOrder`).
"""
from __future__ import annotations

import fnmatch
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Tuple

Listener = Callable[[str, Any], None]


class PubSubHub:
    def __init__(self):
        self._lock = threading.RLock()
        self._channels: Dict[str, List[Tuple[int, Listener]]] = defaultdict(list)
        self._patterns: Dict[str, List[Tuple[int, Listener]]] = defaultdict(list)
        self._next_id = 1
        self._closed = False

    def subscribe(self, channel: str, listener: Listener) -> int:
        with self._lock:
            lid = self._next_id
            self._next_id += 1
            self._channels[channel].append((lid, listener))
            return lid

    def psubscribe(self, pattern: str, listener: Listener) -> int:
        with self._lock:
            lid = self._next_id
            self._next_id += 1
            self._patterns[pattern].append((lid, listener))
            return lid

    def unsubscribe(self, channel: str, listener_id: int) -> None:
        with self._lock:
            subs = self._channels.get(channel, [])
            self._channels[channel] = [(i, l) for i, l in subs if i != listener_id]
            if not self._channels[channel]:
                self._channels.pop(channel, None)

    def punsubscribe(self, pattern: str, listener_id: int) -> None:
        with self._lock:
            subs = self._patterns.get(pattern, [])
            self._patterns[pattern] = [(i, l) for i, l in subs if i != listener_id]
            if not self._patterns[pattern]:
                self._patterns.pop(pattern, None)

    def publish(self, channel: str, message: Any) -> int:
        """Deliver to all channel + matching pattern listeners; returns the
        receiver count (PUBLISH reply semantics)."""
        with self._lock:
            if self._closed:
                return 0
            targets = list(self._channels.get(channel, []))
            ptargets = [
                (pat, lid, fn)
                for pat, subs in self._patterns.items()
                if fnmatch.fnmatchcase(channel, pat)
                for lid, fn in subs
            ]
        n = 0
        for _lid, fn in targets:
            fn(channel, message)
            n += 1
        for _pat, _lid, fn in ptargets:
            fn(channel, message)
            n += 1
        return n

    def subscriber_count(self, channel: str) -> int:
        with self._lock:
            return len(self._channels.get(channel, []))

    def has_listeners(self, channel: str) -> bool:
        """True when a publish to `channel` would reach anyone — exact
        subscribers OR matching pattern subscribers.  Publishers use this to
        skip payload-construction cost; gating on subscriber_count alone
        would silently starve PSUBSCRIBE-only consumers."""
        with self._lock:
            if self._channels.get(channel):
                return True
            return any(
                fnmatch.fnmatchcase(channel, pat)
                for pat, subs in self._patterns.items()
                if subs
            )

    def channels(self) -> List[str]:
        with self._lock:
            return list(self._channels)

    def close(self):
        with self._lock:
            self._closed = True
            self._channels.clear()
            self._patterns.clear()


class WaitEntry:
    """Per-object wait latch: the RedissonLockEntry analog (pubsub/LockPubSub.java).

    Blocked acquirers park on `wait_for`; an unlock/release message wakes one
    (or all) of them.  Built on a condition variable instead of a Redis
    subscription, but the contract is the same: subscribe-once per object,
    wake on message, re-try the acquisition loop.
    """

    def __init__(self):
        self.cond = threading.Condition()
        self._signals = 0
        self._waiters = 0
        self._last_used = time.monotonic()

    def signal(self, all_: bool = False):
        with self.cond:
            self._last_used = time.monotonic()
            self._signals += 1
            if all_:
                self.cond.notify_all()
            else:
                self.cond.notify()

    def wait_for(self, timeout: float | None) -> bool:
        """Wait until signalled; consumes one signal. Returns False on timeout."""
        with self.cond:
            self._last_used = time.monotonic()
            if self._signals > 0:
                self._signals -= 1
                return True
            self._waiters += 1
            try:
                ok = self.cond.wait(timeout)
            finally:
                self._waiters -= 1
                self._last_used = time.monotonic()
            if ok and self._signals > 0:
                self._signals -= 1
            return ok

    def touch(self) -> None:
        """Reset the idle clock — called on every registry fetch so the GC
        can never prune an entry between a caller's wait_entry() lookup and
        its first park (the fetch-to-park window is the race the sweep's
        60s idle threshold must dominate)."""
        with self.cond:
            self._last_used = time.monotonic()

    def idle(self, max_idle: float) -> bool:
        """True when prunable: nobody parked and untouched for `max_idle`
        seconds (the engine's wait-entry GC predicate).  A buffered signal
        does NOT pin the entry — it is a wakeup hint, and every parker in the
        codebase re-checks its condition in a bounded retry loop, so losing a
        stale signal costs one park timeout, never a hang."""
        with self.cond:
            return (
                self._waiters == 0
                and time.monotonic() - self._last_used >= max_idle
            )
