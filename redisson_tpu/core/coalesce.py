"""Adaptive cross-object coalescing plane (ISSUE 2 tentpole).

The batch layer (core/batch.py) and the server's pipelined frames
(server/server.py) both arrive at the same shape of work: a RUN of same-verb
bloom ops against DIFFERENT filters in one pipeline window — the config-5
fan-out (64 per-tenant filters, one BF.MADD64 + one BF.MEXISTS64 each).
Ungrouped that costs one device dispatch per (verb, object); each dispatch
pays the fixed XLA-dispatch + tunnel overhead (~10-100us on-chip, far more
through a tunneled session), so a 64-filter wave pays it 64 times for work
one kernel could do.

This module fuses such a run into ONE kernel call: filters that share
geometry (same m, k, hash, physical plane size) are stacked into a (F, S)
bank on device, every op's keys concatenate into one packed (3, B) transfer
buffer whose first row is the SEGMENT SLOT (which filter each key probes),
and the existing bank kernels (core/kernels.py — flat `slot*stride + idx`
indexing) execute the whole run.  Results scatter back to each issuer by
segment offset.  The stack itself is an HBM-side copy (F*S bytes), cheap
next to F dispatch overheads; adds write each filter's row back under the
same locked_many window that ordered the dispatch.

Semantics preserved exactly:
  * per-issuer results: segment offsets are computed host-side from the
    submitted lengths, so every reply slices back to its op in order;
  * adds: "newly" is evaluated against the window-start plane — identical
    to the single-group semantics for duplicate keys inside one flush; a
    run with the SAME filter named twice under `add` is ineligible (the
    second group must see the first's bits, which one dispatch cannot do);
  * locking: the whole fused dispatch runs under engine.locked_many over
    the touched names (sorted order, deadlock-free), the same exclusion a
    per-group dispatch takes per name.

Ineligible runs (mixed geometry, codec keys, missing records, duplicate add
names, int32 flat-index overflow) raise CoalesceIneligible — callers fall
back to the per-group path, so coalescing is a pure fast path, never a
semantics change.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from redisson_tpu.core import kernels as K
from redisson_tpu.utils import hashing as H


class CoalesceIneligible(Exception):
    """Run cannot fuse; caller must dispatch per group."""


def runs_within_admission(runs, shed_mask) -> List[Tuple[int, int]]:
    """Split each [start, end) coalescible run at QoS shed boundaries
    (ISSUE 10): a shed command never dispatches, so a run spanning one would
    fuse commands the admission decision already refused — and, worse, a
    fused ADD run that partially applied could never be re-dispatched
    (at-most-once).  Runs therefore form per ADMITTED window only: each run
    is cut into its maximal admitted sub-runs, and sub-runs shorter than 2
    fall back to per-command dispatch.  ``shed_mask`` None (fully admitted
    frame) returns ``runs`` unchanged — the disarmed path costs nothing."""
    if shed_mask is None:
        return list(runs)
    out: List[Tuple[int, int]] = []
    for start, end in runs:
        i = start
        while i < end:
            if shed_mask[i]:
                i += 1
                continue
            j = i + 1
            while j < end and not shed_mask[j]:
                j += 1
            if j - i >= 2:
                out.append((i, j))
            i = j
    return out


def plan_subwindows(items: Sequence[int], target: int) -> List[Tuple[int, int]]:
    """Partition one coalescible run into preemptible sub-windows
    (ISSUE 18): given the per-command device-item counts of a run's
    commands, return [start, end) chunks (indices into the run) such that
    each chunk's total stays within ``target`` items — the bound on how
    long one sub-window can occupy its device lane before the next
    preemption point.

    Splits happen at COMMAND boundaries only, never inside one command's
    key batch: each chunk dispatches as a self-contained fused run with
    the standard add-run at-most-once discipline (a failed chunk errors
    per-command and is never re-dispatched; earlier chunks already applied
    and replied — exactly the sub-run semantics ``runs_within_admission``
    already establishes at shed boundaries).  A single command larger than
    ``target`` therefore forms its own oversized chunk: bounding it any
    tighter would require splitting a fused apply mid-batch, which the
    at-most-once contract forbids.

    ``target <= 0`` (splitting disarmed) or a run already within target
    returns the whole run as one chunk — the historical dispatch shape.
    """
    n = len(items)
    if n == 0:
        return []
    if target <= 0 or sum(items) <= target:
        return [(0, n)]
    out: List[Tuple[int, int]] = []
    start = 0
    acc = 0
    for i, it in enumerate(items):
        if i > start and acc + it > target:
            out.append((start, i))
            start = i
            acc = 0
        acc += it
    out.append((start, n))
    return out


def _concat_segments(engine, keys_list) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Concatenate per-op int-key arrays into one preallocated buffer plus an
    aligned segment-slot column.  Returns (slot, keys, lengths)."""
    arrs = []
    for ks in keys_list:
        a = np.asarray(ks)
        if not engine.is_int_batch(a):
            raise CoalesceIneligible("non-integer key batch")
        arrs.append(np.ascontiguousarray(a, np.int64).reshape(-1))
    lengths = [a.shape[0] for a in arrs]
    total = sum(lengths)
    if total == 0:
        raise CoalesceIneligible("empty run")
    keys = np.empty(total, np.int64)
    slot = np.empty(total, np.int32)
    off = 0
    for s, a in enumerate(arrs):
        n = a.shape[0]
        keys[off : off + n] = a
        slot[off : off + n] = s
        off += n
    return slot, keys, lengths


# ACTUAL committed device of a plane (None = uncommitted/host: stacks with
# anything) — the ONE device-detection rule, shared process-wide
from redisson_tpu.core.ioplane import device_of as _plane_device


def _validated_records(engine, names: Sequence[str]):
    """Fetch + geometry-check the run's records.  Caller holds the locks.

    Device check (device-sharded serving, ISSUE 8): every plane in the
    stack must live on ONE device — jnp.stack across committed devices
    would have to gather through the host, which the coalescing plane must
    never do.  The server splits runs per device BEFORE coalescing
    (placement.plan_frame), so a mixed group here only happens mid-slot-
    handoff — the run simply falls back to per-record dispatch (each record
    executes on its own current device), never a host-side gather."""
    recs = []
    m = k = shape = hname = None
    device = None
    for name in names:
        rec = engine.store.get(name)
        if rec is None or rec.kind != "bloom":
            raise CoalesceIneligible(f"'{name}' is not an initialized bloom filter")
        if m is None:
            m, k = rec.meta["m"], rec.meta["k"]
            hname = rec.meta.get("hash")
            shape = rec.arrays["bits"].shape
            device = _plane_device(rec.arrays["bits"])
        elif (
            rec.meta["m"] != m
            or rec.meta["k"] != k
            or rec.meta.get("hash") != hname
            or rec.arrays["bits"].shape != shape
        ):
            raise CoalesceIneligible("mixed filter geometry in run")
        else:
            d = _plane_device(rec.arrays["bits"])
            if d is not None and device is not None and d != device:
                raise CoalesceIneligible(
                    "planes span devices (slot handoff in flight)"
                )
            device = device if device is not None else d
        recs.append(rec)
    if len(names) * shape[0] > K.BANK_MAX_CELLS:
        raise CoalesceIneligible("stacked planes exceed flat int32 index space")
    return recs, m, k


def _pack_window(engine, slot: np.ndarray, keys: np.ndarray, device=None):
    """(slot, keys) -> staged (3, B) uint32 transfer buffer + n_valid.
    Staged through the engine's double-buffered pool (overlap plane): one
    wave's packing overlaps the previous wave's in-flight upload.  With
    placement on, `device` selects that device's LANE pool so two devices'
    waves never contend on one slot pair (ISSUE 8)."""
    n = keys.shape[0]
    b = K.bucket_size(n)
    lo, hi = H.int_keys_to_u32_pair(keys)
    return K.pack_rows(slot, lo, hi, size=b, pool=engine.staging_pool(device)), n


def fused_bloom_contains_async(engine, names: Sequence[str], keys_list):
    """ONE dispatch for a contains run over several same-geometry filters.

    Returns (device bool array over the concatenated window, lengths) —
    slice issuer i's reply at [sum(lengths[:i]), +lengths[i]).  No host
    sync: callers force on their own result path (frame-level gather on
    the server, np.asarray in the batch layer)."""
    slot, keys, lengths = _concat_segments(engine, keys_list)
    tlh, n = _pack_window(
        engine, slot, keys, device=engine.device_for_name(names[0])
    )
    import jax.numpy as jnp

    with engine.locked_many(set(names)):
        recs, m, k = _validated_records(engine, names)
        planes = jnp.stack([r.arrays["bits"] for r in recs])
        found = K.bloom_bank_contains_packed(planes, tlh, K.valid_n(n), k, m)
    return found, lengths


def fused_bloom_add_async(engine, names: Sequence[str], keys_list):
    """ONE dispatch for an add run over several DISTINCT same-geometry
    filters; writes each filter's new plane row back under the run's locks.
    Returns (device newly-added bool array, lengths)."""
    if len(set(names)) != len(names):
        raise CoalesceIneligible(
            "duplicate filter in add run (second group must observe the first)"
        )
    slot, keys, lengths = _concat_segments(engine, keys_list)
    tlh, n = _pack_window(
        engine, slot, keys, device=engine.device_for_name(names[0])
    )
    import jax.numpy as jnp

    with engine.locked_many(set(names)):
        recs, m, k = _validated_records(engine, names)
        planes = jnp.stack([r.arrays["bits"] for r in recs])
        bits2d, newly = K.bloom_bank_add_packed(planes, tlh, K.valid_n(n), k, m)
        for i, rec in enumerate(recs):
            rec.arrays["bits"] = bits2d[i]
            rec.version += 1
    return newly, lengths


def fused_bloom_pair_async(engine, name: str, add_keys, probe_keys):
    """The hot add-then-probe PAIR on one filter as a single fused program
    (kernels.bloom_fused_add_contains): the probe observes the adds, the
    plane stays donated/resident between the scatter and the gather.
    Returns (device newly bool, n_add, device found bool, n_probe)."""
    add_arr = np.asarray(add_keys)
    probe_arr = np.asarray(probe_keys)
    if not (engine.is_int_batch(add_arr) and engine.is_int_batch(probe_arr)):
        raise CoalesceIneligible("non-integer key batch")
    if add_arr.size == 0 or probe_arr.size == 0:
        raise CoalesceIneligible("empty side of fused pair")
    kind_a, lh_a, n_a = engine.pack_keys(add_arr, None)
    kind_p, lh_p, n_p = engine.pack_keys(probe_arr, None)
    if kind_a != "u64" or kind_p != "u64":
        raise CoalesceIneligible("fused pair requires u64 key packing")
    with engine.locked(name):
        rec = engine.store.get(name)
        if rec is None or rec.kind != "bloom":
            raise CoalesceIneligible(f"'{name}' is not an initialized bloom filter")
        m, k = rec.meta["m"], rec.meta["k"]
        bits, newly, found = K.bloom_fused_add_contains(
            rec.arrays["bits"], lh_a, K.valid_n(n_a), lh_p, K.valid_n(n_p), k, m
        )
        rec.arrays["bits"] = bits
        rec.version += 1
    return newly, n_a, found, n_p
