"""Overlapped device I/O plane: double-buffered H2D staging, dispatch-ahead,
demand-driven D2H readback (ISSUE 3 tentpole).

The flush path used to execute stage -> dispatch -> fetch strictly in series:
every window paid a blocking host->device staging barrier AND a blocking
computed-result fetch (~66ms fixed through the tunnel, BENCH_r05's
"computed-result fetch floor") before the next window could even stage.  The
reference never serializes this way — every command is async at the
CommandAsyncExecutor boundary and the wire only waits on results the caller
demanded.  This module is the device-side analog of that contract:

  * **Staging** (`StagingPool`): flush packing fills one of `depth` reusable
    host buffers; the upload of buffer B overlaps the refill of buffer A.  A
    slot is only re-issued once its previous upload has materialized on
    device, so reuse can never corrupt an in-flight copy.
  * **Dispatch-ahead** (`FlushPipeline`): up to `depth` windows stay
    dispatched-but-unfetched; window i+1's upload and kernel overlap window
    i's readback.
  * **Readback futures** (`ReadbackFuture`): kernel outputs stay on device as
    lazy handles; the D2H transfer happens only when a result is actually
    demanded (`result()`), and co-pending futures can drain in ONE grouped
    transfer (`force_all` / `gather_device_results` — the server's
    `_force_lazies` seam generalized).

Disable with ``--no-overlap`` (tpu-server flag) or ``set_overlap(False)`` /
``RTPU_NO_OVERLAP=1`` for A/B measurement: the disabled plane reproduces the
serial stage/dispatch/fetch shape exactly, and results are bit-identical in
both modes (the plane reorders WAITS, never device work — the device stream
stays in-order).

Accounting (`STATS`) counts blocking device syncs and exposed readback time;
the structural contract CI pins (tests/test_perf_smoke.py) is: N flush
windows cost <= N+1 blocking syncs overlapped vs 2N serial.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

# -- global switch ------------------------------------------------------------

_overlap = os.environ.get("RTPU_NO_OVERLAP", "") not in ("1", "true", "yes")


def overlap_enabled() -> bool:
    return _overlap


def set_overlap(on: bool) -> bool:
    """Flip the process-global overlap switch; returns the previous value
    (callers restore it — the A/B discipline of bench.py)."""
    global _overlap
    prev = _overlap
    _overlap = bool(on)
    return prev


_staging_safe: Optional[bool] = None


def staging_reuse_safe() -> bool:
    """Pooled host-buffer reuse requires device_put to COPY.  CPU jax may
    zero-copy ALIAS suitably-aligned numpy memory, so refilling a slot would
    corrupt the "device" value it staged earlier; off-CPU the upload is a
    real DMA copy and reuse is safe.  Cached once per process."""
    global _staging_safe
    if _staging_safe is None:
        try:
            import jax

            _staging_safe = jax.default_backend() != "cpu"
        except Exception:  # noqa: BLE001 — no jax: nothing stages anyway
            _staging_safe = False
    return _staging_safe


# -- blocking-sync + readback accounting --------------------------------------


class IOStats:
    """Process-global counters for the plane's observable costs.

    ``blocking_syncs`` counts every host-side wait on device work the plane
    performs (staging barriers, forced readbacks, grouped gathers) — the
    quantity the structural smoke test bounds.  ``readback_exposed_s``
    accumulates ONLY the readback wall time spent while the device value was
    not yet ready (the un-hidden part); bench.py derives overlap efficiency
    as 1 - exposed/serial_total."""

    __slots__ = ("_lock", "blocking_syncs", "readbacks", "readback_wait_s",
                 "readback_exposed_s", "staging_waits", "barrier_wait_s")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.blocking_syncs = 0
        self.readbacks = 0
        self.readback_wait_s = 0.0
        self.readback_exposed_s = 0.0
        self.staging_waits = 0
        self.barrier_wait_s = 0.0

    def count_sync(self, n: int = 1) -> None:
        with self._lock:
            self.blocking_syncs += n

    def add_barrier(self, wall_s: float) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.barrier_wait_s += wall_s

    def count_staging_wait(self) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.staging_waits += 1

    def add_readback(self, wall_s: float, was_ready: bool) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.readbacks += 1
            self.readback_wait_s += wall_s
            if not was_ready:
                self.readback_exposed_s += wall_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "blocking_syncs": self.blocking_syncs,
                "readbacks": self.readbacks,
                "readback_wait_s": self.readback_wait_s,
                "readback_exposed_s": self.readback_exposed_s,
                "staging_waits": self.staging_waits,
                "barrier_wait_s": self.barrier_wait_s,
            }


STATS = IOStats()


def _is_ready(x) -> bool:
    """True when a device value has materialized (forcing it costs only the
    transfer, no compute wait).  Non-jax values (numpy fallbacks) are always
    ready."""
    f = getattr(x, "is_ready", None)
    if f is None:
        return True
    try:
        return bool(f())
    except Exception:  # noqa: BLE001 — deleted/donated buffer: nothing to wait on
        return True


def barrier(values) -> None:
    """COUNTED blocking device sync: the serial path's explicit
    stage/dispatch drain before a fetch (the `--no-overlap` reference
    shape).  The overlapped path never calls this.  Wall time is recorded
    (STATS.barrier_wait_s) so bench's A/B can attribute the serial path's
    total readback cost: barrier wait + forced fetch."""
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(values)
    STATS.add_barrier(time.perf_counter() - t0)


# -- readback futures ----------------------------------------------------------


class ReadbackFuture:
    """Demand-driven D2H readback handle (the RFuture of the device plane).

    Holds kernel outputs as device references; ``result()`` performs the
    host transfer on first demand (counted, exposed-time attributed) and
    caches.  ``force_all`` primes several futures with ONE grouped transfer
    instead — device references are released either way."""

    __slots__ = ("_device", "_finish", "_value", "_error", "_done")

    def __init__(self, device: Sequence[Any], finish: Optional[Callable] = None):
        self._device: tuple = tuple(device)
        self._finish = finish
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = False

    def done(self) -> bool:
        return self._done

    def ready(self) -> bool:
        """True when result() would not block on device work."""
        return self._done or all(_is_ready(v) for v in self._device)

    def _deliver(self, host: tuple) -> None:
        try:
            self._value = self._finish(host) if self._finish is not None else (
                host[0] if len(host) == 1 else host
            )
        except BaseException as e:  # noqa: BLE001 — surfaced on result()
            self._error = e
        self._done = True
        self._device = ()  # release device memory references

    def result(self):
        if not self._done:
            was_ready = all(_is_ready(v) for v in self._device)
            t0 = time.perf_counter()
            try:
                host = tuple(np.asarray(v) for v in self._device)
            except BaseException as e:  # noqa: BLE001
                STATS.add_readback(time.perf_counter() - t0, was_ready)
                self._error = e
                self._done = True
                self._device = ()
            else:
                STATS.add_readback(time.perf_counter() - t0, was_ready)
                self._deliver(host)
        if self._error is not None:
            raise self._error
        return self._value


def gather_device_results(groups: Sequence[Sequence[Any]]) -> List[tuple]:
    """Fetch every device value of `groups` with ONE device->host transfer:
    bitcast each value to a uint8 byte stream on device, concatenate, pull
    once, split and reinterpret on the host.  Every sync through the tunnel
    costs a fixed ~68ms regardless of size, so G groups at one transfer each
    would pay G floors — this path pays ~one.  Constraint: each device
    value's dtype must round-trip via ``np.dtype(a.dtype.name)``."""
    import jax
    import jax.numpy as jnp

    flat = []  # (device uint8 stream, host dtype, orig shape, was_bool)
    index: List[List[int]] = []  # per group: flat positions
    for group in groups:
        pos = []
        for arr in group:
            a = jnp.asarray(arr)
            was_bool = a.dtype == jnp.bool_
            if was_bool:
                b = a.astype(jnp.uint8)  # exact: values are 0/1
            elif a.dtype == jnp.uint8:
                b = a
            else:
                b = jax.lax.bitcast_convert_type(a, jnp.uint8)
            pos.append(len(flat))
            flat.append((
                jnp.ravel(b),
                np.dtype(a.dtype.name if not was_bool else "uint8"),
                a.shape,
                was_bool,
            ))
        index.append(pos)
    parts = [f[0] for f in flat]
    sizes = [int(p.shape[0]) for p in parts]
    if not parts:
        return [() for _ in groups]
    STATS.count_sync()
    if len(parts) == 1:
        merged = np.asarray(parts[0])
    else:
        merged = np.asarray(jnp.concatenate(parts))  # THE one transfer
    chunks = np.split(merged, np.cumsum(sizes)[:-1]) if len(parts) > 1 else [merged]
    host: List[Any] = []
    for chunk, (_p, dtype, shape, was_bool) in zip(chunks, flat):
        v = np.ascontiguousarray(chunk).view(dtype).reshape(shape)
        host.append(v.astype(bool) if was_bool else v)
    return [tuple(host[i] for i in pos) for pos in index]


def force_all(futures: Sequence[ReadbackFuture]) -> None:
    """Materialize several ReadbackFutures with ONE grouped transfer (the
    frame-level drain the server's reply path uses; the embedded Batch
    drains its pending groups through here too)."""
    todo = [f for f in futures if not f.done()]
    if not todo:
        return
    try:
        host_groups = gather_device_results([f._device for f in todo])
    except Exception:  # noqa: BLE001 — grouped path failed; force singly
        for f in todo:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — error lands on THAT future
                pass
        return
    for f, host in zip(todo, host_groups):
        f._deliver(host)


# -- double-buffered host staging ----------------------------------------------


class _StageSlot:
    __slots__ = ("buf", "staged", "busy")

    def __init__(self, buf: np.ndarray):
        self.buf = buf
        self.staged = None  # device handle last uploaded from this buffer
        self.busy = False


class StagingPool:
    """Double-buffered host staging buffers for flush packing.

    ``acquire(shape, dtype)`` hands out a zeroed host view backed by one of
    ``depth`` reusable slots; ``commit(slot, staged)`` pairs the slot with
    the device copy made from it and frees it.  A slot is re-issued only
    once its previous upload has materialized (a real wait is counted as a
    blocking sync) — refilling buffer A therefore overlaps buffer B's
    in-flight upload, and reuse can never scribble over bytes the DMA is
    still reading.  When every slot is checked out (deep concurrent
    fan-out) acquire degrades to a fresh one-off allocation (slot=None):
    correctness never depends on pool depth."""

    def __init__(self, depth: int = 2):
        self._lock = threading.Lock()
        self._slots: List[_StageSlot] = []
        self._depth = max(1, depth)
        self.reuses = 0  # observability (ResourceCensus-friendly gauges)
        self.oneoffs = 0

    def acquire(self, shape, dtype=np.uint32) -> Tuple[np.ndarray, Optional[_StageSlot]]:
        want = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        slot = None
        with self._lock:
            for s in self._slots:
                if not s.busy:
                    s.busy = True
                    slot = s
                    break
            if slot is None and len(self._slots) < self._depth:
                slot = _StageSlot(np.empty(max(want, 1), np.uint8))
                slot.busy = True
                self._slots.append(slot)
        if slot is None:
            self.oneoffs += 1
            return np.zeros(shape, dtype), None
        staged, slot.staged = slot.staged, None
        if staged is not None and not _is_ready(staged):
            # the double-buffer boundary: the slot's previous upload is
            # still in flight — wait (counted) before touching its bytes
            import jax

            STATS.count_staging_wait()
            jax.block_until_ready(staged)
        if slot.buf.nbytes < want:
            slot.buf = np.empty(want, np.uint8)
        self.reuses += 1
        view = slot.buf[:want].view(dtype).reshape(shape)
        view[...] = 0
        return view, slot

    def commit(self, slot: Optional[_StageSlot], staged):
        """Record the device handle uploaded from `slot` and free the slot.
        Returns `staged` for call-site chaining; slot=None (one-off buffer)
        is a no-op."""
        if slot is not None:
            with self._lock:
                slot.staged = staged
                slot.busy = False
        return staged

    def release(self, slot: Optional[_StageSlot]) -> None:
        """Abandon a slot without an upload (error paths)."""
        if slot is not None:
            with self._lock:
                slot.busy = False

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()

    def slot_count(self) -> int:
        with self._lock:
            return len(self._slots)


# -- dispatch-ahead flush driver -----------------------------------------------


class FlushPipeline:
    """stage -> dispatch -> fetch driver for a stream of flush windows — the
    plane's A/B harness (bench.py's overlap sub-measurement and the CPU
    structural smoke both drive it).

    ``submit(fn)``: ``fn()`` stages + dispatches ONE window and returns
    ``(device_values, finish)`` with ``finish(host_tuple) -> result``.

      * overlap on: returns a ReadbackFuture immediately; at most ``depth``
        windows stay un-forced (the dispatch-ahead bound) — submitting
        window depth+1 forces the oldest, whose readback by then overlapped
        the younger windows' staging and dispatch.  N windows cost N counted
        readback syncs (+ at most one staging wait): the <= N+1 contract.
      * overlap off: the strict serial reference — a counted barrier on the
        window's device values (the stage/dispatch drain) then an immediate
        forced fetch: exactly 2 blocking syncs per window, the 2N shape.
    """

    def __init__(self, *, overlap: Optional[bool] = None, depth: int = 2):
        self.overlap = overlap_enabled() if overlap is None else bool(overlap)
        self.depth = max(1, depth)
        self._ring: List[ReadbackFuture] = []

    def submit(self, fn: Callable[[], Tuple[Sequence[Any], Optional[Callable]]]) -> ReadbackFuture:
        device, finish = fn()
        fut = ReadbackFuture(device, finish)
        if not self.overlap:
            barrier(tuple(device))
            try:
                fut.result()
            except Exception:  # noqa: BLE001 — error stays on the future
                pass
            return fut
        self._ring.append(fut)
        if len(self._ring) > self.depth:
            oldest = self._ring.pop(0)
            try:
                oldest.result()
            except Exception:  # noqa: BLE001
                pass
        return fut

    def drain(self) -> None:
        """Force every still-pending window (end of the stream)."""
        ring, self._ring = self._ring, []
        for fut in ring:
            try:
                fut.result()
            except Exception:  # noqa: BLE001
                pass
