"""Overlapped device I/O plane: double-buffered H2D staging, dispatch-ahead,
demand-driven D2H readback (ISSUE 3 tentpole).

The flush path used to execute stage -> dispatch -> fetch strictly in series:
every window paid a blocking host->device staging barrier AND a blocking
computed-result fetch (~66ms fixed through the tunnel, BENCH_r05's
"computed-result fetch floor") before the next window could even stage.  The
reference never serializes this way — every command is async at the
CommandAsyncExecutor boundary and the wire only waits on results the caller
demanded.  This module is the device-side analog of that contract:

  * **Staging** (`StagingPool`): flush packing fills one of `depth` reusable
    host buffers; the upload of buffer B overlaps the refill of buffer A.  A
    slot is only re-issued once its previous upload has materialized on
    device, so reuse can never corrupt an in-flight copy.
  * **Dispatch-ahead** (`FlushPipeline`): up to `depth` windows stay
    dispatched-but-unfetched; window i+1's upload and kernel overlap window
    i's readback.
  * **Readback futures** (`ReadbackFuture`): kernel outputs stay on device as
    lazy handles; the D2H transfer happens only when a result is actually
    demanded (`result()`), and co-pending futures can drain in ONE grouped
    transfer (`force_all` / `gather_device_results` — the server's
    `_force_lazies` seam generalized).

Disable with ``--no-overlap`` (tpu-server flag) or ``set_overlap(False)`` /
``RTPU_NO_OVERLAP=1`` for A/B measurement: the disabled plane reproduces the
serial stage/dispatch/fetch shape exactly, and results are bit-identical in
both modes (the plane reorders WAITS, never device work — the device stream
stays in-order).

Accounting (`STATS`) counts blocking device syncs and exposed readback time;
the structural contract CI pins (tests/test_perf_smoke.py) is: N flush
windows cost <= N+1 blocking syncs overlapped vs 2N serial.
"""
from __future__ import annotations

import functools
import os
import threading
import time
import weakref
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

# tracing plane (observe/trace.py, ISSUE 12): every site below guards on the
# process-global `_obs._tracer` — disarmed cost is one global load plus an
# `is None` branch (the chaos-hook zero-cost discipline, asserted at the
# allocator level by tests/test_observe.py)
from redisson_tpu.observe import trace as _obs

# device chaos plane (ISSUE 19): the lane dispatch/readback chokepoints
# consult the SAME process-global fault plane net/client.py hosts, under the
# same discipline — disarmed cost is one global load plus an `is None`
# branch, asserted at the allocator level by tests/test_perf_smoke.py
# against these guard lines too
from redisson_tpu.net import client as _net

# -- global switch ------------------------------------------------------------

_overlap = os.environ.get("RTPU_NO_OVERLAP", "") not in ("1", "true", "yes")


def overlap_enabled() -> bool:
    return _overlap


def set_overlap(on: bool) -> bool:
    """Flip the process-global overlap switch; returns the previous value
    (callers restore it — the A/B discipline of bench.py)."""
    global _overlap
    prev = _overlap
    _overlap = bool(on)
    return prev


# process-global flush-window deadline (ISSUE 10): FlushPipelines built
# without an explicit deadline_s follow this default, so CONFIG SET
# qos-interactive-deadline-ms arms the deadline-triggered window close for
# every pipeline constructed afterwards (same process-global discipline as
# set_overlap).  None = deadline trigger off (the historical shape).
_window_deadline_s: Optional[float] = None


def set_window_deadline(seconds: Optional[float]) -> Optional[float]:
    """Set the default FlushPipeline window deadline; returns the previous
    value (callers restore it — the A/B discipline)."""
    global _window_deadline_s
    prev = _window_deadline_s
    _window_deadline_s = seconds
    return prev


def window_deadline() -> Optional[float]:
    return _window_deadline_s


# -- bulk-window preemption (ISSUE 18) ----------------------------------------
#
# The PR 9 QoS plane bounds interactive latency at ADMISSION, but once a big
# coalesced bulk window is in a lane it holds the device stream end to end —
# the tracing plane shows the interactive wait sitting in `stage`, not `qos`.
# Two mechanisms close that gap, both behind this one switch:
#
#   * sub-windows: an oversized bulk run splits into bounded chunks (target
#     items via set_bulk_subwindow_items / CONFIG SET qos-bulk-subwindow-
#     items), each its own self-contained fused dispatch through the lane,
#     with a PREEMPTION POINT between chunks — a waiting interactive frame
#     jumps the inter-sub-window boundary instead of the whole drained
#     window (DeviceLane.preempt_point);
#   * per-class streams: an interactive dispatch occupies the lane's
#     INTERACTIVE stream (its own gate + staging slot + dispatch queue), so
#     its kernel launches without queuing behind the bulk stream's
#     occupancy gate at all.
#
# Disarm with RTPU_NO_PREEMPT=1 / set_preempt(False) / tpu-server
# --no-preempt: the disarmed plane reproduces the exact single-stream,
# unsplit-window PR 9 behavior, bit-identically (splitting moves only WHERE
# the lane gate is released; per-op results are computed by the same
# kernels either way).

_preempt = os.environ.get("RTPU_NO_PREEMPT", "") not in ("1", "true", "yes")


def preempt_enabled() -> bool:
    return _preempt


def set_preempt(on: bool) -> bool:
    """Flip the process-global preemption switch; returns the previous
    value (callers restore it — the A/B discipline of bench.py)."""
    global _preempt
    prev = _preempt
    _preempt = bool(on)
    return prev


# target device items per bulk sub-window (0 = splitting off, the
# historical whole-window dispatch).  CONFIG SET qos-bulk-subwindow-items
# pushes here so every lane's dispatch path shares one knob.
_bulk_subwindow_items = 0


def bulk_subwindow_items() -> int:
    return _bulk_subwindow_items


def set_bulk_subwindow_items(n: int) -> int:
    """Set the sub-window split target; returns the previous value."""
    global _bulk_subwindow_items
    prev = _bulk_subwindow_items
    _bulk_subwindow_items = max(0, int(n))
    return prev


# -- lane watchdog (ISSUE 19) --------------------------------------------------
#
# `ReadbackFuture.result()` historically blocked FOREVER on a transfer that
# never materializes (hung DMA, preempted device) — a wedged writer task
# holding a staging slot and a connection.  The watchdog bounds that wait:
# armed (CONFIG SET lane-watchdog-ms > 0) a readback that has not
# materialized within the bound raises `LaneWatchdogTimeout`, which the
# server dispatch layer converts to a clean retryable -TRYAGAIN and the
# lane's fault ledger counts toward quarantine.  0 = off, the historical
# unbounded-wait shape, bit-identical replies.

_lane_watchdog_s = 0.0


def lane_watchdog_ms() -> int:
    return int(_lane_watchdog_s * 1000)


def set_lane_watchdog_ms(ms: int) -> int:
    """Arm/disarm the readback lane watchdog (0 = off); returns the
    previous value in ms (callers restore it — the A/B discipline)."""
    global _lane_watchdog_s
    prev = int(_lane_watchdog_s * 1000)
    _lane_watchdog_s = max(0, int(ms)) / 1000.0
    return prev


# consecutive device faults/timeouts that flip a lane to QUARANTINED
_quarantine_after = 3


def quarantine_after() -> int:
    return _quarantine_after


def set_quarantine_after(n: int) -> int:
    """Set the consecutive-fault quarantine threshold; returns the
    previous value."""
    global _quarantine_after
    prev = _quarantine_after
    _quarantine_after = max(1, int(n))
    return prev


class LaneWatchdogTimeout(RuntimeError):
    """A device readback exceeded the armed lane-watchdog bound — the
    frame fails retryably (-TRYAGAIN) instead of wedging its writer."""


def is_retryable_device_fault(e: BaseException) -> bool:
    """Device-layer failure shapes the server dispatch layer converts to a
    clean retryable ``-TRYAGAIN``: the lane-watchdog timeout and the
    XlaRuntimeError transient-runtime prefixes (a failed kernel launch, a
    preempted/unavailable device).  Matched on the message, never the
    class, so the chaos plane's RuntimeError fallback rides the same path.
    RESOURCE_EXHAUSTED is deliberately NOT here — HBM exhaustion takes the
    -OOM degradation path (services/vector.DeviceOomError)."""
    if isinstance(e, LaneWatchdogTimeout):
        return True
    if not isinstance(e, RuntimeError):
        return False
    return str(e).lstrip().startswith(
        ("INTERNAL", "UNAVAILABLE", "ABORTED", "CANCELLED",
         "DEADLINE_EXCEEDED")
    )


# which lane stream the CURRENT THREAD's dispatch occupies ("interactive"
# while an interactive _LaneOccupancy is held): engine.staging_pool reads
# this to hand the interactive fast path its own staging slot without
# threading the QoS class through every pack call
_stream_tls = threading.local()


def current_stream() -> Optional[str]:
    return getattr(_stream_tls, "stream", None)


_staging_safe: Optional[bool] = None


def staging_reuse_safe() -> bool:
    """Pooled host-buffer reuse requires device_put to COPY.  CPU jax may
    zero-copy ALIAS suitably-aligned numpy memory, so refilling a slot would
    corrupt the "device" value it staged earlier; off-CPU the upload is a
    real DMA copy and reuse is safe.  Cached once per process."""
    global _staging_safe
    if _staging_safe is None:
        try:
            import jax

            _staging_safe = jax.default_backend() != "cpu"
        except Exception:  # noqa: BLE001 — no jax: nothing stages anyway
            _staging_safe = False
    return _staging_safe


# -- blocking-sync + readback accounting --------------------------------------


class IOStats:
    """Process-global counters for the plane's observable costs.

    ``blocking_syncs`` counts every host-side wait on device work the plane
    performs (staging barriers, forced readbacks, grouped gathers) — the
    quantity the structural smoke test bounds.  ``readback_exposed_s``
    accumulates ONLY the readback wall time spent while the device value was
    not yet ready (the un-hidden part); bench.py derives overlap efficiency
    as 1 - exposed/serial_total.

    ``d2d_colocations`` / ``host_colocations`` audit the cross-device merge
    discipline (ISSUE 8): moving a device value onto another device for an
    on-device merge must be a direct device transfer (``colocate``), never a
    host round trip — the soak/tests assert host_colocations stays 0."""

    __slots__ = ("_lock", "blocking_syncs", "readbacks", "readback_wait_s",
                 "readback_exposed_s", "staging_waits", "barrier_wait_s",
                 "d2d_colocations", "host_colocations", "sharded_knn_merges")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.blocking_syncs = 0
        self.readbacks = 0
        self.readback_wait_s = 0.0
        self.readback_exposed_s = 0.0
        self.staging_waits = 0
        self.barrier_wait_s = 0.0
        self.d2d_colocations = 0
        self.host_colocations = 0
        self.sharded_knn_merges = 0

    def count_sync(self, n: int = 1) -> None:
        with self._lock:
            self.blocking_syncs += n

    def add_barrier(self, wall_s: float) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.barrier_wait_s += wall_s

    def count_staging_wait(self) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.staging_waits += 1

    def add_readback(self, wall_s: float, was_ready: bool) -> None:
        with self._lock:
            self.blocking_syncs += 1
            self.readbacks += 1
            self.readback_wait_s += wall_s
            if not was_ready:
                self.readback_exposed_s += wall_s

    def count_colocation(self, via_host: bool) -> None:
        with self._lock:
            if via_host:
                self.host_colocations += 1
            else:
                self.d2d_colocations += 1

    def count_sharded_merge(self) -> None:
        """One on-device sharded-KNN top-k merge ran (ISSUE 15) — paired
        with host_colocations == 0 this proves the cross-shard reduce
        stayed on the interconnect (the vector soak asserts both)."""
        with self._lock:
            self.sharded_knn_merges += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "blocking_syncs": self.blocking_syncs,
                "readbacks": self.readbacks,
                "readback_wait_s": self.readback_wait_s,
                "readback_exposed_s": self.readback_exposed_s,
                "staging_waits": self.staging_waits,
                "barrier_wait_s": self.barrier_wait_s,
                "d2d_colocations": self.d2d_colocations,
                "host_colocations": self.host_colocations,
                "sharded_knn_merges": self.sharded_knn_merges,
            }


STATS = IOStats()

# -- per-device stats (ISSUE 8: IOStats split per device) ---------------------
# One IOStats per local device id, lazily created: the per-device serving
# lanes attribute their gathers/syncs here IN ADDITION to the global STATS
# (the global counters keep their exact historical semantics — every
# structural contract pinned against STATS is unchanged).

_DEVICE_STATS: dict = {}
_DEVICE_STATS_LOCK = threading.Lock()


def device_stats(dev_id: int) -> IOStats:
    with _DEVICE_STATS_LOCK:
        st = _DEVICE_STATS.get(dev_id)
        if st is None:
            st = _DEVICE_STATS[dev_id] = IOStats()
        return st


def device_stats_snapshot() -> dict:
    with _DEVICE_STATS_LOCK:
        stats = dict(_DEVICE_STATS)
    return {d: s.snapshot() for d, s in stats.items()}


def reset_device_stats() -> None:
    with _DEVICE_STATS_LOCK:
        for s in _DEVICE_STATS.values():
            s.reset()


def device_of(value):
    """Single committed device of a jax array, else None (numpy values,
    uncommitted arrays, multi-device sharded planes)."""
    devs = getattr(value, "devices", None)
    if devs is None:
        return None
    try:
        ds = devs()
    except TypeError:  # pragma: no cover
        return None
    return next(iter(ds)) if len(ds) == 1 else None


def _device_id_of(value) -> Optional[int]:
    """Single committed device id of a jax array, else None (numpy values
    and multi-device sharded arrays)."""
    devs = getattr(value, "devices", None)
    if devs is None:
        return None
    try:
        ds = devs()
    except TypeError:  # pragma: no cover
        return None
    if len(ds) != 1:
        return None
    return next(iter(ds)).id


def colocate(value, device):
    """Move a device value onto `device` WITHOUT a host round trip: the
    cross-device merge primitive (HLL PFMERGE/PFCOUNT across slots,
    MapReduce chunk-merge, BITOP across records).  On TPU this is an ICI
    device-to-device copy — the same interconnect the parallel/ mesh
    collectives ride; the host fallback exists only for exotic transfer
    failures and is COUNTED so the zero-host-gather contract is auditable
    (STATS.host_colocations)."""
    if device is None:
        return value
    devs = getattr(value, "devices", None)
    if devs is None:
        return value  # host value: the dispatch will stage it where needed
    try:
        if devs() == {device}:
            return value
    except TypeError:  # pragma: no cover
        return value
    import jax

    try:
        out = jax.device_put(value, device)
        STATS.count_colocation(via_host=False)
        return out
    except Exception:  # noqa: BLE001 — transfer path unavailable: go via host
        out = jax.device_put(np.asarray(value), device)
        STATS.count_colocation(via_host=True)
        return out


def _is_ready(x) -> bool:
    """True when a device value has materialized (forcing it costs only the
    transfer, no compute wait).  Non-jax values (numpy fallbacks) are always
    ready."""
    f = getattr(x, "is_ready", None)
    if f is None:
        return True
    try:
        return bool(f())
    except Exception:  # noqa: BLE001 — deleted/donated buffer: nothing to wait on
        return True


def barrier(values) -> None:
    """COUNTED blocking device sync: the serial path's explicit
    stage/dispatch drain before a fetch (the `--no-overlap` reference
    shape).  The overlapped path never calls this.  Wall time is recorded
    (STATS.barrier_wait_s) so bench's A/B can attribute the serial path's
    total readback cost: barrier wait + forced fetch."""
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(values)
    wall = time.perf_counter() - t0
    STATS.add_barrier(wall)
    for dev_id in {
        d for d in (_device_id_of(v) for v in values) if d is not None
    }:
        device_stats(dev_id).add_barrier(wall)


# -- readback futures ----------------------------------------------------------


class ReadbackFuture:
    """Demand-driven D2H readback handle (the RFuture of the device plane).

    Holds kernel outputs as device references; ``result()`` performs the
    host transfer on first demand (counted, exposed-time attributed) and
    caches.  ``force_all`` primes several futures with ONE grouped transfer
    instead — device references are released either way."""

    __slots__ = ("_device", "_finish", "_value", "_error", "_done")

    def __init__(self, device: Sequence[Any], finish: Optional[Callable] = None):
        self._device: tuple = tuple(device)
        self._finish = finish
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = False

    def done(self) -> bool:
        return self._done

    def ready(self) -> bool:
        """True when result() would not block on device work."""
        return self._done or all(_is_ready(v) for v in self._device)

    def _deliver(self, host: tuple) -> None:
        try:
            self._value = self._finish(host) if self._finish is not None else (
                host[0] if len(host) == 1 else host
            )
        except BaseException as e:  # noqa: BLE001 — surfaced on result()
            self._error = e
        self._done = True
        self._device = ()  # release device memory references

    def _chaos_stall(self, plane, dev_ids, was_ready: bool) -> bool:
        """Apply an injected hung-transfer stall (device_hang).  With the
        watchdog armed a stall past the bound waits only the bound and
        trips; otherwise the transfer just takes `stall` seconds — the
        pre-watchdog shape, bounded so tests terminate.  Returns the
        (possibly demoted) was_ready flag."""
        stall = 0.0
        for d in dev_ids:
            s = plane.on_device_readback(d)
            if s > stall:
                stall = s
        if stall <= 0.0:
            return was_ready
        bound = _lane_watchdog_s
        if bound > 0.0 and stall > bound:
            time.sleep(bound)
            self._trip(dev_ids, bound)
        else:
            time.sleep(stall)
        return False

    def _wait_ready(self, bound: float) -> bool:
        """Bounded poll for device materialization (the armed watchdog's
        wait): True when every value is ready within `bound` seconds."""
        deadline = time.monotonic() + bound
        while not all(_is_ready(v) for v in self._device):
            left = deadline - time.monotonic()
            if left <= 0.0:
                return False
            time.sleep(min(0.002, left))
        return True

    def _trip(self, dev_ids, wall: float) -> None:
        """The watchdog fired: account the (bounded) wait, attribute a
        timeout fault to every involved lane, and fail this future with
        `LaneWatchdogTimeout` — retryable, never a wedged writer."""
        STATS.add_readback(wall, False)
        for d in dev_ids:
            device_stats(d).add_readback(wall, False)
            note_device_fault(d, "watchdog_timeout")
        if _obs._tracer is not None:
            cur = _obs.current_trace()
            if cur is not None:
                now = time.monotonic()
                cur.add_span(
                    "readback", now - wall, now,
                    blocking=1, grouped=0, timeout=1,
                )
        devs = ", ".join(str(d) for d in sorted(dev_ids)) or "?"
        self._error = LaneWatchdogTimeout(
            f"readback exceeded lane-watchdog bound "
            f"({lane_watchdog_ms()}ms) on device(s) {devs}"
        )
        self._done = True
        self._device = ()

    def _guard(self, plane, bound: float) -> None:
        """Armed-only detection gate shared by ``result()`` and
        ``force_all``: applies any injected hung-transfer stall, then
        enforces the lane-watchdog bound on the device wait.  Never called
        on the disarmed path (no plane, watchdog off)."""
        was_ready = all(_is_ready(v) for v in self._device)
        dev_ids = {
            d for d in (_device_id_of(v) for v in self._device)
            if d is not None
        }
        t0 = time.perf_counter()
        if plane is not None:
            was_ready = self._chaos_stall(plane, dev_ids, was_ready)
        if (not self._done and bound > 0.0 and not was_ready
                and not self._wait_ready(bound)):
            self._trip(dev_ids, time.perf_counter() - t0)

    def result(self):
        if not self._done:
            plane = _net._fault_plane
            bound = _lane_watchdog_s
            if plane is not None or bound > 0.0:
                self._guard(plane, bound)
        if not self._done:
            was_ready = all(_is_ready(v) for v in self._device)
            dev_ids = {
                d for d in (_device_id_of(v) for v in self._device)
                if d is not None
            }
            t0 = time.perf_counter()
            try:
                host = tuple(np.asarray(v) for v in self._device)
            except BaseException as e:  # noqa: BLE001
                STATS.add_readback(time.perf_counter() - t0, was_ready)
                for dev_id in dev_ids:
                    note_device_fault(dev_id, "readback_error")
                self._error = e
                self._done = True
                self._device = ()
            else:
                wall = time.perf_counter() - t0
                STATS.add_readback(wall, was_ready)
                for dev_id in dev_ids:  # per-lane sync ledger (ISSUE 8)
                    device_stats(dev_id).add_readback(wall, was_ready)
                    note_device_ok(dev_id)
                if _obs._tracer is not None:
                    cur = _obs.current_trace()
                    if cur is not None:
                        # this frame PAID a blocking sync iff the device
                        # value had not materialized when force hit it
                        now = time.monotonic()
                        cur.add_span(
                            "readback", now - wall, now,
                            blocking=int(not was_ready), grouped=0,
                        )
                self._deliver(host)
        if self._error is not None:
            raise self._error
        return self._value


_GATHER_POOL = None
_GATHER_POOL_LOCK = threading.Lock()


def _gather_pool():
    """Small shared pool for CONCURRENT per-device d2h fetches: with the
    slot table device-sharded (ISSUE 8), one frame's results live on
    several devices and cannot concatenate into one stream — fetching the
    per-device sub-streams in parallel overlaps their transfer latencies
    (on the tunnel each sync costs its fixed floor REGARDLESS of size, so
    serializing D fetches would pay D floors)."""
    global _GATHER_POOL
    with _GATHER_POOL_LOCK:
        if _GATHER_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _GATHER_POOL = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="rtpu-d2h"
            )
        return _GATHER_POOL


def _readback_guard(dev_id: Optional[int], parts: Sequence[Any]) -> None:
    """Armed-only readback gate for the grouped per-device fetch (the
    serving path's ONE transfer per device): applies any injected
    hung-transfer stall and enforces the lane-watchdog bound before the
    blocking transfer starts.  Raises ``LaneWatchdogTimeout`` (retryable)
    with the fault attributed to the lane.  Disarmed cost: one global
    load + one float compare, then return."""
    plane = _net._fault_plane
    bound = _lane_watchdog_s
    if (plane is None and bound <= 0.0) or dev_id is None:
        return
    stall = 0.0
    if plane is not None:
        stall = plane.on_device_readback(dev_id)
    if stall > 0.0:
        if bound > 0.0 and stall > bound:
            time.sleep(bound)
            note_device_fault(dev_id, "watchdog_timeout")
            raise LaneWatchdogTimeout(
                f"readback exceeded lane-watchdog bound "
                f"({lane_watchdog_ms()}ms) on device(s) {dev_id}"
            )
        time.sleep(stall)
        return
    if bound > 0.0:
        deadline = time.monotonic() + bound
        while not all(_is_ready(p) for p in parts):
            left = deadline - time.monotonic()
            if left <= 0.0:
                note_device_fault(dev_id, "watchdog_timeout")
                raise LaneWatchdogTimeout(
                    f"readback exceeded lane-watchdog bound "
                    f"({lane_watchdog_ms()}ms) on device(s) {dev_id}"
                )
            time.sleep(min(0.002, left))


def gather_device_results(groups: Sequence[Sequence[Any]]) -> List[tuple]:
    """Fetch every device value of `groups` with ONE device->host transfer
    PER DEVICE: bitcast each value to a uint8 byte stream on device,
    concatenate per device, pull each device's merged stream (concurrently
    when results span several devices), split and reinterpret on the host.
    Every sync through the tunnel costs a fixed ~68ms regardless of size,
    so G groups at one transfer each would pay G floors — this path pays
    ~one per touched device, and the per-device fetches overlap.
    Constraint: each device value's dtype must round-trip via
    ``np.dtype(a.dtype.name)``."""
    import jax
    import jax.numpy as jnp

    flat = []  # (device uint8 stream, host dtype, orig shape, was_bool)
    index: List[List[int]] = []  # per group: flat positions
    for group in groups:
        pos = []
        for arr in group:
            a = jnp.asarray(arr)
            was_bool = a.dtype == jnp.bool_
            if was_bool:
                b = a.astype(jnp.uint8)  # exact: values are 0/1
            elif a.dtype == jnp.uint8:
                b = a
            else:
                b = jax.lax.bitcast_convert_type(a, jnp.uint8)
            pos.append(len(flat))
            flat.append((
                jnp.ravel(b),
                np.dtype(a.dtype.name if not was_bool else "uint8"),
                a.shape,
                was_bool,
            ))
        index.append(pos)
    if not flat:
        return [() for _ in groups]
    # bucket flat positions by committed device: cross-device streams can
    # neither concatenate nor ride one transfer — each device gets its own
    # merged stream (device-sharded serving, ISSUE 8).  The common single-
    # device case degenerates to exactly the historical one-transfer shape.
    buckets: "dict[Optional[int], List[int]]" = {}
    for fi, (part, _d, _s, _b) in enumerate(flat):
        buckets.setdefault(_device_id_of(part), []).append(fi)

    host: List[Any] = [None] * len(flat)

    def fetch_bucket(dev_id, fis) -> None:
        parts = [flat[fi][0] for fi in fis]
        _readback_guard(dev_id, parts)
        sizes = [int(p.shape[0]) for p in parts]
        STATS.count_sync()
        if dev_id is not None:
            device_stats(dev_id).count_sync()
        if len(parts) == 1:
            merged = np.asarray(parts[0])
            chunks = [merged]
        else:
            merged = np.asarray(jnp.concatenate(parts))  # one transfer/device
            chunks = np.split(merged, np.cumsum(sizes)[:-1])
        for fi, chunk in zip(fis, chunks):
            _p, dtype, shape, was_bool = flat[fi]
            v = np.ascontiguousarray(chunk).view(dtype).reshape(shape)
            host[fi] = v.astype(bool) if was_bool else v

    items = list(buckets.items())
    if len(items) == 1:
        fetch_bucket(*items[0])
    else:
        futs = [
            _gather_pool().submit(fetch_bucket, dev_id, fis)
            for dev_id, fis in items
        ]
        for f in futs:
            f.result()  # surface the first failure (caller falls back)
    return [tuple(host[i] for i in pos) for pos in index]


@functools.lru_cache(maxsize=256)
def _scatter_fn(sig: tuple):
    """Jitted on-device unpack for scatter_host_arrays: slice the merged
    uint8 stream at static offsets, bitcast each piece back to its dtype,
    reshape — one compile per layout signature (the exact inverse of the
    gather path's bitcast/concat)."""
    import jax
    import jax.numpy as jnp

    def unpack(stream):
        out = []
        for off, nbytes, dtype_name, shape, was_bool in sig:
            piece = jax.lax.slice_in_dim(stream, off, off + nbytes)
            dt = np.dtype(dtype_name)
            if was_bool:
                out.append(piece.astype(jnp.bool_).reshape(shape))
            elif dt == np.uint8:
                out.append(piece.reshape(shape))
            else:
                n = nbytes // dt.itemsize
                out.append(
                    jax.lax.bitcast_convert_type(
                        piece.reshape(n, dt.itemsize), dt
                    ).reshape(shape)
                )
        return tuple(out)

    return jax.jit(unpack)


def scatter_host_arrays(arrays: dict, device, pool: "Optional[StagingPool]" = None
                        ) -> dict:
    """Upload a dict of host arrays to `device` with ONE host->device
    transfer — the inverse of gather_device_results: view every array as a
    uint8 byte stream (bool via uint8, values 0/1), pack them into one
    merged host buffer (through the lane's double-buffered staging pool
    when one is armed), upload the merged stream once, then split/bitcast/
    reshape entirely on device (jitted, one compile per layout signature).
    Returns {key: committed jax.Array on `device`}.  Same constraint as
    the gather path: each dtype must round-trip via ``np.dtype(a.dtype
    .name)`` — callers fall back to per-array device_put on any raise."""
    import jax

    keys = sorted(arrays)
    sig = []
    chunks = []
    off = 0
    for k in keys:
        a = np.asarray(arrays[k])
        np.dtype(a.dtype.name)  # raises on non-round-tripping dtypes
        was_bool = a.dtype == np.bool_
        b = a.astype(np.uint8) if was_bool else a
        stream = np.ascontiguousarray(b).view(np.uint8).ravel()
        sig.append((off, int(stream.size), a.dtype.name, tuple(a.shape),
                    was_bool))
        chunks.append(stream)
        off += int(stream.size)
    if off == 0:  # nothing but empty planes: placement still applies
        return {k: jax.device_put(np.asarray(arrays[k]), device) for k in keys}
    if pool is not None:
        buf, slot = pool.acquire((off,), np.uint8)
    else:
        buf, slot = np.empty(off, np.uint8), None
    pos = 0
    for stream in chunks:
        buf[pos:pos + stream.size] = stream
        pos += stream.size
    merged = jax.device_put(buf, device)
    if pool is not None:
        pool.commit(slot, merged)
    parts = _scatter_fn(tuple(sig))(merged)
    return dict(zip(keys, parts))


def force_all(futures: Sequence[ReadbackFuture]) -> None:
    """Materialize several ReadbackFutures with ONE grouped transfer (the
    frame-level drain the server's reply path uses; the embedded Batch
    drains its pending groups through here too)."""
    todo = [f for f in futures if not f.done()]
    if not todo:
        return
    # the SAME detection gate result() applies: injected hung-transfer
    # stalls land here too, and the armed lane watchdog bounds the grouped
    # drain — a wedged device fails its futures with LaneWatchdogTimeout
    # instead of wedging the whole reply frame.  Disarmed cost: one global
    # load + one float compare.
    plane = _net._fault_plane
    bound = _lane_watchdog_s
    if plane is not None or bound > 0.0:
        for f in todo:
            f._guard(plane, bound)
        todo = [f for f in todo if not f.done()]  # tripped: error delivered
        if not todo:
            return
    try:
        host_groups = gather_device_results([f._device for f in todo])
    except Exception:  # noqa: BLE001 — grouped path failed; force singly
        for f in todo:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — error lands on THAT future
                pass
        return
    for f, host in zip(todo, host_groups):
        f._deliver(host)


# -- double-buffered host staging ----------------------------------------------


class _StageSlot:
    __slots__ = ("buf", "staged", "busy")

    def __init__(self, buf: np.ndarray):
        self.buf = buf
        self.staged = None  # device handle last uploaded from this buffer
        self.busy = False


class StagingPool:
    """Double-buffered host staging buffers for flush packing.

    ``acquire(shape, dtype)`` hands out a zeroed host view backed by one of
    ``depth`` reusable slots; ``commit(slot, staged)`` pairs the slot with
    the device copy made from it and frees it.  A slot is re-issued only
    once its previous upload has materialized (a real wait is counted as a
    blocking sync) — refilling buffer A therefore overlaps buffer B's
    in-flight upload, and reuse can never scribble over bytes the DMA is
    still reading.  When every slot is checked out (deep concurrent
    fan-out) acquire degrades to a fresh one-off allocation (slot=None):
    correctness never depends on pool depth."""

    def __init__(self, depth: int = 2):
        self._lock = threading.Lock()
        self._slots: List[_StageSlot] = []
        self._depth = max(1, depth)
        self.reuses = 0  # observability (ResourceCensus-friendly gauges)
        self.oneoffs = 0

    def acquire(self, shape, dtype=np.uint32) -> Tuple[np.ndarray, Optional[_StageSlot]]:
        want = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        slot = None
        with self._lock:
            for s in self._slots:
                if not s.busy:
                    s.busy = True
                    slot = s
                    break
            if slot is None and len(self._slots) < self._depth:
                slot = _StageSlot(np.empty(max(want, 1), np.uint8))
                slot.busy = True
                self._slots.append(slot)
        if slot is None:
            self.oneoffs += 1
            return np.zeros(shape, dtype), None
        staged, slot.staged = slot.staged, None
        if staged is not None and not _is_ready(staged):
            # the double-buffer boundary: the slot's previous upload is
            # still in flight — wait (counted) before touching its bytes
            import jax

            STATS.count_staging_wait()
            jax.block_until_ready(staged)
        if slot.buf.nbytes < want:
            slot.buf = np.empty(want, np.uint8)
        self.reuses += 1
        view = slot.buf[:want].view(dtype).reshape(shape)
        view[...] = 0
        return view, slot

    def commit(self, slot: Optional[_StageSlot], staged):
        """Record the device handle uploaded from `slot` and free the slot.
        Returns `staged` for call-site chaining; slot=None (one-off buffer)
        is a no-op."""
        if slot is not None:
            with self._lock:
                slot.staged = staged
                slot.busy = False
        return staged

    def release(self, slot: Optional[_StageSlot]) -> None:
        """Abandon a slot without an upload (error paths)."""
        if slot is not None:
            with self._lock:
                slot.busy = False

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()

    def slot_count(self) -> int:
        with self._lock:
            return len(self._slots)


# -- dispatch-ahead flush driver -----------------------------------------------


class FlushPipeline:
    """stage -> dispatch -> fetch driver for a stream of flush windows — the
    plane's A/B harness (bench.py's overlap sub-measurement and the CPU
    structural smoke both drive it).

    ``submit(fn)``: ``fn()`` stages + dispatches ONE window and returns
    ``(device_values, finish)`` with ``finish(host_tuple) -> result``.

      * overlap on: returns a ReadbackFuture immediately; at most ``depth``
        windows stay un-forced (the dispatch-ahead bound) — submitting
        window depth+1 forces the oldest, whose readback by then overlapped
        the younger windows' staging and dispatch.  N windows cost N counted
        readback syncs (+ at most one staging wait): the <= N+1 contract.
      * overlap off: the strict serial reference — a counted barrier on the
        window's device values (the stage/dispatch drain) then an immediate
        forced fetch: exactly 2 blocking syncs per window, the 2N shape.

    Deadline-aware window close (ISSUE 10, the QoS plane): size/arrival are
    no longer the ONLY flush triggers —

      * ``submit(fn, interactive=True)`` closes the window at the deadline
        class boundary: an interactive window's readback is forced as soon
        as its dispatch lands instead of parking un-forced behind up to
        ``depth`` bulk windows (laziness trades the bulk stream's
        throughput for the interactive result's latency, exactly the wrong
        trade for that class);
      * with ``deadline_s`` set, any window older than the deadline is
        forced by the next submit, bounding how long a result can sit
        dispatched-but-undelivered when traffic goes quiet.

    Neither trigger reorders device work — only WAITS move, so results stay
    bit-identical (the same contract as the overlap switch itself).
    """

    def __init__(self, *, overlap: Optional[bool] = None, depth: int = 2,
                 deadline_s: Optional[float] = None):
        self.overlap = overlap_enabled() if overlap is None else bool(overlap)
        self.depth = max(1, depth)
        # None = follow the process-global default (set_window_deadline,
        # armed by CONFIG SET qos-interactive-deadline-ms)
        self.deadline_s = (
            _window_deadline_s if deadline_s is None else deadline_s
        )
        self._ring: List[Tuple[ReadbackFuture, float]] = []

    @staticmethod
    def _force(fut: ReadbackFuture) -> None:
        try:
            fut.result()
        except Exception:  # noqa: BLE001 — error stays on the future
            pass

    def submit(self, fn: Callable[[], Tuple[Sequence[Any], Optional[Callable]]],
               interactive: bool = False) -> ReadbackFuture:
        device, finish = fn()
        fut = ReadbackFuture(device, finish)
        if not self.overlap:
            barrier(tuple(device))
            self._force(fut)
            return fut
        now = time.monotonic()
        # deadline-triggered close: windows older than deadline_s deliver
        # NOW — a quiet lane must not hold results hostage to the next
        # arrival or the depth overflow
        if self.deadline_s is not None:
            while self._ring and now - self._ring[0][1] > self.deadline_s:
                self._force(self._ring.pop(0)[0])
        if interactive:
            # deadline-class close: the interactive window never parks in
            # the dispatch-ahead ring — one readback sync, right here, at
            # the earliest point the device can deliver it
            self._force(fut)
            return fut
        self._ring.append((fut, now))
        if len(self._ring) > self.depth:
            self._force(self._ring.pop(0)[0])
        return fut

    def pending(self) -> int:
        return len(self._ring)

    def drain(self) -> None:
        """Force every still-pending window (end of the stream)."""
        ring, self._ring = self._ring, []
        for fut, _t in ring:
            self._force(fut)


# -- per-class QoS in-flight ledger (ISSUE 10) ---------------------------------


class QosLedger:
    """Per-deadline-class in-flight accounting: one global ledger on the
    server's WindowScheduler, one per DeviceLane.  Every ``enter`` must be
    paired with an ``exit`` — the in-flight rows are census gauges (the
    soak's flat-census assertion guards them), the cumulative rows feed the
    CLUSTER QOS / CLUSTER DEVICES wire views."""

    __slots__ = ("_lock", "frames", "ops", "nbytes", "waiting",
                 "dispatched_ops", "dispatched_frames",
                 "stream_inflight", "stream_dispatched")

    _CLASSES = ("interactive", "bulk")
    # device streams (ISSUE 18): which lane stream served a dispatch —
    # "interactive" only when the per-class fast path actually took it
    # (preemption armed AND the frame was interactive-class), "bulk"
    # otherwise, so disarmed runs book every dispatch on the bulk stream
    # exactly as the pre-stream ledger did
    _STREAMS = ("interactive", "bulk")

    def __init__(self):
        self._lock = threading.Lock()
        self.frames = {c: 0 for c in self._CLASSES}
        self.ops = {c: 0 for c in self._CLASSES}
        self.nbytes = {c: 0 for c in self._CLASSES}
        self.waiting = 0  # bulk frames parked at the admission gate
        self.dispatched_ops = {c: 0 for c in self._CLASSES}
        self.dispatched_frames = {c: 0 for c in self._CLASSES}
        self.stream_inflight = {s: 0 for s in self._STREAMS}
        self.stream_dispatched = {s: 0 for s in self._STREAMS}

    @classmethod
    def _cls(cls, qos_class: str) -> str:
        return qos_class if qos_class in cls._CLASSES else "bulk"

    def enter(self, qos_class: str, ops: int, nbytes: int = 0) -> None:
        c = self._cls(qos_class)
        with self._lock:
            self.frames[c] += 1
            self.ops[c] += ops
            self.nbytes[c] += nbytes
            self.dispatched_ops[c] += ops
            self.dispatched_frames[c] += 1

    def exit(self, qos_class: str, ops: int, nbytes: int = 0) -> None:
        c = self._cls(qos_class)
        with self._lock:
            self.frames[c] -= 1
            self.ops[c] -= ops
            self.nbytes[c] -= nbytes

    def wait_enter(self) -> None:
        with self._lock:
            self.waiting += 1

    def wait_exit(self) -> None:
        with self._lock:
            self.waiting -= 1

    def stream_enter(self, stream: str, ops: int) -> None:
        s = stream if stream in self._STREAMS else "bulk"
        with self._lock:
            self.stream_inflight[s] += ops
            self.stream_dispatched[s] += ops

    def stream_exit(self, stream: str, ops: int) -> None:
        s = stream if stream in self._STREAMS else "bulk"
        with self._lock:
            self.stream_inflight[s] -= ops

    def stream_rows(self) -> list:
        """``[b"STREAM", name, in-flight ops, dispatched ops]`` per device
        stream — appended to the CLUSTER QOS reply.  The leading b"STREAM"
        tag keeps the rows distinct from the per-class rows (whose row[0]
        is the class name) so pre-stream consumers' parsers — notably
        OccupancyLoadBalancer._qos_infl_ops — skip them unchanged."""
        with self._lock:
            return [
                [b"STREAM", s.encode(), self.stream_inflight[s],
                 self.stream_dispatched[s]]
                for s in self._STREAMS
            ]

    def census(self, prefix: str = "qos") -> dict:
        """Drain-to-zero gauges only (cumulative counters are exposed on the
        wire views instead, so flat-census assertions stay meaningful)."""
        with self._lock:
            out = {f"{prefix}_bulk_waiting": float(self.waiting)}
            for c in self._CLASSES:
                out[f"{prefix}_{c}_inflight_frames"] = float(self.frames[c])
                out[f"{prefix}_{c}_inflight_ops"] = float(self.ops[c])
                out[f"{prefix}_{c}_inflight_bytes"] = float(self.nbytes[c])
            for s in self._STREAMS:
                out[f"{prefix}_stream_{s}_inflight"] = float(
                    self.stream_inflight[s])
            return out

    def wire_row(self) -> list:
        """[in-flight ops i/b, in-flight bytes i/b, dispatched ops i/b] —
        the compact CLUSTER DEVICES per-lane projection."""
        with self._lock:
            return [
                self.ops["interactive"], self.ops["bulk"],
                self.nbytes["interactive"], self.nbytes["bulk"],
                self.dispatched_ops["interactive"],
                self.dispatched_ops["bulk"],
            ]


# -- per-device serving lanes (ISSUE 8: device-sharded slot ownership) --------
#
# With the slot table mapped onto the local device mesh, ONE flush lane is a
# structural bottleneck: frames routed to different devices would still
# serialize through a single StagingPool/FlushPipeline and a single IOStats
# ledger.  A DeviceLane is the per-chip lane — its own double-buffered
# staging pool, its own dispatch-ahead pipeline, its own stats — and LaneSet
# is the engine's registry of them, plus the cross-lane dispatch-concurrency
# accounting bench.py's config5d reports.

_replica_ns_per_item: Optional[float] = None


def set_replica_occupancy(ns_per_item: Optional[float]) -> Optional[float]:
    """Arm/disarm the CPU-replica device-occupancy model: with a value set,
    every ``DeviceLane.occupy(n_items)`` holds its lane for n_items *
    ns_per_item nanoseconds — modeling the per-chip compute time a real
    accelerator would serialize on its stream.  This exists ONLY for A/B
    measurement on chip-less containers (bench config5d; the same
    scaled-down-replica discipline as the PR 3 overlap-efficiency CPU
    number): the 1-device leg serializes the modeled occupancy through one
    lane, the N-device leg overlaps it across lanes, exactly as N chips
    would.  Disarmed (None, the default) a lane's occupy() costs one
    uncontended lock acquisition.  Returns the previous value."""
    global _replica_ns_per_item
    prev = _replica_ns_per_item
    _replica_ns_per_item = ns_per_item
    return prev


def replica_occupancy() -> Optional[float]:
    return _replica_ns_per_item


# every live LaneSet, weakly held: device-layer faults observed where no
# lane reference exists (ReadbackFuture) are attributed through here
_LANE_SETS: "weakref.WeakSet" = weakref.WeakSet()


def note_device_fault(dev_id: int, kind: str) -> bool:
    """Attribute one device fault to every registered lane for `dev_id`;
    returns True when any lane newly flipped to QUARANTINED."""
    tripped = False
    for ls in list(_LANE_SETS):
        lane = ls._lanes.get(dev_id)
        if lane is not None and lane.note_fault(kind):
            tripped = True
    return tripped


def note_device_ok(dev_id: int) -> None:
    """A readback on `dev_id` completed cleanly: reset its lanes'
    consecutive-fault streaks (quarantine itself clears only via probe)."""
    for ls in list(_LANE_SETS):
        lane = ls._lanes.get(dev_id)
        if lane is not None:
            lane.note_ok()


def quarantined_device_ids() -> set:
    """Device ids currently quarantined on ANY registered lane set."""
    out = set()
    for ls in list(_LANE_SETS):
        for dev_id, lane in ls._lanes.items():
            if lane.quarantined:
                out.add(dev_id)
    return out


class DeviceLane:
    """One device's serving lane: staging pool + flush pipeline + stats +
    the dispatch-occupancy gate (a mutex standing in for the device stream:
    dispatches bound for one device serialize, dispatches bound for
    different devices overlap)."""

    def __init__(self, device, laneset: "LaneSet", depth: int = 2):
        self.device = device
        self.dev_id = getattr(device, "id", 0)
        self.pool = StagingPool(depth=depth)
        self.pipeline = FlushPipeline(depth=depth)
        self.stats = device_stats(self.dev_id)
        # per-lane QoS ledger (ISSUE 10): queue depth / in-flight ops+bytes
        # per deadline class, read by CLUSTER DEVICES and the lane census
        self.qos = QosLedger()
        self._laneset = laneset
        self._gate = threading.Lock()
        # interactive device stream (ISSUE 18): its own gate + staging slot
        # + dispatch queue, so an armed interactive dispatch launches
        # without queuing behind the bulk stream's occupancy gate.  depth=1
        # on both — interactive windows never park (FlushPipeline forces
        # them at submit) and one staging slot matches one-at-a-time
        # latency-bound traffic.
        self._igate = threading.Lock()
        self.ipool = StagingPool(depth=1)
        self.ipipeline = FlushPipeline(depth=1)
        self._icond = threading.Condition(threading.Lock())
        self._iwaiting = 0  # interactive dispatches queued or in flight
        self.dispatches = 0
        self.preemptions = 0  # preempt points that actually yielded
        # device fault ledger (ISSUE 19): consecutive faults/timeouts trip
        # quarantine; a successful readback resets the streak, a probe
        # dispatch (server CLUSTER DEVPROBE) un-quarantines
        self.consec_faults = 0
        self.total_faults = 0
        self.quarantined = False
        self.quarantined_at = 0.0
        self.last_fault_kind = ""

    def note_fault(self, kind: str) -> bool:
        """Record one device-layer fault (kernel launch failure, readback
        timeout/error).  Trips QUARANTINED at the consecutive threshold;
        returns True when THIS call flipped the lane."""
        self.total_faults += 1
        self.consec_faults += 1
        self.last_fault_kind = kind
        if not self.quarantined and self.consec_faults >= _quarantine_after:
            self.quarantined = True
            self.quarantined_at = time.monotonic()
            if _obs._tracer is not None:
                cur = _obs.current_trace()
                if cur is not None:
                    now = time.monotonic()
                    cur.add_span("quarantine", now, now, device=self.dev_id)
            return True
        return False

    def note_ok(self) -> None:
        """A device operation completed cleanly: the consecutive-fault
        streak (NOT the quarantine flag — only a probe clears that) resets."""
        if self.consec_faults:
            self.consec_faults = 0

    def unquarantine(self) -> None:
        """Clear quarantine (the probe-passed path)."""
        self.quarantined = False
        self.consec_faults = 0

    def occupy(self, n_items: int = 0, qos_class: Optional[str] = None,
               nbytes: int = 0):
        """Context manager bounding one dispatch's device occupancy: holds
        the lane gate (per-device serialization) and, under the CPU-replica
        knob, the modeled per-chip compute time for `n_items` ops.  With
        `qos_class` given (the scheduler armed), the dispatch is accounted
        on the lane's per-class QoS ledger for its whole residency.  With
        preemption armed an interactive-class dispatch occupies the lane's
        INTERACTIVE stream (_igate) instead of the bulk gate."""
        return _LaneOccupancy(self, n_items, qos_class, nbytes)

    def submit(self, fn, interactive: bool = False):
        """Route one flush window to the serving stream's pipeline: armed
        interactive windows go through the interactive dispatch queue (so a
        parked bulk ring never delays forcing them), everything else —
        and everything when disarmed — through the bulk pipeline."""
        if interactive and _preempt:
            return self.ipipeline.submit(fn, interactive=True)
        return self.pipeline.submit(fn, interactive=interactive)

    def interactive_waiting(self) -> int:
        with self._icond:
            return self._iwaiting

    def _ienter(self) -> None:
        with self._icond:
            self._iwaiting += 1

    def _iexit(self) -> None:
        with self._icond:
            self._iwaiting -= 1
            if self._iwaiting <= 0:
                self._icond.notify_all()

    def preempt_point(self, timeout: float = 0.05) -> bool:
        """The inter-sub-window preemption point: with preemption armed and
        interactive dispatches queued or in flight on this lane, yield the
        (released) device for up to `timeout` seconds so their kernels
        launch before the next bulk sub-window re-occupies the stream.
        Called BETWEEN chunk dispatches — the caller holds no lane gate and
        no record locks here, and the wait is bounded, so the point can
        never deadlock the bulk stream against a stuck client.  Returns
        True when it actually yielded."""
        if not _preempt:
            return False
        yielded = False
        with self._icond:
            if self._iwaiting > 0:
                deadline = time.monotonic() + timeout
                while self._iwaiting > 0:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._icond.wait(left)
                yielded = True
        if yielded:
            self.preemptions += 1
        return yielded


class _LaneOccupancy:
    __slots__ = ("_lane", "_n", "_cls", "_nbytes", "_tcur", "_tmark",
                 "_stream", "_gate", "_prev_stream")

    def __init__(self, lane: DeviceLane, n_items: int,
                 qos_class: Optional[str] = None, nbytes: int = 0):
        self._lane = lane
        self._n = n_items
        self._cls = qos_class
        self._nbytes = nbytes
        self._tcur = None  # active FrameTrace (tracing armed only)
        self._tmark = 0.0
        # stream selection (ISSUE 18): interactive dispatches take the
        # lane's interactive stream only with preemption armed — disarmed,
        # everything serializes through the one bulk gate, the exact
        # pre-stream behavior
        if qos_class == "interactive" and _preempt:
            self._stream = "interactive"
            self._gate = lane._igate
        else:
            self._stream = "bulk"
            self._gate = lane._gate
        self._prev_stream = None

    def __enter__(self):
        # device dispatch chokepoint (ISSUE 19): consulted BEFORE any
        # ledger entry so an injected kernel-launch failure unwinds with
        # nothing to undo — __exit__ never runs when __enter__ raises
        plane = _net._fault_plane
        if plane is not None:
            try:
                plane.on_device_dispatch(self._lane.dev_id)
            except BaseException:
                self._lane.note_fault("kernel_launch")
                raise
        if self._cls is not None:
            self._lane.qos.enter(self._cls, self._n, self._nbytes)
        self._lane.qos.stream_enter(self._stream, self._n)
        if self._stream == "interactive":
            # visible to preempt_point from the moment the dispatch queues
            # on the interactive gate, not just once it holds it
            self._lane._ienter()
        if _obs._tracer is not None:
            self._tcur = _obs.current_trace()
        if self._tcur is not None:
            # `stage` = time queued behind the lane gate (ahead of the
            # chip); the occupancy hold itself becomes the `dispatch` span
            t0 = time.monotonic()
            self._gate.acquire()
            self._tmark = time.monotonic()
            self._tcur.add_span(
                "stage", t0, self._tmark,
                device=self._lane.dev_id, items=self._n,
                nbytes=self._nbytes, stream=self._stream,
            )
        else:
            self._gate.acquire()
        self._prev_stream = getattr(_stream_tls, "stream", None)
        _stream_tls.stream = self._stream
        self._lane._laneset._enter()
        self._lane.dispatches += 1
        return self._lane

    def __exit__(self, *exc):
        try:
            ns = _replica_ns_per_item
            if ns is not None and self._n > 0:
                time.sleep(self._n * ns * 1e-9)
        finally:
            if self._tcur is not None:
                self._tcur.add_span(
                    "dispatch", self._tmark, time.monotonic(),
                    device=self._lane.dev_id, items=self._n,
                    nbytes=self._nbytes, stream=self._stream,
                )
            self._lane._laneset._exit()
            _stream_tls.stream = self._prev_stream
            self._gate.release()
            if self._stream == "interactive":
                self._lane._iexit()
            self._lane.qos.stream_exit(self._stream, self._n)
            if self._cls is not None:
                self._lane.qos.exit(self._cls, self._n, self._nbytes)
        return False


class LaneSet:
    """The engine's per-device lane registry + cross-lane concurrency
    accounting (``peak_concurrent`` is bench config5d's dispatch-concurrency
    sub-metric: >1 proves frames routed to different devices actually
    dispatched in parallel)."""

    def __init__(self, devices: Sequence[Any], depth: int = 2):
        self._lanes = {
            getattr(d, "id", i): DeviceLane(d, self, depth=depth)
            for i, d in enumerate(devices)
        }
        self._lock = threading.Lock()
        self._active = 0
        self.peak_concurrent = 0
        # fault attribution registry (ISSUE 19): ReadbackFuture holds no
        # lane reference, so watchdog trips reach lanes through here
        _LANE_SETS.add(self)

    def lane(self, device) -> DeviceLane:
        dev_id = device if isinstance(device, int) else getattr(device, "id", 0)
        lane = self._lanes.get(dev_id)
        if lane is None:  # unknown device (placement grew): one-off lane
            with self._lock:
                lane = self._lanes.get(dev_id)
                if lane is None:
                    lane = self._lanes[dev_id] = DeviceLane(device, self)
        return lane

    def lanes(self) -> List[DeviceLane]:
        return list(self._lanes.values())

    def _enter(self) -> None:
        with self._lock:
            self._active += 1
            if self._active > self.peak_concurrent:
                self.peak_concurrent = self._active

    def _exit(self) -> None:
        with self._lock:
            self._active -= 1

    def active(self) -> int:
        with self._lock:
            return self._active

    def reset_concurrency(self) -> int:
        with self._lock:
            prev, self.peak_concurrent = self.peak_concurrent, 0
            return prev

    def census(self) -> dict:
        """Flat gauges for ResourceCensus: staging slots and in-flight
        dispatch count must return to baseline after a storm."""
        out = {"lanes": len(self._lanes), "active_dispatches": self.active()}
        for dev_id, lane in sorted(self._lanes.items()):
            out[f"lane{dev_id}_staging_slots"] = lane.pool.slot_count()
            out[f"lane{dev_id}_istaging_slots"] = lane.ipool.slot_count()
            out[f"lane{dev_id}_iwaiting"] = lane.interactive_waiting()
            # quarantine state (ISSUE 19): both must return to 0 after a
            # fault storm recovers (probe passed / evacuation complete)
            out[f"lane{dev_id}_quarantined"] = int(lane.quarantined)
            out[f"lane{dev_id}_consec_faults"] = lane.consec_faults
            # per-lane QoS in-flight (ISSUE 10): must drain to 0 at quiesce
            for k, v in lane.qos.census(prefix=f"lane{dev_id}_qos").items():
                out[k] = v
        return out

    def clear(self) -> None:
        for lane in self._lanes.values():
            lane.pool.clear()
            lane.ipool.clear()
            lane.pipeline.drain()
            lane.ipipeline.drain()
