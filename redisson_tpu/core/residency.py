"""Tiered HBM residency: HOT on device, WARM in host RAM, COLD on disk
(ISSUE 20 tentpole).

The HBM capacity plane stopped at enforcement — ``ftvec-device-budget``
REFUSES over-budget growth (PR 15) and the per-device byte ledgers MEASURE
residency (PR 19) — but nothing managed it: an over-budget tenant got a
``VectorBudgetError``, not service.  This module treats HBM as a **cache**
over host RAM and checkpoint-backed storage, the working-set-tiering shape
every serving stack leans on (KV-cache offload, parameter paging):

  * **HOT**  — device arrays live in HBM (today's only state);
  * **WARM** — the record's device arrays are RELEASED; a host-RAM numpy
    mirror (``rec.stash``) holds the exact bytes.  Promotion is ONE packed
    H2D through the owner lane's staging path (``scatter_host_arrays``) —
    same geometry, same device, so the warm kernel pool re-hits with ZERO
    rebuilds;
  * **COLD** — the host mirror is spilled to a checkpoint-container file
    (MAGIC + CRC trailer, ``checkpoint.read_verified`` reads it back) and
    dropped; promotion adds exactly one verified generation read.

Fault-in on first touch: the DeviceStore getters fire
``plane.on_record_access`` AFTER releasing the store lock; a WARM/COLD
record promotes synchronously before the caller sees it, so handlers never
observe a tier.  Demotion is safe by construction — only clean state
demotes (dirty probes pin HOT; vector banks with pending rows register
one), fenced/migrating slots never demote (``fence_check``), records
touched within ``min_idle_s`` never demote (the touch clock closes the
get-then-read race), and sharded / host-only records are simply ineligible.

Arming follows the chaos-hook discipline (net/client.py ``_fault_plane``,
observe/trace.py ``_tracer``): ``_tier_plane`` is the ONE module global
every store-getter site loads — ``None`` (the default) costs one load plus
an ``is None`` branch and replies stay bit-identical; armed, the plane
routes to the store's own :class:`ResidencyManager`.  The plane arms only
when a manager is actually installed (``enable_residency`` /
``set_tier(True)``) — armed-with-no-manager would charge every getter a
method call plus two getattrs for nothing, a measured ~70% p99 hit on the
interactive QoS leg.  ``RTPU_NO_TIER=1`` is the hard kill-switch:
``set_tier(True)`` becomes a no-op, so even ``CONFIG SET
residency-enabled yes`` cannot arm the guard.

Lock discipline (the dispatch path's order is lane -> record):

  * promotion runs WITHOUT the store lock (getters fire the hook after
    release), takes the record lock first, then the per-record transition
    lock, then TRIES the owner lane's gate with a short timeout — a
    dispatch holding the gate while waiting on this record's lock would
    otherwise ABBA; on timeout the upload proceeds gateless (contention,
    not correctness: ``device_put`` needs no gate);
  * demotion try-acquires the record lock (never blocks a serving path)
    and snapshots + swaps arrays entirely under it, so a concurrent
    wholesale plane replacement can never be clobbered.
"""
from __future__ import annotations

import collections
import itertools
import os
import struct
import threading
import time
import zlib
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

# interned tier constants: guard sites compare with ``is``
HOT = "hot"
WARM = "warm"
COLD = "cold"

_SPILL_FMT = 1

# -- per-device byte budget (generalizes ftvec-device-budget) ------------------

DEVICE_BUDGET_BYTES = int(os.environ.get("RTPU_DEVICE_BUDGET_BYTES", "0"))
# per-DEVICE budget over ALL record kinds' device bytes (0 = unlimited) —
# the ledger PR 19 measures is what this bounds; the sweeper demotes the
# least-recently-touched clean records until each device fits.


def set_device_budget_bytes(value: int) -> int:
    """Set the per-device byte budget (0 = unlimited); returns previous."""
    global DEVICE_BUDGET_BYTES
    prev, DEVICE_BUDGET_BYTES = DEVICE_BUDGET_BYTES, max(0, int(value))
    return prev


# -- the disarm switch (RTPU_NO_TIER) ------------------------------------------


class _TierPlane:
    """Router the armed store-getter sites call: resolves the touched
    store's OWN manager (multiple engines in one test process must never
    cross-wire), so the module global stays a single is-None guard."""

    def on_record_access(self, store, name: str, rec) -> None:
        if getattr(_tls, "bypass", False):
            return  # census / serializer scan: observe, never promote
        mgr = getattr(store, "residency", None)
        if mgr is not None:
            mgr.on_access(name, rec)


_PLANE = _TierPlane()

# THE guard every getter site loads: None = disarmed (zero-cost).  Same
# shape as observe/trace.py `_tracer` / net/client.py `_fault_plane`.
# Starts disarmed — enable_residency()/set_tier(True) arms it when a
# manager exists to route to; RTPU_NO_TIER=1 pins it disarmed for good.
_NO_TIER = os.environ.get("RTPU_NO_TIER", "") in ("1", "true", "yes")
_tier_plane: Optional[_TierPlane] = None

_tls = threading.local()


def tier_enabled() -> bool:
    return _tier_plane is not None


def set_tier(on: bool) -> bool:
    """Arm/disarm the residency plane; returns the previous armed state
    (callers restore it — the A/B discipline of RTPU_NO_QOS).  Under
    RTPU_NO_TIER=1 arming is refused: the env var is the operator's
    bit-identity guarantee and must beat any in-process caller."""
    global _tier_plane
    prev = _tier_plane is not None
    _tier_plane = _PLANE if (on and not _NO_TIER) else None
    return prev


class no_promote:
    """Context: observe records without faulting them in — the census /
    serializer discipline (a metrics scrape or checkpoint cut walking every
    record must never drag the whole WARM set back into HBM)."""

    def __enter__(self):
        self._prev = getattr(_tls, "bypass", False)
        _tls.bypass = True
        return self

    def __exit__(self, *exc):
        _tls.bypass = self._prev
        return False


# -- residency-aware host views (work disarmed too) ----------------------------


def record_host_arrays(rec) -> Dict[str, Any]:
    """Host-side numpy view of a record's named arrays REGARDLESS of tier —
    the one seam checkpoint/replication/migration serializers read through,
    so a WARM/COLD record checkpoints and ships without promotion."""
    stash = getattr(rec, "stash", None)
    if stash is not None:
        return dict(stash)
    path = getattr(rec, "cold_path", None)
    if path is not None:
        return load_spill(path)
    import numpy as np

    return {k: np.asarray(v) for k, v in rec.arrays.items()}


def record_device_bytes(rec) -> int:
    """HBM bytes this record holds RIGHT NOW (0 for WARM/COLD)."""
    total = 0
    for a in rec.arrays.values():
        n = getattr(a, "nbytes", None)
        if n is not None:
            total += int(n)
    return total


def _host_bytes(arrays: Dict[str, Any]) -> int:
    return sum(int(getattr(a, "nbytes", 0)) for a in arrays.values())


# -- COLD spill container (checkpoint format: MAGIC + pickle + CRC) ------------


def write_spill(path: str, arrays: Dict[str, Any]) -> int:
    """One record's host arrays as a verified container file — the same
    MAGIC/CRC-trailer shape as checkpoints, read back by ``load_spill``
    through ``checkpoint.read_verified`` (COLD promotion = exactly one
    checkpoint-generation read).  Returns the payload byte count."""
    import pickle

    import numpy as np

    from redisson_tpu.core import checkpoint as ckpt

    payload = {
        "format": _SPILL_FMT,
        "arrays": {k: np.asarray(v) for k, v in arrays.items()},
    }
    body = ckpt.MAGIC + pickle.dumps(payload, protocol=4)
    data = body + ckpt.TRAILER_MAGIC + struct.pack(
        ">I", zlib.crc32(body) & 0xFFFFFFFF
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(data)


def load_spill(path: str) -> Dict[str, Any]:
    """Read + CRC-verify one spill file back to host arrays (raises
    ``CheckpointCorruptError`` on a torn/forged file)."""
    from redisson_tpu.core import checkpoint as ckpt

    payload = ckpt.read_verified(path)
    if not isinstance(payload, dict) or payload.get("format") != _SPILL_FMT:
        raise ckpt.CheckpointCorruptError(f"not a residency spill: {path!r}")
    return dict(payload["arrays"])


# -- the manager ---------------------------------------------------------------


class ResidencyManager:
    """Per-engine tier manager: touch clock, fault-in, clock/LRU demotion
    against the per-device byte budget, COLD spill, and the census rows
    the ``CLUSTER RESIDENCY`` verb / METRICS multi-gauge render."""

    def __init__(self, engine, spill_dir: Optional[str] = None,
                 min_idle_s: float = 0.25, cold_after_s: float = 0.0,
                 sweep_interval: float = 0.0, gate_timeout_s: float = 0.25):
        self.engine = engine
        self._spill_dir = spill_dir
        self._owns_spill_dir = False
        self.min_idle_s = float(min_idle_s)
        # WARM records idle longer than this spill COLD (0 = never auto-COLD)
        self.cold_after_s = float(cold_after_s)
        self.gate_timeout_s = float(gate_timeout_s)
        # touch clock: name -> (monotonic seq, wall-ish monotonic seconds);
        # plain dict writes are GIL-atomic — the hot getter path takes no lock
        self._clock = itertools.count(1)
        self._touch: Dict[str, Tuple[int, float]] = {}
        # per-record transition locks (promote/demote mutual exclusion)
        self._tlocks: Dict[str, threading.Lock] = {}
        self._tguard = threading.Lock()
        # demotion pins: probes that flag a record DIRTY (pending vector
        # rows, mid-2PC state, ...) — dirty records pin HOT.  The vector
        # plane's pending-rows probe is always on: a bank mid-accumulation
        # must not demote between set_row and flush.
        self.pin_probes: List[Callable[[str, Any], bool]] = [
            self._vector_pending_probe,
        ]
        # slot-fence probe (server wires migrating/importing/recovering):
        # fenced slots never demote — their records are mid-handoff
        self.fence_check: Callable[[str], bool] = lambda name: False
        # counters (census + METRICS rows)
        self.promotions = 0
        self.demotions_warm = 0
        self.demotions_cold = 0
        self.cold_loads = 0
        self.fault_in_ms_total = 0.0
        self.fault_in_ms_max = 0.0
        # bounded per-promotion duration ring — percentile source for the
        # bench gate (config8_fault_in_p99_ms); a deque so an overcommitted
        # long run can't grow it unbounded
        self.fault_in_samples: Deque[float] = collections.deque(maxlen=4096)
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if sweep_interval > 0:
            self.start_sweeper(sweep_interval)

    # -- plumbing -------------------------------------------------------------

    def _tlock(self, name: str) -> threading.Lock:
        with self._tguard:
            lk = self._tlocks.get(name)
            if lk is None:
                lk = self._tlocks[name] = threading.Lock()
            return lk

    def spill_dir(self) -> str:
        if self._spill_dir is None:
            import tempfile

            self._spill_dir = tempfile.mkdtemp(prefix="rtpu-residency-")
            self._owns_spill_dir = True
        else:
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    def _spill_path(self, name: str) -> str:
        import hashlib

        h = hashlib.sha256(name.encode()).hexdigest()[:32]
        return os.path.join(self.spill_dir(), f"{h}.spill")

    def _vector_pending_probe(self, name: str, rec) -> bool:
        if rec.kind not in ("vector_bank",):
            return False
        from redisson_tpu.services.vector import bank_has_pending

        return bank_has_pending(self.engine.store, name)

    def touch_age(self, name: str) -> float:
        t = self._touch.get(name)
        return float("inf") if t is None else time.monotonic() - t[1]

    # -- the getter hook (armed path) -----------------------------------------

    def on_access(self, name: str, rec) -> None:
        self._touch[name] = (next(self._clock), time.monotonic())
        if rec.tier is not HOT and rec.tier != HOT:
            self.fault_in(name, rec)

    # -- fault-in (promotion) -------------------------------------------------

    def fault_in(self, name: str, rec) -> None:
        """Promote a WARM/COLD record back to HOT: one packed H2D through
        the owner lane's staging path (COLD first pays one verified spill
        read).  Synchronous — the touching command proceeds only once the
        arrays are device-resident, so its QoS admission window charges the
        fault-in by construction."""
        eng = self.engine
        t0 = time.monotonic()
        from_tier = rec.tier
        with eng.locked(name):
            with self._tlock(name):
                if rec.tier == HOT:
                    return  # raced with another promoter
                stash = rec.stash
                if stash is None:
                    path = rec.cold_path
                    if path is None:
                        # nothing to restore (empty record demoted): just flip
                        rec.tier = HOT
                        return
                    stash = load_spill(path)
                    self.cold_loads += 1
                nbytes = _host_bytes(stash)
                device = eng.device_for_name(name)
                self._upload(name, rec, stash, device)
                rec.stash = None
                if rec.cold_path is not None:
                    try:
                        os.unlink(rec.cold_path)
                    except OSError:
                        pass
                    rec.cold_path = None
                rec.tier = HOT
                self.promotions += 1
        dt_ms = (time.monotonic() - t0) * 1e3
        self.fault_in_ms_total += dt_ms
        if dt_ms > self.fault_in_ms_max:
            self.fault_in_ms_max = dt_ms
        self.fault_in_samples.append(dt_ms)
        from redisson_tpu.observe import trace as _obs

        if _obs._tracer is not None:
            tr = _obs.current_trace()
            if tr is not None:
                from redisson_tpu.core.ioplane import current_stream

                tr.add_span(
                    "promote", t0, time.monotonic(), record=name,
                    tier=from_tier, bytes=nbytes,
                    stream=current_stream() or "bulk",
                )

    def _upload(self, name: str, rec, stash: Dict[str, Any], device) -> None:
        """ONE packed H2D of the stash onto `device` — the owner lane's
        gate is TRIED (not taken) so a dispatch already holding it while
        waiting on this record's lock can never ABBA; a promote fired from
        INSIDE a lane occupancy (current_stream set) already owns the gate
        and skips it."""
        from redisson_tpu.core import ioplane

        lane = None
        if device is not None and self.engine.lanes is not None:
            try:
                lane = self.engine.lanes.lane(device)
            except Exception:  # noqa: BLE001 — unknown device: gateless
                lane = None
        gate = None
        if lane is not None and ioplane.current_stream() is None:
            if lane._gate.acquire(timeout=self.gate_timeout_s):
                gate = lane._gate
        try:
            pool = self.engine.staging_pool(device)
            try:
                arrays = ioplane.scatter_host_arrays(stash, device, pool=pool)
            except Exception:  # noqa: BLE001 — packed path refused (exotic
                import jax      # dtype): per-array upload, same bytes

                arrays = {
                    k: (jax.device_put(v, device) if device is not None
                        else jax.device_put(v))
                    for k, v in stash.items()
                }
            rec.arrays.update(arrays)
        finally:
            if gate is not None:
                gate.release()

    # -- demotion -------------------------------------------------------------

    def _demotable(self, name: str, rec) -> bool:
        """Clean, single-device, unfenced, idle: the safe-by-construction
        predicate.  Anything ambiguous pins HOT."""
        if rec.tier != HOT or not rec.arrays or rec.expired():
            return False
        if self.touch_age(name) < self.min_idle_s:
            return False  # touched too recently: closes the get-read race
        if self.fence_check(name):
            return False  # migrating/importing/recovering slot
        for probe in self.pin_probes:
            try:
                if probe(name, rec):
                    return False  # dirty (e.g. pending vector rows)
            except Exception:  # noqa: BLE001 — a broken probe pins, never
                return False   # unpins: fail safe
        for a in rec.arrays.values():
            devs = getattr(a, "devices", None)
            if devs is None:
                return False  # host-side numpy plane: nothing to release
            try:
                ds = devs()
            except TypeError:  # pragma: no cover
                return False
            if len(ds) != 1:
                return False  # mesh-sharded plane: parallel/ owns layout
        return True

    def demote(self, name: str, cold: bool = False,
               force: bool = False) -> bool:
        """Release one record's device arrays to its host stash (WARM), or
        spill the stash to disk (COLD).  Never blocks a serving path: the
        record lock is TRY-acquired; a busy record just stays HOT.  Returns
        True iff the tier actually changed."""
        eng = self.engine
        ctx = eng.try_locked(name)
        if ctx is None:
            return False
        with ctx:
            with self._tlock(name):
                rec = eng.store.get_unguarded(name)
                if rec is None:
                    return False
                if rec.tier == HOT:
                    if not force and not self._demotable(name, rec):
                        return False
                    if force and (not rec.arrays or self.fence_check(name)):
                        return False
                    import numpy as np

                    stash = {
                        k: np.asarray(v) for k, v in rec.arrays.items()
                    }
                    dev = -1
                    for a in rec.arrays.values():
                        devs = getattr(a, "devices", None)
                        if devs is not None:
                            try:
                                ds = devs()
                                if len(ds) == 1:
                                    dev = next(iter(ds)).id
                                    break
                            except TypeError:  # pragma: no cover
                                pass
                    rec.arrays.clear()
                    rec.stash = stash
                    rec.stash_dev = dev
                    rec.tier = WARM
                    self.demotions_warm += 1
                    if not cold:
                        return True
                if cold and rec.tier == WARM and rec.stash is not None:
                    path = self._spill_path(name)
                    write_spill(path, rec.stash)
                    rec.cold_path = path
                    rec.cold_bytes = _host_bytes(rec.stash)
                    rec.stash = None
                    rec.tier = COLD
                    self.demotions_cold += 1
                    return True
        return False

    # -- pressure / budget ----------------------------------------------------

    def hot_bytes_by_device(self) -> Dict[int, int]:
        """HBM bytes by device id over every live record — the PR 19
        ledger scan, reused as the demotion pressure signal."""
        out: Dict[int, int] = {}
        with no_promote():
            for _kind, rec in self.engine.store.census_records():
                for a in rec.arrays.values():
                    devs = getattr(a, "devices", None)
                    if devs is None:
                        continue
                    try:
                        ds = devs()
                    except TypeError:  # pragma: no cover
                        continue
                    if len(ds) == 1:
                        d = next(iter(ds)).id
                        out[d] = out.get(d, 0) + int(a.nbytes)
        return out

    def _candidates_on(self, dev_id: int, exclude=()) -> List[Tuple[float, str, int]]:
        """(idle_age, name, device_bytes) of demotable records whose arrays
        live on `dev_id`, coldest (longest-idle) first."""
        cands: List[Tuple[float, str, int]] = []
        with self.engine.store._lock:
            items = list(self.engine.store._states.items())
        for name, rec in items:
            if name in exclude or rec.expired() or rec.tier != HOT:
                continue
            nbytes = 0
            on_dev = False
            for a in rec.arrays.values():
                devs = getattr(a, "devices", None)
                if devs is None:
                    continue
                try:
                    ds = devs()
                except TypeError:  # pragma: no cover
                    continue
                if len(ds) == 1 and next(iter(ds)).id == dev_id:
                    on_dev = True
                    nbytes += int(a.nbytes)
            if on_dev and self._demotable(name, rec):
                cands.append((self.touch_age(name), name, nbytes))
        cands.sort(reverse=True)  # longest idle first
        return cands

    def make_room(self, dev_id: int, need_bytes: int, exclude=()) -> int:
        """Demote longest-idle clean records off `dev_id` until
        `need_bytes` are freed (or candidates run out).  Returns freed."""
        freed = 0
        for _age, name, nbytes in self._candidates_on(dev_id, exclude):
            if freed >= need_bytes:
                break
            if self.demote(name):
                freed += nbytes
        return freed

    def admit_device_alloc(self, device, delta_bytes: int,
                           exclude=()) -> None:
        """Growth admission against ``device-budget-bytes``: demote colder
        records first, refuse (VectorBudgetError) only as the LAST resort
        — the ISSUE 20 bugfix for unsharded bank growth."""
        budget = DEVICE_BUDGET_BYTES
        if not budget or delta_bytes <= 0:
            return
        dev_id = getattr(device, "id", 0) if device is not None else 0
        hot = self.hot_bytes_by_device().get(dev_id, 0)
        over = hot + delta_bytes - budget
        if over <= 0:
            return
        freed = self.make_room(dev_id, over, exclude=exclude)
        if freed < over:
            from redisson_tpu.services.vector import VectorBudgetError

            raise VectorBudgetError(
                f"allocating {delta_bytes} bytes on device {dev_id} exceeds "
                f"the {budget}-byte device-budget-bytes and only {freed} of "
                f"the needed {over} bytes were demotable (the rest is hot, "
                f"dirty, or fenced)"
            )

    # -- sweeper --------------------------------------------------------------

    def sweep(self) -> Dict[str, int]:
        """One control-loop pass: (1) demote each over-budget device back
        under ``device-budget-bytes``; (2) spill long-idle WARM records
        COLD; (3) GC spill files of deleted records."""
        out = {"demoted": 0, "colded": 0, "freed_bytes": 0}
        budget = DEVICE_BUDGET_BYTES
        if budget:
            for dev_id, hot in self.hot_bytes_by_device().items():
                if hot > budget:
                    before = self.demotions_warm
                    out["freed_bytes"] += self.make_room(dev_id, hot - budget)
                    out["demoted"] += self.demotions_warm - before
        if self.cold_after_s > 0:
            with self.engine.store._lock:
                warm = [
                    n for n, r in self.engine.store._states.items()
                    if r.tier == WARM and not r.expired()
                ]
            for name in warm:
                if self.touch_age(name) >= self.cold_after_s:
                    if self.demote(name, cold=True):
                        out["colded"] += 1
        self._gc_spills()
        return out

    def _gc_spills(self) -> None:
        if self._spill_dir is None or not os.path.isdir(self._spill_dir):
            return
        with self.engine.store._lock:
            live = {
                r.cold_path for r in self.engine.store._states.values()
                if r.cold_path is not None
            }
        for fn in os.listdir(self._spill_dir):
            if not fn.endswith(".spill"):
                continue
            path = os.path.join(self._spill_dir, fn)
            if path not in live:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def start_sweeper(self, interval: float) -> None:
        if self._sweeper is not None:
            return
        self._sweep_interval = float(interval)

        def _run():
            while not self._stop.wait(self._sweep_interval):
                try:
                    self.sweep()
                except Exception:  # noqa: BLE001 — sweep must never die
                    pass

        self._sweeper = threading.Thread(
            target=_run, name="rtpu-residency", daemon=True
        )
        self._sweeper.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._sweeper
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._sweeper = None
        if self._owns_spill_dir and self._spill_dir is not None:
            import shutil

            shutil.rmtree(self._spill_dir, ignore_errors=True)
            self._spill_dir = None
            self._owns_spill_dir = False

    # -- census / observability -----------------------------------------------

    def census(self) -> Dict[str, float]:
        """Per-device per-tier byte rows (nonzero only — drain-to-absence
        on DEL/DROPINDEX) plus the monotonic counters."""
        hot: Dict[int, int] = {}
        warm: Dict[int, int] = {}
        cold: Dict[int, int] = {}
        with self.engine.store._lock:
            items = list(self.engine.store._states.items())
        with no_promote():
            for _name, rec in items:
                if rec.expired():
                    continue
                if rec.tier == WARM and rec.stash is not None:
                    d = rec.stash_dev
                    warm[d] = warm.get(d, 0) + _host_bytes(rec.stash)
                elif rec.tier == COLD:
                    d = rec.stash_dev
                    cold[d] = cold.get(d, 0) + int(rec.cold_bytes)
                else:
                    for a in rec.arrays.values():
                        devs = getattr(a, "devices", None)
                        if devs is None:
                            continue
                        try:
                            ds = devs()
                        except TypeError:  # pragma: no cover
                            continue
                        if len(ds) == 1:
                            d = next(iter(ds)).id
                            hot[d] = hot.get(d, 0) + int(a.nbytes)
        rows: Dict[str, float] = {}
        for tier, per in (("hot", hot), ("warm", warm), ("cold", cold)):
            for d, n in sorted(per.items()):
                if n:
                    rows[f"residency_bytes_dev{d}_{tier}"] = float(n)
        rows["residency_promotions"] = float(self.promotions)
        rows["residency_demotions_warm"] = float(self.demotions_warm)
        rows["residency_demotions_cold"] = float(self.demotions_cold)
        rows["residency_cold_loads"] = float(self.cold_loads)
        rows["residency_fault_in_ms_total"] = round(self.fault_in_ms_total, 3)
        rows["residency_fault_in_ms_max"] = round(self.fault_in_ms_max, 3)
        return rows

    def tier_of(self, name: str) -> Optional[str]:
        with no_promote():
            rec = self.engine.store.get_unguarded(name)
        return None if rec is None else rec.tier
