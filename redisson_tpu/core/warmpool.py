"""Persistent kernel warm-pool: precompile the hot programs OUTSIDE any
request's latency budget (ISSUE 2 tentpole, part 2).

XLA compiles lazily: the first dispatch of every (kernel, shape-bucket,
dtype) combination pays trace + compile (~seconds for the big programs) or,
with the persistent compile cache, a program LOAD (~1-2s for the word-count
sort) — inside whatever request happened to arrive first.  That is exactly
the MapReduce cold-start miss (BENCH r3-r5: 2.3s vs the <2s target) and the
windowed-phase recompile stalls.  The reference keeps executor workers warm
for the same reason (executor/TasksRunnerService.java:54,192 warm pools);
here "warm" means the compiled program is resident in the in-process jit
cache before serving starts.

One process-global pool (jit caches are process-global), keyed by
``(verb, shape, dtype, epoch, geometry, device)``:

  * verb   — logical kernel family ("bloom.add", "hll.add", "wc", ...);
  * shape  — the padded shape bucket(s) the program was built for;
  * dtype  — operand dtype discriminator;
  * epoch  — mesh epoch for sharded programs (a reshard invalidates those
             builds; single-chip programs use epoch 0);
  * device — the PLACEMENT axis (ISSUE 8): jit specializes per committed
             device, so with the slot table device-sharded a program warmed
             on device 0 is cold on device 3.  ``prewarm_store`` therefore
             warms each geometry on every requested device (the whole local
             mesh under ``Engine.prewarm`` with placement on) — a slot
             handoff onto any device then hits the pool with ZERO rebuilds.
             Single-device engines use device id -1 (the default device),
             preserving every pre-placement key.

The pool only BOOKKEEPS which combinations are already warm (bounded LRU —
it never pins device memory; compiled executables live in jax's own cache);
``warm()`` runs the dummy-dispatch thunk exactly once per key, so engine
startup, mapper boot and repeated prewarm calls cannot duplicate compile
work.  ``prewarm_store`` walks an engine's live records and warms each
record kind's hot verbs at the requested batch buckets — the server-boot
ritual (TpuServer --prewarm / Engine.prewarm()).

The SHARDED warm pool (cross-epoch kernel reuse when a reshard returns to a
previous geometry) lives on parallel/manager.MeshManager; this module covers
the single-chip engine kernels and the MapReduce programs.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterable, Optional, Tuple


class KernelWarmPool:
    """Bounded bookkeeping of warmed (verb, shape, dtype, epoch) keys."""

    def __init__(self, max_entries: int = 512):
        self._entries: "OrderedDict[Tuple, float]" = OrderedDict()
        self._max = max_entries
        self._lock = threading.Lock()
        self.hits = 0    # warm() calls that found the key already warm
        self.warms = 0   # thunks actually executed

    def warmed(self, key: Tuple) -> bool:
        with self._lock:
            return key in self._entries

    def warm(self, key: Tuple, thunk) -> bool:
        """Run `thunk` once per key; True iff THIS call executed it.
        The thunk runs OUTSIDE the lock (it may compile for seconds); a
        concurrent warm of the same key at worst duplicates one compile —
        jax's jit cache dedupes the program itself."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return False
        thunk()
        import time

        with self._lock:
            self._entries[key] = time.monotonic()
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)
            self.warms += 1
        return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits, "warms": self.warms}


# process-global pool: the jit cache it mirrors is process-global too
POOL = KernelWarmPool()


def _dev_key(device) -> int:
    """Pool-key device axis: -1 = the default (pre-placement) device, so
    single-device engines keep their historical keys exactly."""
    return -1 if device is None else getattr(device, "id", 0)


def _on(device, arr):
    """Commit a throwaway warm plane to `device` (the kernel then compiles
    FOR that device); None keeps the default placement."""
    if device is None:
        return arr
    import jax

    return jax.device_put(arr, device)


def _warm_bloom(engine, rec, buckets: Iterable[int], device=None) -> int:
    import numpy as np

    import jax

    from redisson_tpu.core import kernels as K
    from redisson_tpu.ops import bittensor as bt

    m, k = rec.meta["m"], rec.meta["k"]
    n = 0
    for b in buckets:
        b = K.bucket_size(b)

        def thunk(b=b):
            lh = K.stage(np.zeros((2, b), np.uint32))
            lh2 = K.stage(np.zeros((2, b), np.uint32))
            nv = K.valid_n(1)
            # throwaway zeros plane of the record's geometry: add kernels
            # DONATE their state, so real record planes never warm directly
            bits = _on(device, bt.make(m))
            bits, _ = K.bloom_add_packed(bits, lh, nv, k, m)
            K.bloom_contains_packed_bits(bits, lh, nv, k, m)
            bits2 = _on(device, bt.make(m))
            bits2, _ = K.bloom_add_packed_count(bits2, lh, nv, k, m)
            out = K.bloom_fused_add_contains(bits2, lh, nv, lh2, nv, k, m)
            jax.block_until_ready(out[0])

        n += POOL.warm(("bloom", (b,), "u64", 0, (m, k), _dev_key(device)), thunk)
    return n


def _warm_bloom_array(engine, rec, buckets: Iterable[int], device=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from redisson_tpu.core import kernels as K

    m, k, tenants = rec.meta["m"], rec.meta["k"], rec.meta["tenants"]
    n = 0
    for b in buckets:
        b = K.bucket_size(b)

        def thunk(b=b):
            tlh = K.stage(np.zeros((3, b), np.uint32))
            nv = K.valid_n(1)
            bank = _on(device, jnp.zeros((tenants, m), jnp.uint8))
            bank, _ = K.bloom_bank_add_packed_bits(bank, tlh, nv, k, m)
            out = K.bloom_bank_contains_packed_bits(bank, tlh, nv, k, m)
            jax.block_until_ready(out)

        n += POOL.warm(
            ("bloom_array", (tenants, b), "u64", 0, (m, k), _dev_key(device)),
            thunk,
        )
    return n


def _warm_hll(engine, rec, buckets: Iterable[int], device=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from redisson_tpu.core import kernels as K

    p = rec.meta["p"]
    regs = rec.arrays["regs"]
    shape = regs.shape
    n = 0
    for b in buckets:
        b = K.bucket_size(b)

        def thunk(b=b):
            nv = K.valid_n(1)
            dummy = _on(device, jnp.zeros(shape, regs.dtype))
            if len(shape) == 2:
                tlh = K.stage(np.zeros((3, b), np.uint32))
                out = K.hll_bank_add_packed(dummy, tlh, nv, p)
            else:
                lh = K.stage(np.zeros((2, b), np.uint32))
                out = K.hll_add_packed(dummy, lh, nv, p)
            jax.block_until_ready(out)

        n += POOL.warm(
            ("hll", shape, str(regs.dtype), 0, (p, b), _dev_key(device)), thunk
        )
    return n


def _warm_vector_bank(engine, rec, buckets: Iterable[int], device=None) -> int:
    """Warm one embedding bank's KNN programs (ISSUE 15): the FLAT
    matmul-top-k (and the IVF routed gather when the record carries a
    trained coarse index) at the bank's exact plane geometry, per device.
    Sharded banks hit this once PER SHARD RECORD — each shard is a plain
    vector_bank record on its own device — and their cross-shard merge
    warms through the manifest warmer below."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from redisson_tpu.core import kernels as K

    bank = rec.arrays.get("bank")
    if bank is None:
        return 0  # never flushed: no geometry to warm yet
    meta = rec.meta
    metric = str(meta.get("metric", "COSINE"))
    dtype = str(meta.get("dtype", "FLOAT32"))
    cap, pwidth = bank.shape
    k = max(1, min(10, cap))
    cells = rec.arrays.get("cells")
    cents = rec.arrays.get("centroids")
    nprobe = int(meta.get("nprobe", 0) or 1)
    n = 0

    def thunk():
        q = K.stage(np.zeros((1, pwidth), np.float32))
        nv = K.valid_n(1)
        dummy = _on(device, jnp.zeros(bank.shape, bank.dtype))
        scale = rec.arrays.get("scale")
        dscale = (
            _on(device, jnp.ones((cap,), jnp.float32))
            if scale is not None else None
        )
        dbias = _on(device, jnp.zeros((cap,), jnp.float32))
        if dscale is not None:
            out = K.knn_topk_q(dummy, dscale, dbias, q, nv, k, metric)
        else:
            out = K.knn_topk(dummy, dbias, q, nv, k, metric)
        if cells is not None and cents is not None:
            dc = _on(device, jnp.zeros(cents.shape, jnp.float32))
            dl = _on(device, jnp.zeros(cells.shape, jnp.int32))
            np_eff = max(1, min(nprobe, cents.shape[0]))
            k_ivf = max(1, min(k, np_eff * cells.shape[1]))
            if dscale is not None:
                out = K.knn_ivf_topk_q(dummy, dscale, dbias, dc, dl, q,
                                       nv, k_ivf, np_eff, metric)
            else:
                out = K.knn_ivf_topk(dummy, dbias, dc, dl, q, nv,
                                     k_ivf, np_eff, metric)
        jax.block_until_ready(out[0])

    ivf_key = (
        (cents.shape, cells.shape, nprobe)
        if cells is not None and cents is not None else None
    )
    n += POOL.warm(
        ("ftvec_knn", bank.shape, str(bank.dtype), metric, k, dtype,
         ivf_key, _dev_key(device)),
        thunk,
    )
    return n


def _warm_vector_manifest(engine, rec, buckets: Iterable[int],
                          device=None) -> int:
    """Warm the sharded-KNN MERGE program for a bank constellation: the
    jit instance comes from MeshManager's geometry-keyed cross-epoch pool
    (knn_merge_kernel), so a 4->8->4 reshard re-enters prewarm with the
    already-built program — 0 rebuilds, 0 first-dispatch traces."""
    import jax
    import jax.numpy as jnp

    from redisson_tpu.parallel.manager import MeshManager

    names = rec.meta.get("shard_names") or ()
    n_legs = len(names)
    if n_legs < 2:
        return 0
    mm = MeshManager.of(engine)
    geom = mm.geometry()
    merge = mm.knn_merge_kernel(n_legs, geom=geom)
    k = 10

    def thunk():
        dists = tuple(
            _on(device, jnp.zeros((1, k), jnp.float32))
            for _ in range(n_legs)
        )
        idxs = tuple(
            _on(device, jnp.zeros((1, k), jnp.int32)) for _ in range(n_legs)
        )
        sop = _on(device, jnp.zeros((n_legs * k,), jnp.int32))
        out = merge(dists, idxs, sop, k)
        jax.block_until_ready(out[0])

    return POOL.warm(
        ("ftvec_merge", n_legs, k, mm._mesh_key(geom.mesh),
         _dev_key(device)),
        thunk,
    )


_KIND_WARMERS = {
    "bloom": _warm_bloom,
    "bloom_array": _warm_bloom_array,
    "hll": _warm_hll,
    "hll_array": _warm_hll,
    "vector_bank": _warm_vector_bank,
    "vector_bank_manifest": _warm_vector_manifest,
}


def prewarm_store(engine, names: Optional[Iterable[str]] = None,
                  buckets: Iterable[int] = (0,),
                  devices: Optional[Iterable] = None) -> int:
    """Warm the hot verbs of every (named) live record at the given batch
    buckets (0 = the minimum bucket).  Returns the number of programs this
    call actually compiled/loaded; everything already warm is free.  Run at
    server boot or before a timed serving phase — never on the hot path.

    ``devices``: the placement axis — warm each geometry ON EACH of these
    devices (Engine.prewarm passes the whole local mesh with placement on,
    so ``tpu-server --prewarm`` compiles every device's kernels, not just
    device 0's, and a later slot handoff re-hits the pool: 0 rebuilds).
    None warms each record on its CURRENT device (the owner with placement
    on, the default device otherwise)."""
    from redisson_tpu.core import kernels as K

    buckets = [K.bucket_size(max(1, b)) for b in buckets]
    warmed = 0
    for name in list(names) if names is not None else engine.store.keys():
        rec = engine.store.get(name)
        if rec is None:
            continue
        warmer = _KIND_WARMERS.get(rec.kind)
        if warmer is None:
            continue
        if devices is not None:
            devs = list(devices)
        else:
            devs = [engine.device_for_name(name)]  # None with placement off
        with engine.locked(name):
            rec = engine.store.get(name)
            if rec is None:
                continue
            for dev in devs:
                warmed += warmer(engine, rec, buckets, device=dev)
    return warmed


def prewarm_word_count_pooled(total_chars: int, total_words: int,
                              n_chunks: int = 2) -> bool:
    """services.mapreduce.prewarm_word_count through the pool: repeated
    boots / repeated jobs over same-bucket corpora skip the (re)warm
    entirely.  True iff this call did the work."""
    from redisson_tpu.core import kernels as K

    b = K.bucket_size(max(1, -(-total_chars // n_chunks)))
    eb = K.bucket_size(max(1, -(-total_words // n_chunks)))

    def thunk():
        from redisson_tpu.services.mapreduce import prewarm_word_count

        prewarm_word_count(total_chars, total_words, n_chunks=n_chunks)

    return POOL.warm(("wc", (b, eb, n_chunks), "uint8", 0, ()), thunk)
