"""Checkpoint / restore of the DeviceStore — the RDB-snapshot analog.

The reference delegates durability to Redis RDB/AOF (SURVEY.md §5.4); here the
"server state" is device-resident (HBM) plus host-side python structures, so
the framework needs its own snapshot path: device arrays are pulled to host
(one `np.asarray` per array — a single device→host DMA each), serialized with
the host state into a single versioned container, and written atomically
(tmp + rename) so a crash mid-save never corrupts the previous snapshot.

Restore re-creates every StateRecord and `jax.device_put`s arrays back onto
the default device — snapshots carry plain host arrays, never a device
layout, so a checkpoint taken on one mesh restores on any other; format
stability beats layout fidelity (SURVEY.md §7.3 hard-part 5: hash/layout
compatibility is part of the persisted format, so `meta` carries the hash
version of ops/bittensor).

Wire format (version 1):
    8-byte magic  b"RTPUCKP1"
    pickle(protocol 4) of {
        "format": 1, "saved_at": epoch-seconds, "hash_version": int,
        "records": [
            {"name", "kind", "meta", "version", "expire_at",
             "host_pickled": bytes, "arrays": {name: np.ndarray}},
            ...
        ],
    }
    8-byte trailer magic b"RTPUCRC1" + 4-byte big-endian CRC32 of everything
    before the trailer — a torn write (power loss after the rename, media
    that lied about fsync) truncates the tail, so a missing/mismatched
    trailer is the crash-consistency detector.

Durability generations (ISSUE 4): ``save`` keeps the last ``keep`` good
snapshots — the previous head rotates to ``<path>.1``, the one before to
``<path>.2``, ... — and fsyncs the parent DIRECTORY after the final
``os.replace`` so the rename itself survives power loss.  ``load`` verifies
the CRC trailer and, when the head is corrupt or truncated, falls back to
the newest intact generation LOUDLY (logged + counted in ``STATS``; the
chaos census exposes the counters via
``ResourceCensus.track_checkpoints``).

Restore uses the restricted unpickler policy of net/safe_pickle.py extended
with numpy reconstruction — a checkpoint is the same trust domain as a Redis
RDB file, but there is no reason to allow arbitrary classes either.

Fault injection: the two file-I/O event sites (write, fsync) consult the
process-global chaos plane (``chaos/faults.py`` storage stream: ``enospc``,
``torn_write``, ``fsync_fail``) exactly like ``net/client.py`` consults it
for transport events — injected storage faults flow through the REAL
durability machinery, never around it.
"""
from __future__ import annotations

import io
import logging
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from redisson_tpu.core import residency as _residency
from redisson_tpu.utils.durability import fsync_dir as _fsync_dir

MAGIC = b"RTPUCKP1"
TRAILER_MAGIC = b"RTPUCRC1"
FORMAT = 1
DEFAULT_GENERATIONS = 3  # head + 2 rotated backups

_log = logging.getLogger("redisson_tpu.checkpoint")

# durability bookkeeping, exposed to the chaos census
# (ResourceCensus.track_checkpoints): corruption must be OBSERVABLE, not
# just survived
STATS: Dict[str, int] = {
    "corrupt_generations": 0,   # candidates that failed CRC/magic on load
    "generation_fallbacks": 0,  # loads served by a non-head generation
}


class CheckpointCorruptError(ValueError):
    """A checkpoint file failed structural verification (bad magic,
    truncated payload, CRC mismatch, unreadable pickle) — distinct from
    version/hash INCOMPATIBILITY, which raises plain ValueError and never
    falls back (an incompatible head means incompatible generations)."""


def _storage_plane():
    # the same process-global plane net/client.py consults; checkpoint I/O
    # is cold path, so no zero-cost contract applies here
    from redisson_tpu.net import client as _net

    return _net._fault_plane


# serializes same-process savers (AutoCheckpointer thread vs SAVE command);
# cross-process uniqueness comes from the tmp-file name
_save_lock = __import__("threading").Lock()
_save_seq = __import__("itertools").count()


def _snapshot_records(engine) -> List[Dict[str, Any]]:
    """Materialize every live record to host memory under the store lock."""
    store = engine.store
    out: List[Dict[str, Any]] = []
    with store._lock:
        items = [(n, r) for n, r in store._states.items() if not r.expired()]
    for name, rec in items:
        # per-record lock: a compound mutation replaces arrays wholesale, so
        # holding the record lock gives a consistent (kind, meta, arrays) cut.
        # host state is serialized HERE, inside the lock — keeping a live
        # reference would race with mutators once the lock is released
        with engine.locked(name):
            # residency-aware host view (ISSUE 20): a WARM record's exact
            # bytes come from its host stash and a COLD one's from its
            # spill — demoted records checkpoint WITHOUT promotion
            arrays = _residency.record_host_arrays(rec)
            out.append(
                {
                    "name": name,
                    "kind": rec.kind,
                    "meta": dict(rec.meta),
                    "version": rec.version,
                    # creation identity MUST survive a restore: replication
                    # and migration transfers compare (nonce, version), and
                    # a restored record minted a fresh nonce would read as
                    # "recreated" — apply_records would then install its
                    # STALE state over a peer's newer copy of the same
                    # lineage (the restored-source fork, ISSUE 6 soak)
                    "nonce": rec.nonce,
                    "expire_at": rec.expire_at,
                    "host_pickled": pickle.dumps(rec.host, protocol=4),
                    "arrays": arrays,
                }
            )
    return out


def generation_path(path: str, gen: int) -> str:
    """Generation 0 is the head; generation N is the Nth-newest backup."""
    return path if gen == 0 else f"{path}.{gen}"


def save(engine, path: str, keep: int = DEFAULT_GENERATIONS) -> int:
    """Snapshot the full DeviceStore to `path`. Returns #records saved.

    Keeps the ``keep - 1`` previous snapshots as rotated generations
    (``<path>.1`` newest) so a head corrupted AFTER a successful save
    (torn write surfacing at the block layer, admin truncation) still
    leaves a loadable lineage.  The write path is: tmp file -> fsync(file)
    -> rotate old generations -> ``os.replace`` onto the head ->
    fsync(parent dir), so no crash point can lose BOTH the old head and
    the new one."""
    from redisson_tpu.utils import hashing as H

    with _save_lock:
        records = _snapshot_records(engine)
        payload = {
            "format": FORMAT,
            "saved_at": time.time(),
            "hash_version": getattr(H, "HASH_VERSION", 1),
            "records": records,
        }
        body = MAGIC + pickle.dumps(payload, protocol=4)
        data = body + TRAILER_MAGIC + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)
        tmp = f"{path}.tmp.{os.getpid()}.{next(_save_seq)}"
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        plane = _storage_plane()
        if plane is not None:
            # may raise OSError(ENOSPC), or return a torn PREFIX that this
            # save then treats as fully written (the media-lied model)
            data = plane.on_storage_write(tmp, data)
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                if plane is not None:
                    plane.on_storage_fsync(tmp)  # may raise OSError(EIO)
                os.fsync(f.fileno())
            # rotate: previous head -> .1, .1 -> .2, ... (newest-first);
            # anything past `keep - 1` backups falls off the end
            if keep > 1 and os.path.exists(path):
                for gen in range(keep - 1, 1, -1):
                    older = generation_path(path, gen - 1)
                    if os.path.exists(older):
                        os.replace(older, generation_path(path, gen))
                os.replace(path, generation_path(path, 1))
            os.replace(tmp, path)
            # the renames live in the DIRECTORY's blocks: without this
            # fsync a power loss can roll the whole rotation back
            _fsync_dir(parent)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return len(records)


def _make_unpickler(stream):
    from redisson_tpu.net.safe_pickle import RestrictedUnpickler

    class _CheckpointUnpickler(RestrictedUnpickler):
        """safe_pickle policy + numpy array reconstruction."""

        def find_class(self, module: str, name: str):
            if module.startswith("numpy"):
                import importlib

                return getattr(importlib.import_module(module), name)
            return super().find_class(module, name)

    return _CheckpointUnpickler(stream)


def _loads(data: bytes):
    return _make_unpickler(io.BytesIO(data)).load()


def read_verified(path: str):
    """Read + structurally verify ONE checkpoint file; returns the payload
    dict or raises :class:`CheckpointCorruptError`."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(MAGIC):
        raise CheckpointCorruptError(f"not a redisson_tpu checkpoint: {path!r}")
    trailer_len = len(TRAILER_MAGIC) + 4
    if len(data) < len(MAGIC) + trailer_len or data[-trailer_len:-4] != TRAILER_MAGIC:
        raise CheckpointCorruptError(
            f"checkpoint truncated (CRC trailer missing): {path!r}"
        )
    body = data[:-trailer_len]
    (crc,) = struct.unpack(">I", data[-4:])
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise CheckpointCorruptError(
            f"checkpoint CRC mismatch (torn write?): {path!r}"
        )
    try:
        return _loads(body[len(MAGIC):])
    except Exception as e:  # noqa: BLE001 — CRC passed but pickle didn't: corrupt
        raise CheckpointCorruptError(
            f"checkpoint payload unreadable: {path!r}: {e}"
        ) from e


def _load_lineage(path: str):
    """Try the head, then each rotated generation, newest first.  Returns
    ``(payload, generation_index)``; corruption is counted and logged
    loudly, and only the exhaustion of EVERY generation re-raises (the
    head's error, so callers see the primary failure)."""
    head_err: Optional[Exception] = None
    gen = 0
    while True:
        cand = generation_path(path, gen)
        if gen > 0 and not os.path.exists(cand):
            break
        try:
            payload = read_verified(cand)
        except FileNotFoundError as e:
            # gen 0 only (gen > 0 is existence-guarded above): save()'s
            # crash window between the rotation rename and the head install
            # leaves NO head but an intact .1 — fall through to the
            # generations; a checkpoint that never existed re-raises below
            # once no generation turns up either
            if head_err is None:
                head_err = e
            gen += 1
            continue
        except CheckpointCorruptError as e:
            STATS["corrupt_generations"] += 1
            _log.error("checkpoint generation %s corrupt: %s", gen, e)
            if head_err is None:
                head_err = e
            gen += 1
            continue
        if gen > 0:
            STATS["generation_fallbacks"] += 1
            _log.error(
                "checkpoint head %r missing/corrupt; falling back to "
                "generation %d (%r)", path, gen, cand,
            )
        return payload, gen
    assert head_err is not None
    raise head_err


def load(engine, path: str) -> int:
    """Restore a snapshot into the engine's store. Returns #records loaded.

    Existing records with the same name are overwritten (RESTORE REPLACE
    semantics); records whose TTL already elapsed are skipped.  A corrupt
    or truncated head (bad magic, missing/mismatched CRC trailer, torn
    pickle) falls back to the newest intact generation — loudly: logged,
    counted in ``STATS``, and raising :class:`CheckpointCorruptError` only
    when NO generation survives.
    """
    import jax

    from redisson_tpu.core.store import StateRecord
    from redisson_tpu.utils import hashing as H

    payload, _gen = _load_lineage(path)
    if payload.get("format") != FORMAT:
        raise ValueError(f"unsupported checkpoint format {payload.get('format')}")
    hv = payload.get("hash_version", 1)
    if hv != getattr(H, "HASH_VERSION", 1):
        # bloom/HLL indexes are a function of the hash (SURVEY.md §7.3 item 5):
        # a mismatched hash version would silently corrupt membership answers
        raise ValueError(
            f"checkpoint hash_version={hv} != runtime {getattr(H, 'HASH_VERSION', 1)}"
        )

    now = time.time()
    n = 0
    for r in payload["records"]:
        if r["expire_at"] is not None and r["expire_at"] <= now:
            continue
        arrays = {k: jax.device_put(v) for k, v in r["arrays"].items()}
        rec = StateRecord(
            kind=r["kind"],
            meta=r["meta"],
            arrays=arrays,
            host=_loads(r["host_pickled"]) if "host_pickled" in r else r.get("host"),
            version=r["version"],
            expire_at=r["expire_at"],
        )
        if "nonce" in r:
            # restore is NOT a recreation: keep the record's creation
            # identity so peers still recognize this lineage (legacy
            # checkpoints without the field keep the fresh nonce)
            rec.nonce = r["nonce"]
        with engine.locked(r["name"]):
            engine.store.put(r["name"], rec)
        n += 1
    return n


class AutoCheckpointer:
    """Background periodic snapshotter (the `save <sec> <changes>` RDB knob).

    Runs `save()` every `interval` seconds on a daemon thread; failures are
    recorded on `.last_error` and never kill the loop (a failed snapshot must
    not take down the data plane).
    """

    def __init__(self, engine, path: str, interval: float = 300.0):
        import threading

        self.engine = engine
        self.path = path
        self.interval = interval
        self.last_save: float | None = None
        self.last_error: Exception | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rtpu-checkpoint", daemon=True
        )

    def start(self) -> "AutoCheckpointer":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                save(self.engine, self.path)
                self.last_save = time.time()
                self.last_error = None
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                self.last_error = e

    def stop(self, flush: bool = True, join_timeout: float = 5.0) -> bool:
        """Stop the loop, then take a FINAL snapshot (flush-on-stop: writes
        since the last interval tick would otherwise die with the process —
        the `SHUTDOWN SAVE` discipline applied to the background saver).

        Returns whether the thread actually joined; ``False`` means a save
        longer than ``join_timeout`` is STILL RUNNING on the daemon thread
        — previously this was silent, and the final snapshot is skipped in
        that case (the in-flight save IS the freshest one, and a second
        saver would just queue behind its lock)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
        joined = not self._thread.is_alive()
        if flush and joined and self._thread.ident is not None:
            try:
                save(self.engine, self.path)
                self.last_save = time.time()
                self.last_error = None
            except Exception as e:  # noqa: BLE001 — report, never raise mid-teardown
                self.last_error = e
        return joined


# -- single-record portable blobs (RObject.dump/restore + the DUMP verb) -----

def dump_record(engine, name: str) -> bytes:
    """ONE record as a self-contained blob: same field set as checkpoint
    records (kind/meta/host/arrays/expire_at) plus the hash_version stamp —
    dump/restore and checkpoints must never drift, or a migrated bloom
    filter would silently answer wrong under a different hash build."""
    from redisson_tpu.utils import hashing as H

    with engine.locked(name), _residency.no_promote():
        rec = engine.store.get(name)
        if rec is None:
            raise KeyError(f"object '{name}' does not exist")
        payload = {
            "format": 1,
            "hash_version": getattr(H, "HASH_VERSION", 1),
            "kind": rec.kind,
            "meta": dict(rec.meta),
            "expire_at": rec.expire_at,
            "host_pickled": pickle.dumps(rec.host, protocol=4),
            # residency-aware: DUMP of a WARM/COLD record ships its stash/
            # spill bytes without faulting the arrays back into HBM
            "arrays": _residency.record_host_arrays(rec),
        }
    return pickle.dumps(payload, protocol=4)


def restore_record(
    engine, name: str, state: bytes, ttl=None, replace: bool = False,
    persist: bool = False,
) -> None:
    """Install a dump_record blob under `name`.  BUSYKEY unless `replace`
    (Redis RESTORE semantics); `ttl` (seconds) overrides the blob's own
    expire_at; `persist` strips expiry entirely; hash-version mismatches
    refuse exactly like checkpoint.load.  A blob whose carried TTL has
    ALREADY elapsed refuses loudly — installing it would reply OK and then
    serve nothing (silent loss), while silently resurrecting it persistent
    would serve data past its expiry."""
    import jax.numpy as jnp

    from redisson_tpu.core.store import StateRecord
    from redisson_tpu.utils import hashing as H

    payload = _loads(bytes(state))  # restricted unpickler: wire-reachable
    if not isinstance(payload, dict) or payload.get("format") != 1:
        raise ValueError("unrecognized dump payload")
    hv = payload.get("hash_version", 1)
    if hv != getattr(H, "HASH_VERSION", 1):
        raise ValueError(
            f"dump hash_version={hv} != runtime {getattr(H, 'HASH_VERSION', 1)}"
        )
    host = _loads(payload["host_pickled"])  # inner state is attacker-reachable too
    with engine.locked(name):
        if not replace and engine.store.exists(name):
            raise ValueError(f"BUSYKEY object '{name}' already exists")
        rec = StateRecord(
            kind=payload["kind"],
            meta=dict(payload["meta"]),
            arrays={k: jnp.asarray(v) for k, v in payload["arrays"].items()},
            host=host,
        )
        if persist:
            rec.expire_at = None
        elif ttl is not None:
            rec.expire_at = time.time() + ttl
        else:
            carried = payload.get("expire_at")
            if carried is not None and carried <= time.time():
                raise ValueError(
                    "dump TTL already elapsed; pass an explicit ttl or "
                    "persist=True (wire: RESTORE ... PERSIST)"
                )
            rec.expire_at = carried
        engine.store.delete(name)
        engine.store.put(name, rec)


def clone_record(engine, src_name: str, dst_name: str, replace: bool = False) -> bool:
    """COPY semantics shared by RObject.copy_to and the COPY verb: clone one
    record under a new name.  Device arrays get a DEVICE-SIDE deep copy
    (records mutate through donated buffers — a shared reference dies on
    the next write to either side); host state deep-copies via pickle."""
    import jax.numpy as jnp

    from redisson_tpu.core.store import StateRecord

    with engine.locked_many([src_name, dst_name]), _residency.no_promote():
        rec = engine.store.get(src_name)
        if rec is None:
            return False
        if engine.store.exists(dst_name) and not replace:
            return False
        if rec.stash is None and rec.cold_path is None:
            arrays = {k: jnp.copy(v) for k, v in rec.arrays.items()}
        else:
            # demoted source: the clone hydrates HOT from the host view
            # (the source itself stays WARM/COLD — copying must not
            # double its HBM footprint)
            arrays = {
                k: jnp.asarray(v)
                for k, v in _residency.record_host_arrays(rec).items()
            }
        clone = StateRecord(
            kind=rec.kind,
            meta=pickle.loads(pickle.dumps(dict(rec.meta))),
            arrays=arrays,
            host=pickle.loads(pickle.dumps(rec.host)),
        )
        clone.expire_at = rec.expire_at
        engine.store.delete(dst_name)
        engine.store.put(dst_name, clone)
    return True
