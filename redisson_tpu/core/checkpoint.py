"""Checkpoint / restore of the DeviceStore — the RDB-snapshot analog.

The reference delegates durability to Redis RDB/AOF (SURVEY.md §5.4); here the
"server state" is device-resident (HBM) plus host-side python structures, so
the framework needs its own snapshot path: device arrays are pulled to host
(one `np.asarray` per array — a single device→host DMA each), serialized with
the host state into a single versioned container, and written atomically
(tmp + rename) so a crash mid-save never corrupts the previous snapshot.

Restore re-creates every StateRecord and `jax.device_put`s arrays back onto
the default device — snapshots carry plain host arrays, never a device
layout, so a checkpoint taken on one mesh restores on any other; format
stability beats layout fidelity (SURVEY.md §7.3 hard-part 5: hash/layout
compatibility is part of the persisted format, so `meta` carries the hash
version of ops/bittensor).

Wire format (version 1):
    8-byte magic  b"RTPUCKP1"
    pickle(protocol 4) of {
        "format": 1, "saved_at": epoch-seconds, "hash_version": int,
        "records": [
            {"name", "kind", "meta", "version", "expire_at",
             "host_pickled": bytes, "arrays": {name: np.ndarray}},
            ...
        ],
    }

Restore uses the restricted unpickler policy of net/safe_pickle.py extended
with numpy reconstruction — a checkpoint is the same trust domain as a Redis
RDB file, but there is no reason to allow arbitrary classes either.
"""
from __future__ import annotations

import io
import os
import pickle
import time
from typing import Any, Dict, List

import numpy as np

MAGIC = b"RTPUCKP1"
FORMAT = 1

# serializes same-process savers (AutoCheckpointer thread vs SAVE command);
# cross-process uniqueness comes from the tmp-file name
_save_lock = __import__("threading").Lock()
_save_seq = __import__("itertools").count()


def _snapshot_records(engine) -> List[Dict[str, Any]]:
    """Materialize every live record to host memory under the store lock."""
    store = engine.store
    out: List[Dict[str, Any]] = []
    with store._lock:
        items = [(n, r) for n, r in store._states.items() if not r.expired()]
    for name, rec in items:
        # per-record lock: a compound mutation replaces arrays wholesale, so
        # holding the record lock gives a consistent (kind, meta, arrays) cut.
        # host state is serialized HERE, inside the lock — keeping a live
        # reference would race with mutators once the lock is released
        with engine.locked(name):
            arrays = {k: np.asarray(v) for k, v in rec.arrays.items()}
            out.append(
                {
                    "name": name,
                    "kind": rec.kind,
                    "meta": dict(rec.meta),
                    "version": rec.version,
                    "expire_at": rec.expire_at,
                    "host_pickled": pickle.dumps(rec.host, protocol=4),
                    "arrays": arrays,
                }
            )
    return out


def save(engine, path: str) -> int:
    """Snapshot the full DeviceStore to `path`. Returns #records saved."""
    from redisson_tpu.utils import hashing as H

    with _save_lock:
        records = _snapshot_records(engine)
        payload = {
            "format": FORMAT,
            "saved_at": time.time(),
            "hash_version": getattr(H, "HASH_VERSION", 1),
            "records": records,
        }
        tmp = f"{path}.tmp.{os.getpid()}.{next(_save_seq)}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        try:
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                pickle.dump(payload, f, protocol=4)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return len(records)


def _make_unpickler(stream):
    from redisson_tpu.net.safe_pickle import RestrictedUnpickler

    class _CheckpointUnpickler(RestrictedUnpickler):
        """safe_pickle policy + numpy array reconstruction."""

        def find_class(self, module: str, name: str):
            if module.startswith("numpy"):
                import importlib

                return getattr(importlib.import_module(module), name)
            return super().find_class(module, name)

    return _CheckpointUnpickler(stream)


def _loads(data: bytes):
    return _make_unpickler(io.BytesIO(data)).load()


def load(engine, path: str) -> int:
    """Restore a snapshot into the engine's store. Returns #records loaded.

    Existing records with the same name are overwritten (RESTORE REPLACE
    semantics); records whose TTL already elapsed are skipped.
    """
    import jax

    from redisson_tpu.core.store import StateRecord
    from redisson_tpu.utils import hashing as H

    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"not a redisson_tpu checkpoint: {path!r}")
        payload = _loads(f.read())
    if payload.get("format") != FORMAT:
        raise ValueError(f"unsupported checkpoint format {payload.get('format')}")
    hv = payload.get("hash_version", 1)
    if hv != getattr(H, "HASH_VERSION", 1):
        # bloom/HLL indexes are a function of the hash (SURVEY.md §7.3 item 5):
        # a mismatched hash version would silently corrupt membership answers
        raise ValueError(
            f"checkpoint hash_version={hv} != runtime {getattr(H, 'HASH_VERSION', 1)}"
        )

    now = time.time()
    n = 0
    for r in payload["records"]:
        if r["expire_at"] is not None and r["expire_at"] <= now:
            continue
        arrays = {k: jax.device_put(v) for k, v in r["arrays"].items()}
        rec = StateRecord(
            kind=r["kind"],
            meta=r["meta"],
            arrays=arrays,
            host=_loads(r["host_pickled"]) if "host_pickled" in r else r.get("host"),
            version=r["version"],
            expire_at=r["expire_at"],
        )
        with engine.locked(r["name"]):
            engine.store.put(r["name"], rec)
        n += 1
    return n


class AutoCheckpointer:
    """Background periodic snapshotter (the `save <sec> <changes>` RDB knob).

    Runs `save()` every `interval` seconds on a daemon thread; failures are
    recorded on `.last_error` and never kill the loop (a failed snapshot must
    not take down the data plane).
    """

    def __init__(self, engine, path: str, interval: float = 300.0):
        import threading

        self.engine = engine
        self.path = path
        self.interval = interval
        self.last_save: float | None = None
        self.last_error: Exception | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rtpu-checkpoint", daemon=True
        )

    def start(self) -> "AutoCheckpointer":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                save(self.engine, self.path)
                self.last_save = time.time()
                self.last_error = None
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                self.last_error = e

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


# -- single-record portable blobs (RObject.dump/restore + the DUMP verb) -----

def dump_record(engine, name: str) -> bytes:
    """ONE record as a self-contained blob: same field set as checkpoint
    records (kind/meta/host/arrays/expire_at) plus the hash_version stamp —
    dump/restore and checkpoints must never drift, or a migrated bloom
    filter would silently answer wrong under a different hash build."""
    from redisson_tpu.utils import hashing as H

    with engine.locked(name):
        rec = engine.store.get(name)
        if rec is None:
            raise KeyError(f"object '{name}' does not exist")
        payload = {
            "format": 1,
            "hash_version": getattr(H, "HASH_VERSION", 1),
            "kind": rec.kind,
            "meta": dict(rec.meta),
            "expire_at": rec.expire_at,
            "host_pickled": pickle.dumps(rec.host, protocol=4),
            "arrays": {k: np.asarray(v) for k, v in rec.arrays.items()},
        }
    return pickle.dumps(payload, protocol=4)


def restore_record(
    engine, name: str, state: bytes, ttl=None, replace: bool = False,
    persist: bool = False,
) -> None:
    """Install a dump_record blob under `name`.  BUSYKEY unless `replace`
    (Redis RESTORE semantics); `ttl` (seconds) overrides the blob's own
    expire_at; `persist` strips expiry entirely; hash-version mismatches
    refuse exactly like checkpoint.load.  A blob whose carried TTL has
    ALREADY elapsed refuses loudly — installing it would reply OK and then
    serve nothing (silent loss), while silently resurrecting it persistent
    would serve data past its expiry."""
    import jax.numpy as jnp

    from redisson_tpu.core.store import StateRecord
    from redisson_tpu.utils import hashing as H

    payload = _loads(bytes(state))  # restricted unpickler: wire-reachable
    if not isinstance(payload, dict) or payload.get("format") != 1:
        raise ValueError("unrecognized dump payload")
    hv = payload.get("hash_version", 1)
    if hv != getattr(H, "HASH_VERSION", 1):
        raise ValueError(
            f"dump hash_version={hv} != runtime {getattr(H, 'HASH_VERSION', 1)}"
        )
    host = _loads(payload["host_pickled"])  # inner state is attacker-reachable too
    with engine.locked(name):
        if not replace and engine.store.exists(name):
            raise ValueError(f"BUSYKEY object '{name}' already exists")
        rec = StateRecord(
            kind=payload["kind"],
            meta=dict(payload["meta"]),
            arrays={k: jnp.asarray(v) for k, v in payload["arrays"].items()},
            host=host,
        )
        if persist:
            rec.expire_at = None
        elif ttl is not None:
            rec.expire_at = time.time() + ttl
        else:
            carried = payload.get("expire_at")
            if carried is not None and carried <= time.time():
                raise ValueError(
                    "dump TTL already elapsed; pass an explicit ttl or "
                    "persist=True (wire: RESTORE ... PERSIST)"
                )
            rec.expire_at = carried
        engine.store.delete(name)
        engine.store.put(name, rec)


def clone_record(engine, src_name: str, dst_name: str, replace: bool = False) -> bool:
    """COPY semantics shared by RObject.copy_to and the COPY verb: clone one
    record under a new name.  Device arrays get a DEVICE-SIDE deep copy
    (records mutate through donated buffers — a shared reference dies on
    the next write to either side); host state deep-copies via pickle."""
    import jax.numpy as jnp

    from redisson_tpu.core.store import StateRecord

    with engine.locked_many([src_name, dst_name]):
        rec = engine.store.get(src_name)
        if rec is None:
            return False
        if engine.store.exists(dst_name) and not replace:
            return False
        clone = StateRecord(
            kind=rec.kind,
            meta=pickle.loads(pickle.dumps(dict(rec.meta))),
            arrays={k: jnp.copy(v) for k, v in rec.arrays.items()},
            host=pickle.loads(pickle.dumps(rec.host)),
        )
        clone.expire_at = rec.expire_at
        engine.store.delete(dst_name)
        engine.store.put(dst_name, clone)
    return True
