"""Keyspace admin, strings/buckets, typed data commands, scan cursors (RedissonKeys / RedissonBucket surface).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

import time
from typing import Optional

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.common import (
    _deque,
    _fnum,
    _scan_opts,
    _scan_page,
    _signal_waiters,
    _typed_handle,
)

# -- keyspace admin (RedissonKeys surface) -----------------------------------

@register("KEYS")
def cmd_keys(server, ctx, args):
    pattern = _s(args[0]) if args else "*"
    return [k.encode() for k in server.engine.store.keys(pattern)]


@register("DBSIZE")
def cmd_dbsize(server, ctx, args):
    return len(server.engine.store)


@register("DEL")
def cmd_del(server, ctx, args):
    # Record lock per key: a DEL racing a slot drain must serialize against
    # the in-flight ship (server.py migrate_slot_batch) or the acked delete
    # resurrects from the migrated copy when the slot finalizes.
    def _del(k: str) -> bool:
        with server.engine.locked(k):
            return server.engine.store.delete(k)

    return sum(1 for k in args if _del(_s(k)))


@register("UNLINK")
def cmd_unlink(server, ctx, args):
    return cmd_del(server, ctx, args)


@register("EXISTS")
def cmd_exists(server, ctx, args):
    return sum(1 for k in args if server.engine.store.exists(_s(k)))


def _expire_locked(server, name: str, at) -> int:
    # Same record-lock discipline as DEL: a TTL change racing a slot drain
    # must serialize against the in-flight ship or it silently vanishes.
    with server.engine.locked(name):
        return 1 if server.engine.store.expire(name, at) else 0


@register("EXPIRE")
def cmd_expire(server, ctx, args):
    return _expire_locked(server, _s(args[0]), time.time() + _int(args[1]))


@register("PEXPIRE")
def cmd_pexpire(server, ctx, args):
    return _expire_locked(server, _s(args[0]), time.time() + _int(args[1]) / 1000.0)


@register("PERSIST")
def cmd_persist(server, ctx, args):
    return _expire_locked(server, _s(args[0]), None)


@register("TTL")
def cmd_ttl(server, ctx, args):
    name = _s(args[0])
    if not server.engine.store.exists(name):
        return -2
    ttl = server.engine.store.ttl(name)
    return -1 if ttl is None else int(ttl)


@register("PTTL")
def cmd_pttl(server, ctx, args):
    name = _s(args[0])
    if not server.engine.store.exists(name):
        return -2
    ttl = server.engine.store.ttl(name)
    return -1 if ttl is None else int(ttl * 1000)


@register("RENAME")
def cmd_rename(server, ctx, args):
    src, dst = _s(args[0]), _s(args[1])
    with server.engine.locked_many([src, dst]):
        if not server.engine.store.rename(src, dst):
            raise RespError("ERR no such key")
    return "+OK"


@register("FLUSHALL")
def cmd_flushall(server, ctx, args):
    server.engine.store.flushall()
    return "+OK"


@register("FLUSHDB")
def cmd_flushdb(server, ctx, args):
    # single-keyspace engine: the selected db IS the keyspace
    return cmd_flushall(server, ctx, args)


@register("TYPE")
def cmd_type(server, ctx, args):
    rec = server.engine.store.get(_s(args[0]))
    return ("+" + (rec.kind if rec else "none"))


# -- strings / buckets --------------------------------------------------------

def _bucket(server, name: str):
    from redisson_tpu.client.objects.bucket import Bucket
    from redisson_tpu.client.codec import BytesCodec

    return Bucket(server.engine, name, BytesCodec())


@register("GET")
def cmd_get(server, ctx, args):
    return _bucket(server, _s(args[0])).get()


@register("SET")
def cmd_set(server, ctx, args):
    name = _s(args[0])
    value = bytes(args[1])
    px: Optional[float] = None
    nx = xx = False
    i = 2
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"PX":
            px = _int(args[i + 1]) / 1000.0
            i += 2
        elif opt == b"EX":
            px = float(_int(args[i + 1]))
            i += 2
        elif opt == b"NX":
            nx = True
            i += 1
        elif opt == b"XX":
            xx = True
            i += 1
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    b = _bucket(server, name)
    if nx:
        if not b.try_set(value, ttl=px):
            return None
    elif xx:
        with server.engine.locked(name):
            if not b.set_if_exists(value):
                return None
            if px is not None:
                server.engine.store.expire(name, time.time() + px)
    else:
        b.set(value, ttl=px)
    return "+OK"


@register("INCR")
def cmd_incr(server, ctx, args):
    from redisson_tpu.client.objects.bucket import AtomicLong

    return AtomicLong(server.engine, _s(args[0])).increment_and_get()


@register("INCRBY")
def cmd_incrby(server, ctx, args):
    from redisson_tpu.client.objects.bucket import AtomicLong

    return AtomicLong(server.engine, _s(args[0])).add_and_get(_int(args[1]))


@register("DECR")
def cmd_decr(server, ctx, args):
    from redisson_tpu.client.objects.bucket import AtomicLong

    return AtomicLong(server.engine, _s(args[0])).decrement_and_get()


# -- typed data commands (Redis-compatible wire surface) ----------------------
# The reference registry defines ~447 typed commands (RedisCommands.java);
# the batch-first blob forms above are the TPU-first primary citizens, and
# OBJCALL carries the full object surface — but generic Redis clients speak
# THESE verbs.  Values are raw bytes (BytesCodec), Redis semantics: a typed
# command and a default-codec OBJCALL handle on the same name see different
# encodings, exactly like mixing codecs in the reference.


@register("HSET")
def cmd_hset(server, ctx, args):
    name = _s(args[0])
    m = _typed_handle(server, "get_map", name)
    n = 0
    with server.engine.locked(name):  # multi-field writes land atomically
        for i in range(1, len(args) - 1, 2):
            if m.fast_put(bytes(args[i]), bytes(args[i + 1])):
                n += 1
    return n


@register("HMSET")
def cmd_hmset(server, ctx, args):
    """Deprecated Redis alias of HSET that replies +OK (the reference's
    RedisCommands.HMSET row)."""
    cmd_hset(server, ctx, args)
    return "+OK"


@register("HGET")
def cmd_hget(server, ctx, args):
    return _typed_handle(server, "get_map", _s(args[0])).get(bytes(args[1]))


@register("HMGET")
def cmd_hmget(server, ctx, args):
    m = _typed_handle(server, "get_map", _s(args[0]))
    return [m.get(bytes(f)) for f in args[1:]]


@register("HDEL")
def cmd_hdel(server, ctx, args):
    m = _typed_handle(server, "get_map", _s(args[0]))
    return int(m.fast_remove(*[bytes(f) for f in args[1:]]))


@register("HGETALL")
def cmd_hgetall(server, ctx, args):
    # dict reply: RESP3 map frame `%`, RESP2 flattens to field-value array
    m = _typed_handle(server, "get_map", _s(args[0]))
    return {bytes(k): v for k, v in m.read_all_entry_set()}


@register("HEXISTS")
def cmd_hexists(server, ctx, args):
    return 1 if _typed_handle(server, "get_map", _s(args[0])).contains_key(bytes(args[1])) else 0


@register("HLEN")
def cmd_hlen(server, ctx, args):
    return _typed_handle(server, "get_map", _s(args[0])).size()


@register("HKEYS")
def cmd_hkeys(server, ctx, args):
    return _typed_handle(server, "get_map", _s(args[0])).read_all_keys()


@register("HVALS")
def cmd_hvals(server, ctx, args):
    return _typed_handle(server, "get_map", _s(args[0])).read_all_values()


@register("SADD")
def cmd_sadd(server, ctx, args):
    s = _typed_handle(server, "get_set", _s(args[0]))
    return sum(1 for v in args[1:] if s.add(bytes(v)))


@register("SREM")
def cmd_srem(server, ctx, args):
    s = _typed_handle(server, "get_set", _s(args[0]))
    return sum(1 for v in args[1:] if s.remove(bytes(v)))


@register("SISMEMBER")
def cmd_sismember(server, ctx, args):
    return 1 if _typed_handle(server, "get_set", _s(args[0])).contains(bytes(args[1])) else 0


@register("SMEMBERS")
def cmd_smembers(server, ctx, args):
    # a python set encodes as the RESP3 `~` set frame (RESP2 projects to an
    # array) — the CommandDecoder.java marker for SMEMBERS-family replies
    return set(_typed_handle(server, "get_set", _s(args[0])).read_all())


@register("SCARD")
def cmd_scard(server, ctx, args):
    return _typed_handle(server, "get_set", _s(args[0])).size()



@register("LPUSH")
def cmd_lpush(server, ctx, args):
    d = _deque(server, _s(args[0]))
    for v in args[1:]:
        d.add_first(bytes(v))
    return d.size()


@register("RPUSH")
def cmd_rpush(server, ctx, args):
    d = _deque(server, _s(args[0]))
    for v in args[1:]:
        d.add_last(bytes(v))
    return d.size()


@register("LPOP")
def cmd_lpop(server, ctx, args):
    return _deque(server, _s(args[0])).poll_first()


@register("RPOP")
def cmd_rpop(server, ctx, args):
    return _deque(server, _s(args[0])).poll_last()


@register("LLEN")
def cmd_llen(server, ctx, args):
    return _deque(server, _s(args[0])).size()


@register("LRANGE")
def cmd_lrange(server, ctx, args):
    from redisson_tpu.client.objects.scoredsortedset import _norm_range

    d = _deque(server, _s(args[0]))
    items = d.read_all()
    lo, hi = _norm_range(_int(args[1]), _int(args[2]), len(items))
    return items[lo : hi + 1] if hi >= lo else []


@register("LINDEX")
def cmd_lindex(server, ctx, args):
    items = _deque(server, _s(args[0])).read_all()
    i = _int(args[1])
    if i < 0:
        i += len(items)
    return items[i] if 0 <= i < len(items) else None


@register("ZADD")
def cmd_zadd(server, ctx, args):
    name = _s(args[0])
    z = _typed_handle(server, "get_scored_sorted_set", name)
    n = 0
    with server.engine.locked(name):  # multi-member adds land atomically
        for i in range(1, len(args) - 1, 2):
            if z.add(float(args[i]), bytes(args[i + 1])):
                n += 1
    _signal_waiters(server, name)  # wake parked BZPOPMIN/BZPOPMAX
    return n


@register("ZSCORE")
def cmd_zscore(server, ctx, args):
    # float reply: RESP3 double frame `,`, RESP2 Redis-formatted bulk
    sc = _typed_handle(server, "get_scored_sorted_set", _s(args[0])).get_score(bytes(args[1]))
    return None if sc is None else float(sc)


@register("ZREM")
def cmd_zrem(server, ctx, args):
    z = _typed_handle(server, "get_scored_sorted_set", _s(args[0]))
    return sum(1 for m in args[1:] if z.remove(bytes(m)))


@register("ZCARD")
def cmd_zcard(server, ctx, args):
    return _typed_handle(server, "get_scored_sorted_set", _s(args[0])).size()


@register("ZRANK")
def cmd_zrank(server, ctx, args):
    return _typed_handle(server, "get_scored_sorted_set", _s(args[0])).rank(bytes(args[1]))


@register("ZINCRBY")
def cmd_zincrby(server, ctx, args):
    z = _typed_handle(server, "get_scored_sorted_set", _s(args[0]))
    return float(z.add_score(bytes(args[2]), float(args[1])))


@register("ZRANGE")
def cmd_zrange(server, ctx, args):
    z = _typed_handle(server, "get_scored_sorted_set", _s(args[0]))
    withscores = len(args) > 3 and bytes(args[3]).upper() == b"WITHSCORES"
    lo, hi = _int(args[1]), _int(args[2])
    if withscores:
        out = []
        for member, score in z.entry_range(lo, hi):
            out += [member, _fnum(score)]
        return out
    return z.value_range(lo, hi)


@register("MGET")
def cmd_mget(server, ctx, args):
    # atomic snapshot across keys (Redis executes MGET as one step): without
    # all locks, a reader interleaving a concurrent MSET could see a torn
    # half-old half-new multi-key view
    names = [_s(k) for k in args]
    with server.engine.locked_many(names):
        return [_bucket(server, n).get() for n in names]


@register("MSET")
def cmd_mset(server, ctx, args):
    # ALL record locks up front (engine.locked_many): Redis MSET is atomic —
    # a concurrent MGET must never observe a torn multi-key write
    names = [_s(args[i]) for i in range(0, len(args) - 1, 2)]
    with server.engine.locked_many(names):
        for i in range(0, len(args) - 1, 2):
            _bucket(server, _s(args[i])).set(bytes(args[i + 1]))
    return "+OK"


@register("GETSET")
def cmd_getset(server, ctx, args):
    return _bucket(server, _s(args[0])).get_and_set(bytes(args[1]))


@register("GETDEL")
def cmd_getdel(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        v = _bucket(server, name).get()
        server.engine.store.delete(name)
        return v


@register("APPEND")
def cmd_append(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        b = _bucket(server, name)
        cur = b.get() or b""
        new = bytes(cur) + bytes(args[1])
        b.set(new)
        return len(new)


@register("STRLEN")
def cmd_strlen(server, ctx, args):
    v = _bucket(server, _s(args[0])).get()
    return 0 if v is None else len(bytes(v))


# -- typed surface expansion (strings / keys / scan cursors) ------------------
# Same contract as the block above: BytesCodec values, Redis reply shapes,
# record locks for compound read-modify-write.  Reference definitions:
# client/protocol/RedisCommands.java (SETNX:188, SETRANGE/GETRANGE:199-201,
# INCRBYFLOAT:214, SCAN:531, EXPIREAT:340).





@register("SETNX")
def cmd_setnx(server, ctx, args):
    return 1 if _bucket(server, _s(args[0])).try_set(bytes(args[1])) else 0


@register("SETEX")
def cmd_setex(server, ctx, args):
    ttl = _int(args[1])
    if ttl <= 0:
        raise RespError("ERR invalid expire time in 'setex' command")
    _bucket(server, _s(args[0])).set(bytes(args[2]), ttl=float(ttl))
    return "+OK"


@register("PSETEX")
def cmd_psetex(server, ctx, args):
    ttl = _int(args[1])
    if ttl <= 0:
        raise RespError("ERR invalid expire time in 'psetex' command")
    _bucket(server, _s(args[0])).set(bytes(args[2]), ttl=ttl / 1000.0)
    return "+OK"


@register("GETEX")
def cmd_getex(server, ctx, args):
    name = _s(args[0])
    # parse the FULL option list before touching state: a trailing syntax
    # error must leave the TTL unchanged (Redis validates then applies)
    actions = []
    i = 1
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"EX":
            actions.append(lambda n=name, s=_int(args[i + 1]): server.engine.store.expire(n, time.time() + s))
            i += 2
        elif opt == b"PX":
            actions.append(lambda n=name, ms=_int(args[i + 1]): server.engine.store.expire(n, time.time() + ms / 1000.0))
            i += 2
        elif opt == b"EXAT":
            actions.append(lambda n=name, at=float(_int(args[i + 1])): server.engine.store.expire(n, at))
            i += 2
        elif opt == b"PXAT":
            actions.append(lambda n=name, at=_int(args[i + 1]) / 1000.0: server.engine.store.expire(n, at))
            i += 2
        elif opt == b"PERSIST":
            actions.append(lambda n=name: server.engine.store.expire(n, None))
            i += 1
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    with server.engine.locked(name):
        v = _bucket(server, name).get()
        if v is None:
            return None
        for act in actions:
            act()
        return v


@register("GETRANGE")
def cmd_getrange(server, ctx, args):
    v = _bucket(server, _s(args[0])).get()
    if v is None:
        return b""
    data = bytes(v)
    from redisson_tpu.client.objects.scoredsortedset import _norm_range

    lo, hi = _norm_range(_int(args[1]), _int(args[2]), len(data))
    return data[lo : hi + 1] if hi >= lo else b""


@register("SETRANGE")
def cmd_setrange(server, ctx, args):
    name = _s(args[0])
    off = _int(args[1])
    if off < 0:
        raise RespError("ERR offset is out of range")
    patch = bytes(args[2])
    with server.engine.locked(name):
        b = _bucket(server, name)
        cur = bytearray(bytes(b.get() or b""))
        if len(cur) < off + len(patch):
            cur.extend(b"\x00" * (off + len(patch) - len(cur)))
        cur[off : off + len(patch)] = patch
        b.set(bytes(cur))
        return len(cur)


@register("INCRBYFLOAT")
def cmd_incrbyfloat(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        b = _bucket(server, name)
        cur = b.get()
        try:
            new = (float(cur) if cur is not None else 0.0) + float(args[1])
        except ValueError:
            raise RespError("ERR value is not a valid float")
        b.set(_fnum(new))
        return _fnum(new)


@register("DECRBY")
def cmd_decrby(server, ctx, args):
    from redisson_tpu.client.objects.bucket import AtomicLong

    return AtomicLong(server.engine, _s(args[0])).add_and_get(-_int(args[1]))


@register("MSETNX")
def cmd_msetnx(server, ctx, args):
    # all-or-nothing: every key must be absent (Redis MSETNX contract)
    names = [_s(args[i]) for i in range(0, len(args) - 1, 2)]
    with server.engine.locked_many(names):
        if any(server.engine.store.exists(n) for n in names):
            return 0
        for i in range(0, len(args) - 1, 2):
            _bucket(server, _s(args[i])).set(bytes(args[i + 1]))
        return 1


@register("EXPIREAT")
def cmd_expireat(server, ctx, args):
    return _expire_locked(server, _s(args[0]), float(_int(args[1])))


@register("PEXPIREAT")
def cmd_pexpireat(server, ctx, args):
    return _expire_locked(server, _s(args[0]), _int(args[1]) / 1000.0)


def _expiretime(server, name: str, ms: bool):
    if not server.engine.store.exists(name):
        return -2
    ttl = server.engine.store.ttl(name)
    if ttl is None:
        return -1
    at = time.time() + ttl
    return int(at * 1000) if ms else int(at)


@register("EXPIRETIME")
def cmd_expiretime(server, ctx, args):
    return _expiretime(server, _s(args[0]), ms=False)


@register("PEXPIRETIME")
def cmd_pexpiretime(server, ctx, args):
    return _expiretime(server, _s(args[0]), ms=True)


@register("RANDOMKEY")
def cmd_randomkey(server, ctx, args):
    import random

    ks = list(server.engine.store.keys())
    return random.choice(ks).encode() if ks else None


@register("TOUCH")
def cmd_touch(server, ctx, args):
    return sum(1 for k in args if server.engine.store.exists(_s(k)))


@register("SCAN")
def cmd_scan(server, ctx, args):
    pattern, count, _ = _scan_opts(args, 1)
    ks = sorted(server.engine.store.keys(pattern))
    return _scan_page([k.encode() for k in ks], _int(args[0]), count)


