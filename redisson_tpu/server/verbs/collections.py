"""Hashes, sets, lists/deques, multi-pop + blocking family (RedissonMap/Set/List/Deque wire surface).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""


from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.common import (
    _block_loop,
    _deque,
    _fnum,
    _glob_match,
    _scan_opts,
    _scan_page,
    _typed_handle,
    _znumkeys,
    _zset,
)

# -- typed surface expansion (hashes) ----------------------------------------

@register("HSETNX")
def cmd_hsetnx(server, ctx, args):
    m = _typed_handle(server, "get_map", _s(args[0]))
    return 1 if m.fast_put_if_absent(bytes(args[1]), bytes(args[2])) else 0


def _hash_incr(server, args, parse, fmt):
    name = _s(args[0])
    field = bytes(args[1])
    m = _typed_handle(server, "get_map", name)
    with server.engine.locked(name):
        cur = m.get(field)
        try:
            new = (parse(cur) if cur is not None else parse(b"0")) + parse(args[2])
        except ValueError:
            raise RespError("ERR hash value is not a number")
        m.fast_put(field, fmt(new))
        return new


@register("HINCRBY")
def cmd_hincrby(server, ctx, args):
    return _hash_incr(server, args, _int, lambda v: str(v).encode())


@register("HINCRBYFLOAT")
def cmd_hincrbyfloat(server, ctx, args):
    return _fnum(_hash_incr(server, args, float, _fnum))


@register("HSTRLEN")
def cmd_hstrlen(server, ctx, args):
    v = _typed_handle(server, "get_map", _s(args[0])).get(bytes(args[1]))
    return 0 if v is None else len(bytes(v))


@register("HRANDFIELD")
def cmd_hrandfield(server, ctx, args):
    import random

    m = _typed_handle(server, "get_map", _s(args[0]))
    entries = m.read_all_entry_set()
    if len(args) == 1:
        return random.choice(entries)[0] if entries else None
    n = _int(args[1])
    withvalues = len(args) > 2 and bytes(args[2]).upper() == b"WITHVALUES"
    if n >= 0:  # distinct fields, at most n
        picked = random.sample(entries, min(n, len(entries)))
    else:  # repeats allowed, exactly |n|
        picked = [random.choice(entries) for _ in range(-n)] if entries else []
    out = []
    for k, v in picked:
        out += [k, v] if withvalues else [k]
    return out


@register("HSCAN")
def cmd_hscan(server, ctx, args):
    pattern, count, novalues = _scan_opts(args, 2)
    m = _typed_handle(server, "get_map", _s(args[0]))
    entries = sorted(m.read_all_entry_set())
    if pattern is not None:
        entries = [e for e in entries if _glob_match(pattern, e[0].decode(errors="replace"))]
    cur, page = _scan_page(entries, _int(args[1]), count)
    flat = []
    for k, v in page:
        flat += [k] if novalues else [k, v]
    return [cur, flat]


# -- typed surface expansion (sets) ------------------------------------------

def _set(server, name: str):
    return _typed_handle(server, "get_set", name)


@register("SPOP")
def cmd_spop(server, ctx, args):
    s = _set(server, _s(args[0]))
    if len(args) == 1:
        v = s.remove_random()
        return None if v is None else bytes(v)
    return [bytes(v) for v in (s.remove_random() for _ in range(_int(args[1]))) if v is not None]


@register("SRANDMEMBER")
def cmd_srandmember(server, ctx, args):
    import random

    s = _set(server, _s(args[0]))
    if len(args) == 1:
        v = s.random_member()
        return None if v is None else bytes(v)
    n = _int(args[1])
    members = s.read_all()
    if n >= 0:
        return random.sample(members, min(n, len(members)))
    return [random.choice(members) for _ in range(-n)] if members else []


@register("SMISMEMBER")
def cmd_smismember(server, ctx, args):
    s = _set(server, _s(args[0]))
    return [1 if s.contains(bytes(m)) else 0 for m in args[1:]]


@register("SMOVE")
def cmd_smove(server, ctx, args):
    return 1 if _set(server, _s(args[0])).move(_s(args[1]), bytes(args[2])) else 0


@register("SINTER")
def cmd_sinter(server, ctx, args):
    # set combination replies are RESP3 `~` set frames, like SMEMBERS
    return set(_set(server, _s(args[0])).read_intersection(*[_s(n) for n in args[1:]]))


@register("SUNION")
def cmd_sunion(server, ctx, args):
    return set(_set(server, _s(args[0])).read_union(*[_s(n) for n in args[1:]]))


@register("SDIFF")
def cmd_sdiff(server, ctx, args):
    return set(_set(server, _s(args[0])).read_diff(*[_s(n) for n in args[1:]]))


def _set_store(server, args, op: str):
    # Redis *STORE semantics: result = op over the SOURCES only, dest is
    # overwritten (its old content never participates).  The handle-level
    # union/intersection/diff include self, so compute via the first
    # source's read_* form and write the result — all under one lock scope
    # (record RLocks are re-entrant per thread, so the nested handle locks
    # are safe)
    dest = _s(args[0])
    srcs = [_s(n) for n in args[1:]]
    with server.engine.locked_many([dest, *srcs]):
        result = getattr(_set(server, srcs[0]), op)(*srcs[1:])
        server.engine.store.delete(dest)
        d = _set(server, dest)
        if result:
            d.add_all(bytes(v) for v in result)
        return len(result)


@register("SINTERSTORE")
def cmd_sinterstore(server, ctx, args):
    return _set_store(server, args, "read_intersection")


@register("SUNIONSTORE")
def cmd_sunionstore(server, ctx, args):
    return _set_store(server, args, "read_union")


@register("SDIFFSTORE")
def cmd_sdiffstore(server, ctx, args):
    return _set_store(server, args, "read_diff")


@register("SINTERCARD")
def cmd_sintercard(server, ctx, args):
    n = _int(args[0])
    names = [_s(k) for k in args[1 : 1 + n]]
    limit = None
    if len(args) > 1 + n:
        if bytes(args[1 + n]).upper() != b"LIMIT":
            raise RespError("ERR syntax error")
        limit = _int(args[2 + n])
        if limit < 0:
            raise RespError("ERR LIMIT can't be negative")
    inter = _set(server, names[0]).read_intersection(*names[1:])
    card = len(inter)
    return min(card, limit) if limit not in (None, 0) else card


@register("SSCAN")
def cmd_sscan(server, ctx, args):
    pattern, count, _ = _scan_opts(args, 2)
    members = sorted(bytes(v) for v in _set(server, _s(args[0])).read_all())
    if pattern is not None:
        members = [m for m in members if _glob_match(pattern, m.decode(errors="replace"))]
    return _scan_page(members, _int(args[1]), count)


# -- typed surface expansion (lists) -----------------------------------------
# Compound list edits operate on the queue record's host list directly under
# the record lock (the handle exposes the safe subset; Redis list verbs like
# LINSERT/LREM need positional surgery).

def _list_edit(server, name: str):
    d = _deque(server, name)
    rec = d._rec_or_create()
    return d, rec


@register("LPUSHX")
def cmd_lpushx(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            return 0
        d = _deque(server, name)
        for v in args[1:]:
            d.add_first(bytes(v))
        return d.size()


@register("RPUSHX")
def cmd_rpushx(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            return 0
        d = _deque(server, name)
        for v in args[1:]:
            d.add_last(bytes(v))
        return d.size()


@register("LSET")
def cmd_lset(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            raise RespError("ERR no such key")
        d, rec = _list_edit(server, name)
        i = _int(args[1])
        if i < 0:
            i += len(rec.host)
        if not 0 <= i < len(rec.host):
            raise RespError("ERR index out of range")
        rec.host[i] = bytes(args[2])
        d._touch_version(rec)
        return "+OK"


@register("LINSERT")
def cmd_linsert(server, ctx, args):
    name = _s(args[0])
    where = bytes(args[1]).upper()
    if where not in (b"BEFORE", b"AFTER"):
        raise RespError("ERR syntax error")
    pivot, elem = bytes(args[2]), bytes(args[3])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            return 0
        d, rec = _list_edit(server, name)
        try:
            i = rec.host.index(pivot)
        except ValueError:
            return -1
        rec.host.insert(i if where == b"BEFORE" else i + 1, elem)
        d._touch_version(rec)
        return len(rec.host)


@register("LREM")
def cmd_lrem(server, ctx, args):
    name = _s(args[0])
    n, target = _int(args[1]), bytes(args[2])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            return 0
        d, rec = _list_edit(server, name)
        items = rec.host
        removed = 0
        if n == 0:
            before = len(items)
            rec.host = [v for v in items if v != target]
            removed = before - len(rec.host)
        elif n > 0:
            out = []
            for v in items:
                if v == target and removed < n:
                    removed += 1
                else:
                    out.append(v)
            rec.host = out
        else:
            out = []
            for v in reversed(items):
                if v == target and removed < -n:
                    removed += 1
                else:
                    out.append(v)
            rec.host = out[::-1]
        if removed:
            d._touch_version(rec)
        return removed


@register("LTRIM")
def cmd_ltrim(server, ctx, args):
    from redisson_tpu.client.objects.scoredsortedset import _norm_range

    name = _s(args[0])
    with server.engine.locked(name):
        if not server.engine.store.exists(name):
            return "+OK"
        d, rec = _list_edit(server, name)
        lo, hi = _norm_range(_int(args[1]), _int(args[2]), len(rec.host))
        rec.host = rec.host[lo : hi + 1] if hi >= lo else []
        d._touch_version(rec)
        return "+OK"


@register("LPOS")
def cmd_lpos(server, ctx, args):
    name = _s(args[0])
    target = bytes(args[1])
    rank, num = 1, None
    i = 2
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"RANK":
            rank = _int(args[i + 1])
            if rank == 0:
                raise RespError("ERR RANK can't be zero")
            i += 2
        elif opt == b"COUNT":
            num = _int(args[i + 1])
            i += 2
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    if not server.engine.store.exists(name):
        return None if num is None else []
    items = [bytes(v) for v in _deque(server, name).read_all()]
    order = range(len(items)) if rank > 0 else range(len(items) - 1, -1, -1)
    skip = abs(rank) - 1
    hits = []
    for idx in order:
        if items[idx] != target:
            continue
        if skip:
            skip -= 1
            continue
        hits.append(idx)
        if num is None:  # single-answer form: first match wins
            break
        if num != 0 and len(hits) >= num:  # COUNT 0 = all matches
            break
    if num is None:
        return hits[0] if hits else None
    return hits


def _list_move(server, src: str, dst: str, from_left: bool, to_left: bool):
    with server.engine.locked_many((src, dst)):
        s = _deque(server, src)
        v = s.poll_first() if from_left else s.poll_last()
        if v is None:
            return None
        d = _deque(server, dst)
        (d.add_first if to_left else d.add_last)(bytes(v))
        return bytes(v)


@register("LMOVE")
def cmd_lmove(server, ctx, args):
    wherefrom = bytes(args[2]).upper()
    whereto = bytes(args[3]).upper()
    if wherefrom not in (b"LEFT", b"RIGHT") or whereto not in (b"LEFT", b"RIGHT"):
        raise RespError("ERR syntax error")
    return _list_move(
        server, _s(args[0]), _s(args[1]), wherefrom == b"LEFT", whereto == b"LEFT"
    )


@register("RPOPLPUSH")
def cmd_rpoplpush(server, ctx, args):
    return _list_move(server, _s(args[0]), _s(args[1]), False, True)


# -- multi-pops + blocking family --------------------------------------------



def _bpop(server, args, first: bool):
    names = [_s(k) for k in args[:-1]]
    timeout = float(args[-1])

    def poll_once():
        for nm in names:
            v = _deque(server, nm).poll_first() if first else _deque(server, nm).poll_last()
            if v is not None:
                return [nm.encode(), bytes(v)]
        return None

    return _block_loop(server, names[0], poll_once, timeout)


@register("BLPOP")
def cmd_blpop(server, ctx, args):
    return _bpop(server, args, first=True)


@register("BRPOP")
def cmd_brpop(server, ctx, args):
    return _bpop(server, args, first=False)


@register("BLMOVE")
def cmd_blmove(server, ctx, args):
    src, dst = _s(args[0]), _s(args[1])
    wherefrom = bytes(args[2]).upper()
    whereto = bytes(args[3]).upper()
    if wherefrom not in (b"LEFT", b"RIGHT") or whereto not in (b"LEFT", b"RIGHT"):
        raise RespError("ERR syntax error")
    timeout = float(args[4])

    def poll_once():
        return _list_move(server, src, dst, wherefrom == b"LEFT", whereto == b"LEFT")

    return _block_loop(server, src, poll_once, timeout)


@register("BRPOPLPUSH")
def cmd_brpoplpush(server, ctx, args):
    src, dst = _s(args[0]), _s(args[1])
    timeout = float(args[2])

    def poll_once():
        return _list_move(server, src, dst, False, True)

    return _block_loop(server, src, poll_once, timeout)


@register("LMPOP")
def cmd_lmpop(server, ctx, args):
    """LMPOP numkeys key... LEFT|RIGHT [COUNT n]."""
    _n, names, i = _znumkeys(server, args)
    where = bytes(args[i]).upper()
    if where not in (b"LEFT", b"RIGHT"):
        raise RespError("ERR syntax error")
    count = 1
    if len(args) > i + 1:
        if bytes(args[i + 1]).upper() != b"COUNT" or len(args) <= i + 2:
            raise RespError("ERR syntax error")
        count = _int(args[i + 2])
    for nm in names:
        with server.engine.locked(nm):  # the COUNT batch pops atomically
            d = _deque(server, nm)
            popped = []
            for _ in range(count):
                v = d.poll_first() if where == b"LEFT" else d.poll_last()
                if v is None:
                    break
                popped.append(bytes(v))
        if popped:
            return [nm.encode(), popped]
    return None


def _zpop_entry(server, name: str, first: bool):
    z = _zset(server, name)
    entries = z.entry_range(0, 0) if first else z.entry_range(-1, -1)
    if not entries:
        return None
    m, sc = entries[0]
    z.remove(m)
    return bytes(m), sc


@register("ZMPOP")
def cmd_zmpop(server, ctx, args):
    """ZMPOP numkeys key... MIN|MAX [COUNT n]."""
    _n, names, i = _znumkeys(server, args)
    which = bytes(args[i]).upper()
    if which not in (b"MIN", b"MAX"):
        raise RespError("ERR syntax error")
    count = 1
    if len(args) > i + 1:
        if bytes(args[i + 1]).upper() != b"COUNT" or len(args) <= i + 2:
            raise RespError("ERR syntax error")
        count = _int(args[i + 2])
    for nm in names:
        with server.engine.locked(nm):
            flat = []
            for _ in range(count):
                e = _zpop_entry(server, nm, which == b"MIN")
                if e is None:
                    break
                flat += [e[0], _fnum(e[1])]
        if flat:
            return [nm.encode(), flat]
    return None


def _bzpop(server, args, first: bool):
    names = [_s(k) for k in args[:-1]]
    timeout = float(args[-1])

    def poll_once():
        for nm in names:
            with server.engine.locked(nm):
                e = _zpop_entry(server, nm, first)
            if e is not None:
                return [nm.encode(), e[0], _fnum(e[1])]
        return None

    return _block_loop(server, names[0], poll_once, timeout)


@register("BZPOPMIN")
def cmd_bzpopmin(server, ctx, args):
    return _bzpop(server, args, first=True)


@register("BZPOPMAX")
def cmd_bzpopmax(server, ctx, args):
    return _bzpop(server, args, first=False)


