"""Bit/bloom/HLL device-plane verbs: RBitSet, RedisBloom-compatible BF.*, bloom/HLL bank blob fast paths, PF* (the sketch hot path).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

from typing import Any, List

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import (
    LazyReply,
    register,
    _s,
    _int,
)
from redisson_tpu.server.verbs.common import _bitset

# -- bits (RBitSet surface; batched forms are primary) ------------------------


@register("SETBIT")
def cmd_setbit(server, ctx, args):
    old = _bitset(server, _s(args[0])).set(_int(args[1]), bool(_int(args[2])))
    return 1 if old else 0


@register("GETBIT")
def cmd_getbit(server, ctx, args):
    return 1 if _bitset(server, _s(args[0])).get(_int(args[1])) else 0


@register("BITCOUNT")
def cmd_bitcount(server, ctx, args):
    return _bitset(server, _s(args[0])).cardinality()


@register("BITOP")
def cmd_bitop(server, ctx, args):
    from redisson_tpu.core import kernels as K

    op = bytes(args[0]).upper()
    dest = _s(args[1])
    srcs = [_s(a) for a in args[2:]]
    bs = _bitset(server, dest)
    if op == b"AND":
        bs.and_(*srcs)
    elif op == b"OR":
        bs.or_(*srcs)
    elif op == b"XOR":
        bs.xor(*srcs)
    elif op == b"NOT":
        bs.from_byte_array(_bitset(server, srcs[0]).to_byte_array())
        bs.not_()
    else:
        raise RespError("ERR syntax error")
    # reply = dest byte length; computed from the device WITHOUT a per-op
    # sync (the length rides the frame's grouped transfer)
    with server.engine.locked(dest):
        rec = server.engine.store.get(dest)
        if rec is None:
            return 0
        length_dev = K.bitset_length(rec.arrays["bits"])
    return LazyReply(
        device=(length_dev,),
        finish=lambda v: (n := int(v[0])) // 8 + (1 if n % 8 else 0),
    )


def _bf_type(tok: bytes):
    """u<w> (1..63) or i<w> (1..64) -> (signed, width)."""
    t = bytes(tok)
    if len(t) < 2 or t[:1] not in (b"u", b"i"):
        raise RespError("ERR Invalid bitfield type. Use something like i16 u8.")
    signed = t[:1] == b"i"
    try:
        width = int(t[1:])
    except ValueError:
        raise RespError("ERR Invalid bitfield type. Use something like i16 u8.")
    if not 1 <= width <= (64 if signed else 63):
        raise RespError("ERR Invalid bitfield type. Use something like i16 u8.")
    return signed, width


def _bf_offset(tok: bytes, width: int) -> int:
    t = bytes(tok)
    if t[:1] == b"#":
        return int(t[1:]) * width
    return int(t)


@register("BITFIELD")
def cmd_bitfield(server, ctx, args):
    """BITFIELD key [GET ty off] [SET ty off v] [INCRBY ty off n]
    [OVERFLOW WRAP|SAT|FAIL] — Redis bit-layout semantics (offset 0 is the
    MSB of byte 0, matching GETBIT/SETBIT numbering) over the BitSet record;
    fields read/write through the batched get_each/set_each forms so one
    subcommand costs one indexed kernel, not w scalar ops
    (client/protocol/RedisCommands.java BITFIELD def)."""
    import numpy as np

    bs = _bitset(server, _s(args[0]))
    overflow = "WRAP"
    out: List[Any] = []
    i = 1

    def read_field(signed, width, off):
        idx = np.arange(off, off + width, dtype=np.int64)
        nbits = bs.size()
        bits = np.zeros(width, np.uint64)
        in_range = idx < nbits  # bits past the plane read 0 (Redis strings)
        if in_range.any():
            bits[in_range] = np.asarray(bs.get_each(idx[in_range]), np.uint64)
        val = 0
        for b in bits:
            val = (val << 1) | int(b)
        if signed and width and (val >> (width - 1)) & 1:
            val -= 1 << width
        return val

    def write_field(width, off, val):
        mask = (1 << width) - 1
        uval = val & mask
        bits = np.array(
            [(uval >> (width - 1 - k)) & 1 for k in range(width)], dtype=bool
        )
        idx = np.arange(off, off + width, dtype=np.int64)
        if bits.any():
            bs.set_each(idx[bits], True)
        if (~bits).any():
            bs.set_each(idx[~bits], False)

    def apply_overflow(signed, width, val):
        """-> (in-range value, failed) per OVERFLOW mode."""
        lo = -(1 << (width - 1)) if signed else 0
        hi = (1 << (width - 1)) - 1 if signed else (1 << width) - 1
        if lo <= val <= hi:
            return val, False
        if overflow == "FAIL":
            return 0, True
        if overflow == "SAT":
            return (lo if val < lo else hi), False
        span = 1 << width  # WRAP: two's-complement modular arithmetic
        wrapped = val % span
        if signed and wrapped > hi:
            wrapped -= span
        return wrapped, False

    while i < len(args):
        op = bytes(args[i]).upper()
        if op == b"OVERFLOW":
            mode = bytes(args[i + 1]).upper().decode()
            if mode not in ("WRAP", "SAT", "FAIL"):
                raise RespError("ERR Invalid OVERFLOW type specified")
            overflow = mode
            i += 2
        elif op == b"GET":
            signed, width = _bf_type(args[i + 1])
            off = _bf_offset(args[i + 2], width)
            out.append(read_field(signed, width, off))
            i += 3
        elif op == b"SET":
            signed, width = _bf_type(args[i + 1])
            off = _bf_offset(args[i + 2], width)
            new = _int(args[i + 3])
            with server.engine.locked(_s(args[0])):
                old = read_field(signed, width, off)
                new, failed = apply_overflow(signed, width, new)
                if failed:
                    out.append(None)
                else:
                    write_field(width, off, new)
                    out.append(old)
            i += 4
        elif op == b"INCRBY":
            signed, width = _bf_type(args[i + 1])
            off = _bf_offset(args[i + 2], width)
            delta = _int(args[i + 3])
            with server.engine.locked(_s(args[0])):
                cur = read_field(signed, width, off)
                new, failed = apply_overflow(signed, width, cur + delta)
                if failed:
                    out.append(None)
                else:
                    write_field(width, off, new)
                    out.append(new)
            i += 4
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    return out


@register("BITFIELD_RO")
def cmd_bitfield_ro(server, ctx, args):
    """Read-only BITFIELD: GET subcommands only (replica-servable)."""
    for i in range(1, len(args), 3):
        if bytes(args[i]).upper() != b"GET":
            raise RespError(
                "ERR BITFIELD_RO only supports the GET subcommand"
            )
    return cmd_bitfield(server, ctx, args)


# batched forms: SETBITS name idx... / GETBITS name idx... (one kernel each)
@register("SETBITS")
def cmd_setbits(server, ctx, args):
    import numpy as np

    idx = np.asarray([_int(a) for a in args[1:]], np.int64)
    old, n = _bitset(server, _s(args[0])).set_each_async(idx, True)
    return LazyReply(device=(old,), finish=lambda v: [int(x) for x in v[0][:n]])


@register("GETBITS")
def cmd_getbits(server, ctx, args):
    import numpy as np

    idx = np.asarray([_int(a) for a in args[1:]], np.int64)
    got, n = _bitset(server, _s(args[0])).get_each_async(idx)
    return LazyReply(device=(got,), finish=lambda v: [int(x) for x in v[0][:n]])


# blob forms: indexes travel as ONE little-endian i32 buffer and previous
# bit values return as ONE byte blob — RESP integer encode/parse for
# thousands of per-bit args is pure overhead at batch sizes (bytes on the
# wire are the cost that matters through the tunnel)
@register("SETBITSB")
def cmd_setbitsb(server, ctx, args):
    import numpy as np

    idx = np.frombuffer(bytes(args[1]), dtype="<i4").astype(np.int64)
    old, n = _bitset(server, _s(args[0])).set_each_async(idx, True)
    return LazyReply(
        device=(old,), finish=lambda v: np.asarray(v[0][:n], np.uint8).tobytes()
    )


@register("GETBITSB")
def cmd_getbitsb(server, ctx, args):
    import numpy as np

    idx = np.frombuffer(bytes(args[1]), dtype="<i4").astype(np.int64)
    got, n = _bitset(server, _s(args[0])).get_each_async(idx)
    return LazyReply(
        device=(got,), finish=lambda v: np.asarray(v[0][:n], np.uint8).tobytes()
    )


# -- bloom filter (RedisBloom-compatible verbs + batch-first forms) ----------

def _bloom(server, name: str):
    from redisson_tpu.client.objects.bloom import BloomFilter

    return BloomFilter(server.engine, name)


@register("BF.RESERVE")
def cmd_bf_reserve(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    error_rate = float(args[1])
    capacity = _int(args[2])
    if not bf.try_init(capacity, error_rate):
        raise RespError("ERR item exists")  # RedisBloom wording
    return "+OK"


@register("BF.ADD")
def cmd_bf_add(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    return 1 if bf.add(bytes(args[1])) else 0


@register("BF.MADD")
def cmd_bf_madd(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    newly = bf.add_each([bytes(a) for a in args[1:]])
    return [int(v) for v in newly]


@register("BF.EXISTS")
def cmd_bf_exists(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    return 1 if bf.contains(bytes(args[1])) else 0


@register("BF.MEXISTS")
def cmd_bf_mexists(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    found = bf.contains_each([bytes(a) for a in args[1:]])
    return [int(v) for v in found]


@register("BF.INFO")
def cmd_bf_info(server, ctx, args):
    bf = _bloom(server, _s(args[0]))
    rec = server.engine.store.get(bf.name)
    if rec is None:
        raise RespError("ERR not found")
    return [
        b"Capacity", rec.meta.get("expected_insertions", 0),
        b"Size", rec.meta["m"],
        b"Number of hashes", rec.meta["k"],
        b"Number of items inserted", bf.count(),
    ]


# Binary batch forms — the remote RBatch hot path (BASELINE north star):
# one command carries the whole key batch as a little-endian int64 blob, the
# reply is a 0/1 byte per key.  This is the wire shape of "one fused kernel
# dispatch per flush".

@register("BF.MADD64")
def cmd_bf_madd64(server, ctx, args):
    import numpy as np

    keys = np.frombuffer(bytes(args[1]), dtype="<i8")
    newly, n = _bloom(server, _s(args[0])).add_each_async(keys)
    return LazyReply(
        device=(newly,),
        finish=lambda v: np.asarray(v[0], np.uint8)[:n].tobytes(),
    )


@register("BF.MEXISTS64")
def cmd_bf_mexists64(server, ctx, args):
    import numpy as np

    from redisson_tpu.core import kernels as K

    keys = np.frombuffer(bytes(args[1]), dtype="<i8")
    found, n = _bloom(server, _s(args[0])).contains_each_async(keys)

    def finish(vals):
        arr = vals[0]
        if arr.dtype == np.uint32:  # packed bitmap (u64 fast path)
            arr = K.unpack_found(arr, n)
        return np.asarray(arr[:n], np.uint8).tobytes()

    return LazyReply(device=(found,), finish=finish)


# -- frame-run coalescing (the adaptive coalescing plane, ISSUE 2) -----------
# A pipelined frame carrying a RUN of same-verb BF.MADD64 / BF.MEXISTS64
# commands against different filters (the config-5 fan-out: one command per
# tenant filter) used to cost one device dispatch per command.  The server
# frame loop (server/server.py) hands such runs here: same-geometry filters
# stack into one (F, S) bank, the whole run executes as ONE kernel, and each
# command's reply is a device slice riding the frame's single d2h gather.
# Under the overlap plane (core/ioplane) that gather runs on the writer
# task's completion queue, so a 64-filter wave's readback overlaps the NEXT
# wave's staging (engine staging pool) and upload — back-to-back waves
# pipeline instead of serializing on the d2h floor.

def coalesce_bloom_run(server, ctx, cmds: List[List[bytes]]):
    """Fused dispatch for a same-verb BF blob run.  Returns one LazyReply
    per command, or None when the run is ineligible (caller falls back to
    per-command dispatch, which reproduces exact per-command semantics and
    errors).  Prechecks mirror Registry.dispatch's pre-dispatch gates; any
    state that would make them diverge (open MULTI, unauthenticated
    connection, pending ASKING, routing redirect) disqualifies the run."""
    import numpy as np

    from redisson_tpu.core import coalesce as CO

    if ctx.multi_queue is not None or not ctx.authenticated or ctx.asking:
        return None
    verb = bytes(cmds[0][0]).upper()
    add = verb == b"BF.MADD64"
    names: List[str] = []
    keys_list = []
    for cmd in cmds:
        if len(cmd) != 3:
            return None
        if server.cluster_view or server.role == "replica":
            try:
                server.check_routing(verb.decode(), cmd[1:], asking=False)
            except RespError:
                return None  # redirect/readonly: per-command path replies
        try:
            names.append(_s(cmd[1]))
            keys = np.frombuffer(bytes(cmd[2]), dtype="<i8")
        except (ValueError, UnicodeDecodeError):
            # malformed blob/name: NOTHING was dispatched yet, so the run is
            # simply ineligible — per-command dispatch errors only the bad
            # command and serves the rest (uncoalesced semantics, exactly)
            return None
        if keys.size == 0:
            return None  # empty-blob replies follow the per-command path
        keys_list.append(keys)
    from redisson_tpu.utils.metrics import run_hooks_end, run_hooks_start

    hooks = getattr(server, "hooks", None) or ()
    name = verb.decode() + ".COALESCED"
    tokens = run_hooks_start(hooks, name, (len(cmds),))
    try:
        if add:
            flags, lengths = CO.fused_bloom_add_async(server.engine, names, keys_list)
        else:
            flags, lengths = CO.fused_bloom_contains_async(
                server.engine, names, keys_list
            )
    except CO.CoalesceIneligible:
        run_hooks_end(tokens, name, None)
        return None
    except BaseException as e:
        run_hooks_end(tokens, name, e)
        raise
    run_hooks_end(tokens, name, None)

    def reply(seg):
        return LazyReply(
            device=(seg,),
            finish=lambda v: np.asarray(v[0], np.uint8).tobytes(),
        )

    out = []
    off = 0
    for n in lengths:
        out.append(reply(flags[off : off + n]))
        off += n
    return out


@register("BFA.RESERVE")
def cmd_bfa_reserve(server, ctx, args):
    from redisson_tpu.client.objects.bloom_array import BloomFilterArray

    arr = BloomFilterArray(server.engine, _s(args[0]))
    arr.try_init(_int(args[1]), _int(args[2]), float(args[3]))
    return "+OK"


@register("BFA.MADD64")
def cmd_bfa_madd64(server, ctx, args):
    import numpy as np
    from redisson_tpu.client.objects.bloom_array import BloomFilterArray

    arr = BloomFilterArray(server.engine, _s(args[0]))
    tenants = np.frombuffer(bytes(args[1]), dtype="<i4")
    keys = np.frombuffer(bytes(args[2]), dtype="<i8")
    newly, n = arr.add_each_async(tenants, keys)
    if n == 0:
        return b""
    return LazyReply(
        device=(newly,),
        finish=lambda v: np.asarray(v[0], np.uint8)[:n].tobytes(),
    )


@register("BFA.MEXISTS64")
def cmd_bfa_mexists64(server, ctx, args):
    import numpy as np
    from redisson_tpu.client.objects.bloom_array import BloomFilterArray
    from redisson_tpu.core import kernels as K

    arr = BloomFilterArray(server.engine, _s(args[0]))
    tenants = np.frombuffer(bytes(args[1]), dtype="<i4")
    keys = np.frombuffer(bytes(args[2]), dtype="<i8")
    found, n = arr.contains_async(tenants, keys)
    if n == 0:
        return b""
    return LazyReply(
        device=(found,),
        finish=lambda v: np.asarray(K.unpack_found(v[0], n), np.uint8).tobytes(),
    )


@register("PFADD64")
def cmd_pfadd64(server, ctx, args):
    import numpy as np

    keys = np.frombuffer(bytes(args[1]), dtype="<i8")
    return 1 if _hll(server, _s(args[0])).add_all(keys) else 0


# -- hyperloglog BANK blob verbs (the multi-tenant sketch fast path: one
# -- blob frame per flush, mirroring the BFA.* bloom-bank discipline) --------

def _hll_array(server, name: str):
    from redisson_tpu.client.objects.hll_array import HyperLogLogArray

    return HyperLogLogArray(server.engine, name)


@register("HLLA.RESERVE")
def cmd_hlla_reserve(server, ctx, args):
    """HLLA.RESERVE name tenants — idempotent init replies 0 like BFA."""
    ok = _hll_array(server, _s(args[0])).try_init(tenants=_int(args[1]))
    return 1 if ok else 0


@register("HLLA.MADD64")
def cmd_hlla_madd64(server, ctx, args):
    """HLLA.MADD64 name <i32 tenant blob> <i64 key blob> — ONE fused
    scatter-max dispatch for the whole flush."""
    import numpy as np

    t = np.frombuffer(bytes(args[1]), dtype="<i4")
    k = np.frombuffer(bytes(args[2]), dtype="<i8")
    _hll_array(server, _s(args[0])).add(t, k)
    return "+OK"


@register("HLLA.MERGEROWS")
def cmd_hlla_mergerows(server, ctx, args):
    """HLLA.MERGEROWS name <i32 dst blob> <i32 src blob> — batched pairwise
    PFMERGE (the dense gather+max kernel)."""
    import numpy as np

    dst = np.frombuffer(bytes(args[1]), dtype="<i4")
    src = np.frombuffer(bytes(args[2]), dtype="<i4")
    try:
        _hll_array(server, _s(args[0])).merge_rows(dst, src)
    except ValueError as e:
        raise RespError(f"ERR {e}")
    return "+OK"


@register("HLLA.ESTIMATE")
def cmd_hlla_estimate(server, ctx, args):
    """HLLA.ESTIMATE name -> <f64 blob> of per-tenant estimates.  The
    estimate stays on device as a readback future (overlap plane): the reply
    rides the frame's grouped d2h and drains on the writer task."""
    import numpy as np

    est = _hll_array(server, _s(args[0])).estimate_all_async()
    return LazyReply(
        device=(est,),
        finish=lambda v: np.ascontiguousarray(v[0], dtype="<f8").tobytes(),
    )


@register("HLLA.ESTPAIRS")
def cmd_hlla_estpairs(server, ctx, args):
    """HLLA.ESTPAIRS name <i32 a blob> <i32 b blob> -> <f64 blob> of
    per-pair union estimates (PFCOUNT a b without mutation); device-form
    lazy reply like HLLA.ESTIMATE."""
    import numpy as np

    a = np.frombuffer(bytes(args[1]), dtype="<i4")
    b = np.frombuffer(bytes(args[2]), dtype="<i4")
    est = _hll_array(server, _s(args[0])).estimate_union_pairs_async(a, b)
    return LazyReply(
        device=(est,),
        finish=lambda v: np.ascontiguousarray(v[0], dtype="<f8").tobytes(),
    )


# -- hyperloglog (PFADD/PFCOUNT/PFMERGE parity, RedissonHyperLogLog.java) ----

def _hll(server, name: str):
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.client.codec import BytesCodec

    return HyperLogLog(server.engine, name, BytesCodec())


@register("PFADD")
def cmd_pfadd(server, ctx, args):
    name = _s(args[0])
    h = _hll(server, name)
    if len(args) == 1:
        # Redis contract: 1 only if the key was created by this call
        with server.engine.locked(name):
            created = not server.engine.store.exists(name)
            h.create_if_absent()
        return 1 if created else 0
    return 1 if h.add_all([bytes(a) for a in args[1:]]) else 0


@register("PFCOUNT")
def cmd_pfcount(server, ctx, args):
    names = [_s(a) for a in args]
    if len(names) == 1:
        return int(_hll(server, names[0]).count())
    return int(_hll(server, names[0]).count_with(*names[1:]))


@register("PFMERGE")
def cmd_pfmerge(server, ctx, args):
    dest = _hll(server, _s(args[0]))
    dest.merge_with(*[_s(a) for a in args[1:]])
    return "+OK"


