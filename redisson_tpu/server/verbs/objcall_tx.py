"""Generic object invocation (OBJCALL*) and wire transactions (MULTI/EXEC/WATCH + TXEXEC).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

import pickle
from typing import Optional

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import LazyReply, register, _s
from redisson_tpu.server.registry import REGISTRY
from redisson_tpu.server.verbs.common import _exec_tls

# -- generic object invocation (the classBody-shipping analog) ---------------

def _objcall_resolve(server, factory: str, name: str, codec_blob: Optional[bytes] = None):
    """Resolve the (cached) handle instance for one object call.

    `codec_blob` (optional, pickled Codec) lets remote clients carry a
    non-default codec across the wire — the reference's getMap(name, codec)
    contract; without it every wire handle silently used the server's
    default codec.  The raw blob keys the cache so same-name handles with
    different codecs don't alias."""
    if not factory.startswith(("get_", "create_")):
        raise RespError("ERR bad factory")
    client = server.local_client()
    fn = getattr(client, factory, None)
    if fn is None:
        raise RespError(f"ERR unknown factory '{factory}'")

    def _make():
        kw = {}
        if codec_blob is not None:
            import inspect

            from redisson_tpu.net.safe_pickle import safe_loads

            # signature probe, not except-TypeError: a TypeError raised
            # INSIDE an accepting factory must not masquerade as "does not
            # accept a codec"
            try:
                params = inspect.signature(fn).parameters
            except (TypeError, ValueError):
                params = {}
            if "codec" not in params and not any(
                p.kind == p.VAR_KEYWORD for p in params.values()
            ):
                raise RespError(f"ERR factory '{factory}' does not accept a codec")
            kw["codec"] = safe_loads(codec_blob)
        return fn(name, **kw) if name else fn(**kw)

    # handle instances are cached per (factory, name): stateful handles
    # (LocalCachedMap subscribes an invalidation listener, adders register
    # counters) must not accrete one instance per OBJCALL.  create_* stays
    # uncached by contract (fresh object per call).
    if not factory.startswith("get_"):
        return _make()
    cache = server._objcall_handles
    key = (factory, name, codec_blob)
    with server._objcall_handles_lock:
        obj = cache.get(key)
        if obj is None:
            obj = _make()
            cache[key] = obj
            if len(cache) > 4096:  # bounded LRU
                _k, old = cache.popitem(last=False)
                detach = getattr(old, "destroy", None)  # detach-only by contract
                if detach is not None:
                    try:
                        detach()
                    except Exception:  # noqa: BLE001
                        pass
        else:
            cache.move_to_end(key)
    return obj


def _objcall_invoke(server, factory, name, method, call_args, call_kwargs, caller,
                    codec_blob: Optional[bytes] = None):
    """One object-method invocation; returns the raw result (exceptions
    other than protocol errors propagate to the caller for tagging)."""
    obj = _objcall_resolve(server, factory, name, codec_blob)
    m = getattr(obj, method, None)
    if m is None or method.startswith("_"):
        raise RespError(f"ERR unknown method '{method}'")
    with server.engine.impersonate(caller):
        return m(*call_args, **call_kwargs)


@register("OBJCALL")
def cmd_objcall(server, ctx, args):
    """OBJCALL <factory> <name> <method> <pickled (args, kwargs)> [<caller-id>]
    [<pickled codec>] -> pickled result.  factory = RedissonTpu getter name
    ("get_map", ...); caller-id = client uuid:threadId so synchronizer
    identity survives the wire (RedissonBaseLock.getLockName travels
    client->Lua the same way); the optional codec rides the frame so remote
    handles honor getMap(name, codec) semantics."""
    from redisson_tpu.net.safe_pickle import safe_loads

    factory, name, method = _s(args[0]), _s(args[1]), _s(args[2])
    call_args, call_kwargs = safe_loads(bytes(args[3])) if len(args) > 3 else ((), {})
    caller = _s(args[4]) if len(args) > 4 and args[4] is not None else None
    codec_blob = bytes(args[5]) if len(args) > 5 and args[5] is not None else None
    try:
        result = _objcall_invoke(
            server, factory, name, method, call_args, call_kwargs, caller, codec_blob
        )
    except RespError:
        raise
    except Exception as e:  # noqa: BLE001 — ship the exception to the caller
        return b"E" + pickle.dumps(e)
    return b"R" + pickle.dumps(result)


@register("OBJCALLM")
def cmd_objcallm(server, ctx, args):
    """OBJCALLM <pickled [(factory, name, method, args, kwargs), ...]> [caller]
    -> b"M" + pickled [("R", result) | ("E", exception), ...].

    The batched object wire (CommandBatchService.java:87-151 made a single
    command): MANY object ops cross the wire as ONE frame and ONE pickle,
    instead of one round trip + pickle per op — the lever that lifts
    OBJCALL-bound cluster throughput.  Per-op routing errors (MOVED/ASK
    during a reshard) come back as tagged entries so the client re-routes
    just those ops."""
    return _objcallm_run(server, ctx, args, atomic=False)


@register("OBJCALLMA")
def cmd_objcallm_atomic(server, ctx, args):
    """Atomic OBJCALLM (BatchOptions IN_MEMORY_ATOMIC / the MULTI-EXEC
    analog, command/CommandBatchService.java:211-540): every op's record
    lock is taken UP FRONT via engine.locked_many, so no other command
    interleaves with the group — Redis EXEC semantics: non-interleaved
    execution, no rollback of ops that already applied when a later op
    errors.  Cluster rule matches the reference: all object names must
    colocate on this node (use {hashtags})."""
    return _objcallm_run(server, ctx, args, atomic=True)


def _objcallm_run(server, ctx, args, atomic: bool):
    from redisson_tpu.net.safe_pickle import safe_loads

    ops = safe_loads(bytes(args[0]))
    caller = _s(args[1]) if len(args) > 1 else None
    if atomic:
        names = sorted({str(op[1]) for op in ops if op[1]})
        with server.engine.locked_many(names):
            result = _objcallm_apply(server, ops, caller)
    else:
        result = _objcallm_apply(server, ops, caller)
    # the OBJCALLM frame is keyless on the wire, so the registry's generic
    # tracking hook cannot see its keys — invalidate from the decoded ops
    # (write-methods only; tracking/table.note_objcall_ops)
    _track = getattr(server, "tracking", None)
    if _track is not None and _track.active:
        _track.note_objcall_ops(ops, ctx)
    return result


def _objcallm_apply(server, ops, caller):
    out = []
    for op in ops:
        # 5-tuple (factory, name, method, args, kwargs) or 6-tuple with a
        # trailing pickled-codec blob (same contract as OBJCALL's 6th arg)
        factory, name, method, call_args, call_kwargs = op[:5]
        codec_blob = op[5] if len(op) > 5 else None
        try:
            if server.cluster_view:
                # per-op routing check (the frame itself is keyless)
                server.check_routing(
                    "OBJCALL",
                    [str(factory).encode(), str(name).encode(), str(method).encode()],
                )
            out.append(
                (
                    "R",
                    _objcall_invoke(
                        server, factory, name, method,
                        tuple(call_args), dict(call_kwargs), caller, codec_blob,
                    ),
                )
            )
        except Exception as e:  # noqa: BLE001 — tagged per-op, frame continues
            out.append(("E", e))
    return b"M" + pickle.dumps(out)


# -- transactions over the wire ----------------------------------------------
# Two surfaces, one engine mechanism (record versions + locked_many):
#   * MULTI/EXEC/WATCH/DISCARD/UNWATCH — the Redis-compatible verbs for
#     generic clients (queue in CommandContext, optimistic WATCH versions);
#   * OBJCALLV/TXEXEC — the object-level transaction wire used by
#     RemoteTransaction (transaction/RedissonTransaction.java:49-79 role):
#     reads return the observed record version, commit is ONE atomic frame
#     with version preconditions checked under locked_many.

# EXEC runs its queue on one worker thread; blocking verbs inside a
# transaction must degrade to a single non-blocking probe (Redis semantics:
# BLPOP inside MULTI acts as if the timeout elapsed immediately)


@register("MULTI")
def cmd_multi(server, ctx, args):
    if ctx.multi_queue is not None:
        raise RespError("ERR MULTI calls can not be nested")
    ctx.multi_queue = []
    ctx.multi_error = False
    return "+OK"


@register("DISCARD")
def cmd_discard(server, ctx, args):
    if ctx.multi_queue is None:
        raise RespError("ERR DISCARD without MULTI")
    ctx.multi_queue = None
    ctx.multi_error = False
    ctx.watch_versions.clear()
    return "+OK"


@register("WATCH")
def cmd_watch(server, ctx, args):
    if ctx.multi_queue is not None:
        raise RespError("ERR WATCH inside MULTI is not allowed")
    if not args:
        raise RespError("ERR wrong number of arguments for 'watch' command")
    for a in args:
        name = _s(a)
        rec = server.engine.store.get(name)
        # first observation wins (re-WATCHing a key keeps the original
        # precondition, matching the read-versions discipline)
        ctx.watch_versions.setdefault(name, 0 if rec is None else rec.version)
    return "+OK"


@register("UNWATCH")
def cmd_unwatch(server, ctx, args):
    ctx.watch_versions.clear()
    return "+OK"


@register("RESET")
def cmd_reset(server, ctx, args):
    """Connection state reset (Redis 6.2 RESET): transaction, watches,
    subscriptions stay untouched server-side except tx state (subscription
    teardown rides connection close)."""
    ctx.multi_queue = None
    ctx.multi_error = False
    ctx.watch_versions.clear()
    ctx.asking = False
    return "+RESET"


@register("EXEC")
def cmd_exec(server, ctx, args):
    from redisson_tpu.net import commands as C

    if ctx.multi_queue is None:
        raise RespError("ERR EXEC without MULTI")
    queue, ctx.multi_queue = ctx.multi_queue, None
    poisoned, ctx.multi_error = ctx.multi_error, False
    watches, ctx.watch_versions = dict(ctx.watch_versions), {}
    if poisoned:
        raise RespError(
            "EXECABORT Transaction discarded because of previous errors."
        )
    # routing precheck over the WHOLE group before anything applies: a slot
    # migrated since queue time must bounce the entire EXEC, never half of it
    if server.cluster_view or server.role == "replica":
        for qargs in queue:
            server.check_routing(bytes(qargs[0]).decode().upper(), qargs[1:])
    names = set(watches)
    for qargs in queue:
        for key in C.command_keys(bytes(qargs[0]).decode().upper(), qargs[1:]):
            names.add(key.decode() if isinstance(key, (bytes, bytearray)) else str(key))
    # one EXEC at a time: handlers may take record locks beyond the
    # precomputed key set (derived names), and serializing EXECs removes
    # any cross-transaction lock-order inversion those could introduce
    with server._exec_mutex:
        with server.engine.locked_many(sorted(names)):
            for name, seen in watches.items():
                rec = server.engine.store.get(name)
                cur = 0 if rec is None else rec.version
                if cur != seen:
                    return None  # nil reply: transaction aborted (Redis WATCH)
            results = []
            _exec_tls.in_exec = True
            try:
                for qargs in queue:
                    try:
                        r = REGISTRY.dispatch(server, ctx, qargs)
                        if isinstance(r, LazyReply):
                            # the frame-level lazy materializer only walks
                            # TOP-level results; nested lazies force here
                            r = r.force()
                        if isinstance(r, str) and r.startswith("+"):
                            r = r[1:]  # "+OK" marker is a top-level encoding
                        results.append(r)
                    except RespError as e:
                        results.append(e)  # per-command errors as values
                    except Exception as e:  # noqa: BLE001 — WRONGTYPE et al.
                        results.append(
                            RespError(f"ERR internal: {type(e).__name__}: {e}")
                        )
            finally:
                _exec_tls.in_exec = False
            return results


@register("OBJCALLV")
def cmd_objcallv(server, ctx, args):
    """OBJCALL returning (observed record version, result) — the
    transactional read.  The version is captured UNDER the record lock
    before the method runs, so a concurrent writer cannot slip between
    observation and result (RemoteTransaction records it as the commit
    precondition, the WATCH analog for the object surface)."""
    from redisson_tpu.net.safe_pickle import safe_loads

    factory, name, method = _s(args[0]), _s(args[1]), _s(args[2])
    call_args, call_kwargs = safe_loads(bytes(args[3])) if len(args) > 3 else ((), {})
    caller = _s(args[4]) if len(args) > 4 and args[4] is not None else None
    codec_blob = bytes(args[5]) if len(args) > 5 and args[5] is not None else None
    with server.engine.locked(name):
        rec = server.engine.store.get(name)
        version = 0 if rec is None else rec.version
        try:
            result = _objcall_invoke(
                server, factory, name, method, call_args, call_kwargs, caller,
                codec_blob,
            )
        except RespError:
            raise
        except Exception as e:  # noqa: BLE001 — ship the exception to the caller
            return b"E" + pickle.dumps(e)
    return b"R" + pickle.dumps((version, result))


@register("TXEXEC")
def cmd_txexec(server, ctx, args):
    """TXEXEC <pickled {name: version}> <pickled ops> [caller] — the atomic
    transaction commit frame: version preconditions verified and ops applied
    under ONE locked_many, so the check-then-apply window cannot admit a
    concurrent writer.  Versions mismatching reply TXCONFLICT with NOTHING
    applied; op errors after a passing check are tagged per-op with no
    rollback (EXEC semantics, same as OBJCALLMA).  The version-checked
    OBJCALLMA this extends is the commit path of RemoteTransaction
    (transaction/RedissonTransaction.java:270-306 made one frame)."""
    from redisson_tpu.net.safe_pickle import safe_loads

    versions = safe_loads(bytes(args[0]))
    ops = safe_loads(bytes(args[1]))
    caller = _s(args[2]) if len(args) > 2 and args[2] is not None else None
    names = sorted(
        {str(n) for n in versions} | {str(op[1]) for op in ops if op[1]}
    )
    # whole-frame routing precheck BEFORE any lock/apply: a mid-migration
    # frame must bounce atomically (client refreshes topology and retries
    # the full commit — nothing has applied)
    if server.cluster_view:
        for n in names:
            server.check_routing(
                "OBJCALL", [b"tx", n.encode(), b"precheck"]
            )
    with server.engine.locked_many(names):
        for name, seen in versions.items():
            rec = server.engine.store.get(str(name))
            cur = 0 if rec is None else rec.version
            if cur != int(seen):
                raise RespError(
                    f"TXCONFLICT object '{name}' changed concurrently "
                    f"(version {seen} -> {cur})"
                )
        result = _objcallm_apply(server, ops, caller)
    # commit applied: invalidate tracked readers of every written object
    # (keyless frame — same rule as OBJCALLM above)
    _track = getattr(server, "tracking", None)
    if _track is not None and _track.active:
        _track.note_objcall_ops(ops, ctx)
    return result


