"""Connection handshake + pub/sub verbs (BaseConnectionHandler / PublishSubscribeService parity).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

import pickle

from redisson_tpu.net.resp import Push, RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.version import __version__ as VERSION
from redisson_tpu.server.verbs.common import _glob_match

# -- connection handshake (BaseConnectionHandler.java:59-122 parity) ---------

@register("PING")
def cmd_ping(server, ctx, args):
    if args:
        return args[0]
    return "+PONG"


@register("ECHO")
def cmd_echo(server, ctx, args):
    return args[0]


@register("READONLY")
def cmd_readonly(server, ctx, args):
    """READONLY — arm replica reads for this connection (Redis cluster
    parity).  A cluster replica serves keyed reads only to connections
    that declared READONLY; everyone else is -MOVED to the master
    (server.check_routing).  No-op on masters, like Redis."""
    if args:
        raise RespError("ERR wrong number of arguments for 'readonly' command")
    ctx.readonly = True
    return "+OK"


@register("READWRITE")
def cmd_readwrite(server, ctx, args):
    if args:
        raise RespError("ERR wrong number of arguments for 'readwrite' command")
    ctx.readonly = False
    return "+OK"


@register("AUTH")
def cmd_auth(server, ctx, args):
    """AUTH <password> | AUTH <username> <password> — the ACL form matches
    the reference handshake (BaseConnectionHandler.java:59-122 sends
    username+password when a username is configured).  "default" aliases
    the server-level password, like Redis ACL's default user."""
    if len(args) >= 2:
        username, password = _s(args[-2]), _s(args[-1])
    else:
        username, password = "default", _s(args[-1])
    if username == "default":
        # with ACL users configured but NO default password, the default
        # user is DISABLED — `AUTH anything` must not bypass the user gate
        if server.password is not None:
            ok = password == server.password
        else:
            ok = not server.users
    else:
        expected = server.users.get(username)
        ok = expected is not None and password == expected
    if ok:
        ctx.authenticated = True
        ctx.username = username
        return "+OK"
    raise RespError("WRONGPASS invalid username-password pair")


@register("HELLO")
def cmd_hello(server, ctx, args):
    """HELLO [protover [AUTH user pass]] — the real protocol switch
    (config/Config.java:57-99 protocol knob; CommandDecoder.java markers).
    This wire is RESP3-native by default; HELLO 2 downgrades the connection
    to the strict RESP2 projection (maps flatten, pushes become arrays)."""
    i = 0
    if args and bytes(args[0]).isdigit():
        ver = _int(args[0])
        if ver not in (2, 3):
            raise RespError("NOPROTO unsupported protocol version")
        ctx.proto = ver
        i = 1
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"AUTH" and i + 2 < len(args):
            cmd_auth(server, ctx, [args[i + 1], args[i + 2]])
            i += 3
        elif opt == b"SETNAME" and i + 1 < len(args):
            ctx.name = _s(args[i + 1])
            i += 2
        else:
            raise RespError(f"ERR unknown HELLO option '{_s(args[i])}'")
    return {
        b"server": b"redisson-tpu",
        b"version": VERSION.encode(),
        b"proto": ctx.proto,
        b"id": ctx.client_id,
        b"mode": server.mode.encode(),
        b"role": b"master" if server.role == "master" else b"replica",
    }


@register("SELECT")
def cmd_select(server, ctx, args):
    _int(args[0])  # single logical db: accept and ignore, like db 0 only
    return "+OK"


@register("CLIENT")
def cmd_client(server, ctx, args):
    sub = bytes(args[0]).upper() if args else b""
    if sub == b"SETNAME":
        ctx.name = _s(args[1])
        return "+OK"
    if sub == b"GETNAME":
        return ctx.name.encode() if ctx.name else b""
    if sub == b"ID":
        # STABLE identity for this connection's whole life (the redirect
        # target of CLIENT TRACKING REDIRECT; minting a fresh id per call
        # made redirect impossible to express)
        return ctx.client_id
    if sub == b"INFO":
        return _client_info_line(server, ctx)
    if sub == b"TRACKING":
        return _client_tracking(server, ctx, args[1:])
    if sub == b"QOS":
        return _client_qos(server, ctx, args[1:])
    if sub == b"TRACKINGINFO":
        st = server.tracking.state_of(ctx)
        from redisson_tpu.tracking.table import ConnTracking

        if st is None:
            st = ConnTracking()
        return {
            b"flags": st.flags(),
            b"redirect": st.redirect if st.redirect is not None else -1,
            b"prefixes": [p.encode() for p in st.prefixes],
            b"keys": st.nkeys,
        }
    return "+OK"


def _client_info_line(server, ctx) -> bytes:
    """CLIENT INFO: the Redis one-line key=value shape (the fields this
    wire actually has; resp= is the negotiated protocol, tracking flags
    from the table)."""
    from redisson_tpu.tracking.table import ConnTracking

    st = server.tracking.state_of(ctx)
    flags = b"|".join((st or ConnTracking()).flags())
    redirect = st.redirect if (st is not None and st.redirect) else -1
    return (
        f"id={ctx.client_id} name={ctx.name or ''} resp={ctx.proto} "
        f"user={ctx.username or 'default'} "
        f"tracking={flags.decode()} redirect={redirect} "
        f"sub={len(ctx.subscriptions)} psub={len(ctx.psubscriptions)}"
    ).encode()


def _client_tracking(server, ctx, args):
    """CLIENT TRACKING ON|OFF [REDIRECT <client-id>] [BCAST]
    [PREFIX <prefix>]... [NOLOOP] — the server-assisted caching switch
    (tracking/table.py; Redis 6 semantics for the options this wire
    supports)."""
    if not args:
        raise RespError("ERR wrong number of arguments for 'client|tracking'")
    mode = bytes(args[0]).upper()
    if mode not in (b"ON", b"OFF"):
        raise RespError("ERR syntax error in CLIENT TRACKING (ON|OFF expected)")
    redirect = None
    bcast = False
    noloop = False
    prefixes = []
    i = 1
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"REDIRECT" and i + 1 < len(args):
            redirect = _int(args[i + 1])
            i += 2
        elif opt == b"BCAST":
            bcast = True
            i += 1
        elif opt == b"PREFIX" and i + 1 < len(args):
            prefixes.append(_s(args[i + 1]))
            i += 2
        elif opt == b"NOLOOP":
            noloop = True
            i += 1
        else:
            raise RespError(f"ERR unknown CLIENT TRACKING option '{_s(args[i])}'")
    if prefixes and not bcast:
        raise RespError(
            "ERR PREFIX option requires BCAST mode to be enabled"
        )
    if mode == b"OFF":
        server.tracking.disable(ctx)
        return "+OK"
    if redirect == 0:
        redirect = None  # Redis: REDIRECT 0 = no redirection
    if redirect is None and ctx.proto < 3:
        # Redis's own refusal: without RESP3 push frames the invalidation
        # could only arrive as a PLAIN array interleaved into the reply
        # stream, desyncing every later reply on this connection
        raise RespError(
            "ERR Client tracking is only supported in RESP3 mode or when "
            "a redirection client is specified via the 'REDIRECT' option"
        )
    server.tracking.enable(
        ctx, bcast=bcast, prefixes=prefixes, redirect=redirect, noloop=noloop
    )
    return "+OK"


def _client_qos(server, ctx, args):
    """CLIENT QOS CLASS <interactive|bulk|auto> [TENANT <t>] |
    CLIENT QOS TENANT <t> | CLIENT QOS GET — the deadline-class/tenant
    declaration of the QoS plane (ISSUE 10, server/scheduler.py).  CLASS
    pins this connection's frames to a deadline class (auto restores the
    size heuristic); TENANT names the token bucket its ops are charged to
    (default: the frame's key {hashtag}).  GET reports the connection's
    declared state plus its tenant's live bucket level and shed count."""
    if not args:
        raise RespError("ERR wrong number of arguments for 'client|qos'")
    sub = bytes(args[0]).upper()
    if sub == b"CLASS":
        if len(args) < 2:
            raise RespError("ERR CLIENT QOS CLASS expects a class")
        cls = _s(args[1]).lower()
        if cls not in ("interactive", "bulk", "auto"):
            raise RespError(
                "ERR CLIENT QOS CLASS expects interactive|bulk|auto"
            )
        ctx.qos_class = None if cls == "auto" else cls
        rest = args[2:]
        if rest:
            if len(rest) != 2 or bytes(rest[0]).upper() != b"TENANT":
                raise RespError("ERR syntax error in CLIENT QOS CLASS")
            ctx.tenant = _s(rest[1]) or None
        return "+OK"
    if sub == b"TENANT":
        if len(args) != 2:
            raise RespError("ERR CLIENT QOS TENANT expects a tenant name")
        ctx.tenant = _s(args[1]) or None
        return "+OK"
    if sub == b"GET":
        sched = server.scheduler
        tenant = ctx.tenant or "default"
        level = 0.0
        sheds = 0
        for name, lvl, _adm, shed_ops, _sf, _w in sched.tenant_table():
            if name == tenant:
                level, sheds = lvl, shed_ops
                break
        return {
            b"class": (ctx.qos_class or "auto").encode(),
            b"tenant": tenant.encode(),
            b"armed": 1 if sched.armed else 0,
            b"bucket-level": int(level),
            b"shed-ops": sheds,
        }
    raise RespError(f"ERR unknown CLIENT QOS subcommand '{_s(args[0])}'")


@register("QUIT")
def cmd_quit(server, ctx, args):
    raise ConnectionResetError("client quit")


# -- pubsub ------------------------------------------------------------------

@register("SUBSCRIBE")
def cmd_subscribe(server, ctx, args):
    out = []
    for ch_raw in args:
        ch = _s(ch_raw)
        if ch not in ctx.subscriptions:
            push = ctx.push

            def listener(channel, msg, _push=push):
                _push(Push([b"message", channel.encode(), msg if isinstance(msg, bytes) else pickle.dumps(msg)]))

            ctx.subscriptions[ch] = server.engine.pubsub.subscribe(ch, listener)
        out.append(Push([b"subscribe", ch_raw, ctx.subscription_count()]))
    return out


@register("UNSUBSCRIBE")
def cmd_unsubscribe(server, ctx, args):
    chans = [_s(a) for a in args] or list(ctx.subscriptions)
    out = []
    for ch in chans:
        lid = ctx.subscriptions.pop(ch, None)
        if lid is not None:
            server.engine.pubsub.unsubscribe(ch, lid)
        out.append(Push([b"unsubscribe", ch.encode(), ctx.subscription_count()]))
    return out


@register("PSUBSCRIBE")
def cmd_psubscribe(server, ctx, args):
    out = []
    for pat_raw in args:
        pat = _s(pat_raw)
        if pat not in ctx.psubscriptions:
            push = ctx.push

            def listener(channel, msg, _push=push, _pat=pat):
                _push(Push([
                    b"pmessage", _pat.encode(), channel.encode(),
                    msg if isinstance(msg, bytes) else pickle.dumps(msg),
                ]))

            ctx.psubscriptions[pat] = server.engine.pubsub.psubscribe(pat, listener)
        out.append(Push([b"psubscribe", pat_raw, ctx.subscription_count()]))
    return out


@register("PUNSUBSCRIBE")
def cmd_punsubscribe(server, ctx, args):
    pats = [_s(a) for a in args] or list(ctx.psubscriptions)
    out = []
    for pat in pats:
        lid = ctx.psubscriptions.pop(pat, None)
        if lid is not None:
            server.engine.pubsub.punsubscribe(pat, lid)
        out.append(Push([b"punsubscribe", pat.encode(), ctx.subscription_count()]))
    return out


@register("PUBLISH")
def cmd_publish(server, ctx, args):
    return server.engine.pubsub.publish(_s(args[0]), bytes(args[1]))


@register("PUBSUB")
def cmd_pubsub(server, ctx, args):
    """PUBSUB CHANNELS [pattern] | NUMSUB [ch...] | NUMPAT |
    SHARDCHANNELS [pattern] | SHARDNUMSUB [ch...] — hub introspection
    (RedissonTopic.countSubscribers / getChannelNames role)."""
    hub = server.engine.pubsub
    sub = bytes(args[0]).upper() if args else b""
    if sub in (b"CHANNELS", b"SHARDCHANNELS"):
        prefix = _SHARD_NS if sub == b"SHARDCHANNELS" else ""
        pattern = _s(args[1]) if len(args) > 1 else "*"
        out = []
        for ch in hub.channels():
            if prefix:
                if not ch.startswith(prefix):
                    continue
                ch = ch[len(prefix):]
            elif ch.startswith(_SHARD_NS):
                continue  # shard channels live in their own namespace
            if _glob_match(pattern, ch):
                out.append(ch.encode())
        return sorted(out)
    if sub in (b"NUMSUB", b"SHARDNUMSUB"):
        prefix = _SHARD_NS if sub == b"SHARDNUMSUB" else ""
        out = []
        for raw in args[1:]:
            ch = _s(raw)
            out += [raw, hub.subscriber_count(prefix + ch)]
        return out
    if sub == b"NUMPAT":
        return len(hub._patterns)
    raise RespError(f"ERR Unknown PUBSUB subcommand '{_s(args[0]) if args else ''}'")


# sharded pubsub (Redis 7 SPUBLISH/SSUBSCRIBE): shard channels are a
# SEPARATE namespace (a PUBLISH must not reach an SSUBSCRIBE listener) —
# modeled as a reserved hub-channel prefix.  Slot routing happens client-
# side by channel name, same as the plain-SUBSCRIBE slot routing the
# cluster client already does (RedissonShardedTopic semantic parity).
_SHARD_NS = "__shard__:"


@register("SSUBSCRIBE")
def cmd_ssubscribe(server, ctx, args):
    out = []
    for ch_raw in args:
        ch = _s(ch_raw)
        hubch = _SHARD_NS + ch
        if hubch not in ctx.subscriptions:
            push = ctx.push

            def listener(channel, msg, _push=push, _ch=ch):
                _push(Push([
                    b"smessage", _ch.encode(),
                    msg if isinstance(msg, bytes) else pickle.dumps(msg),
                ]))

            ctx.subscriptions[hubch] = server.engine.pubsub.subscribe(hubch, listener)
        out.append(Push([b"ssubscribe", ch_raw, ctx.subscription_count()]))
    return out


@register("SUNSUBSCRIBE")
def cmd_sunsubscribe(server, ctx, args):
    chans = [_s(a) for a in args] or [
        c[len(_SHARD_NS):] for c in ctx.subscriptions if c.startswith(_SHARD_NS)
    ]
    out = []
    for ch in chans:
        lid = ctx.subscriptions.pop(_SHARD_NS + ch, None)
        if lid is not None:
            server.engine.pubsub.unsubscribe(_SHARD_NS + ch, lid)
        out.append(Push([b"sunsubscribe", ch.encode(), ctx.subscription_count()]))
    return out


@register("SPUBLISH")
def cmd_spublish(server, ctx, args):
    return server.engine.pubsub.publish(_SHARD_NS + _s(args[0]), bytes(args[1]))


