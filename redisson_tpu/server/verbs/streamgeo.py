"""Streams (XADD family, RedissonStream parity) and geo (RedissonGeo parity) verbs.

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

import time

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.common import _fnum, _typed_handle

# -- typed stream verbs (XADD family — RedissonStream.java wire parity) ------

def _stream(server, name: str):
    return _typed_handle(server, "get_stream", name)


def _stream_cmd(fn):
    """Map stream-handle exceptions to Redis reply shapes: BUSYGROUP /
    NOGROUP pass through verbatim (clients pattern-match those prefixes),
    anything else becomes a plain ERR instead of 'ERR internal: ...'."""
    import functools

    @functools.wraps(fn)
    def wrapper(server, ctx, args):
        try:
            return fn(server, ctx, args)
        except ValueError as e:
            msg = str(e)
            raise RespError(msg if msg.startswith("BUSYGROUP") else f"ERR {msg}")
        except KeyError as e:
            msg = str(e.args[0]) if e.args else str(e)
            raise RespError(msg if msg.startswith("NOGROUP") else f"ERR {msg}")
        except IndexError:
            raise RespError("ERR syntax error")

    return wrapper


def _xentries(d) -> list:
    """Dict[id, fields] -> Redis XRANGE reply shape [[id, [f, v, ...]], ...]."""
    out = []
    for i, fields in d.items():
        flat = []
        for k, v in fields.items():
            flat += [k, v]
        out.append([i.encode() if isinstance(i, str) else i, flat])
    return out


@register("XADD")
@_stream_cmd
def cmd_xadd(server, ctx, args):
    """XADD key [NOMKSTREAM] [MAXLEN|MINID [~|=] threshold] <id|*> f v ..."""
    name = _s(args[0])
    i = 1
    nomkstream = False
    trim_kind, trim_arg = None, None
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"NOMKSTREAM":
            nomkstream = True
            i += 1
        elif opt in (b"MAXLEN", b"MINID"):
            j = i + 1
            if bytes(args[j]) in (b"~", b"="):  # approximate == exact here
                j += 1
            trim_kind, trim_arg = opt, args[j]
            i = j + 1
        else:
            break
    if i >= len(args) or (len(args) - i - 1) % 2 != 0 or len(args) - i - 1 == 0:
        raise RespError("ERR wrong number of arguments for 'xadd' command")
    if nomkstream and not server.engine.store.exists(name):
        return None
    entry_id = _s(args[i])
    fields = {bytes(args[j]): bytes(args[j + 1]) for j in range(i + 1, len(args) - 1, 2)}
    st = _stream(server, name)
    try:
        rid = st.add(fields, id=None if entry_id == "*" else entry_id)
    except ValueError as e:
        raise RespError(f"ERR {e}")
    if trim_kind == b"MAXLEN":
        st.trim(_int(trim_arg))
    elif trim_kind == b"MINID":
        st.trim_by_min_id(_s(trim_arg))
    return rid.encode()


@register("XLEN")
@_stream_cmd
def cmd_xlen(server, ctx, args):
    return _stream(server, _s(args[0])).size()


def _xrange(server, args, reverse: bool):
    count = None
    if len(args) > 3:
        if bytes(args[3]).upper() != b"COUNT":
            raise RespError("ERR syntax error")
        count = _int(args[4])
    st = _stream(server, _s(args[0]))
    a, b = _s(args[1]), _s(args[2])
    d = st.rev_range(a, b, count) if reverse else st.range(a, b, count)
    return _xentries(d)


@register("XRANGE")
@_stream_cmd
def cmd_xrange(server, ctx, args):
    return _xrange(server, args, reverse=False)


@register("XREVRANGE")
@_stream_cmd
def cmd_xrevrange(server, ctx, args):
    return _xrange(server, args, reverse=True)


@register("XDEL")
@_stream_cmd
def cmd_xdel(server, ctx, args):
    return _stream(server, _s(args[0])).remove(*[_s(i) for i in args[1:]])


@register("XTRIM")
@_stream_cmd
def cmd_xtrim(server, ctx, args):
    kind = bytes(args[1]).upper()
    j = 2
    if bytes(args[j]) in (b"~", b"="):
        j += 1
    st = _stream(server, _s(args[0]))
    if kind == b"MAXLEN":
        return st.trim(_int(args[j]))
    if kind == b"MINID":
        return st.trim_by_min_id(_s(args[j]))
    raise RespError("ERR syntax error")


def _xread_streams(args, i):
    rest = args[i:]
    if not rest or len(rest) % 2:
        raise RespError("ERR Unbalanced XREAD list of streams: for each stream key an ID or '$' must be specified.")
    nk = len(rest) // 2
    return [_s(k) for k in rest[:nk]], [_s(v) for v in rest[nk:]]


@register("XREAD")
@_stream_cmd
def cmd_xread(server, ctx, args):
    """XREAD [COUNT n] [BLOCK ms] STREAMS key... id...  ('$' = from now)."""
    import time as _t

    count, block = None, None
    i = 0
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"COUNT":
            count = _int(args[i + 1])
            i += 2
        elif opt == b"BLOCK":
            block = _int(args[i + 1]) / 1000.0
            i += 2
        elif opt == b"STREAMS":
            i += 1
            break
        else:
            raise RespError("ERR syntax error")
    else:
        raise RespError("ERR syntax error")
    names, ids = _xread_streams(args, i)
    resolved = []
    for nm, fid in zip(names, ids):
        if fid == "$":
            fid = _stream(server, nm).last_id() or "0"
        resolved.append(fid)
    deadline = None if block is None else _t.time() + block
    while True:
        out = []
        for nm, fid in zip(names, resolved):
            d = _stream(server, nm).read(from_id=fid, count=count, timeout=0.0)
            if d:
                out.append([nm.encode(), _xentries(d)])
        if out:
            return out
        if deadline is None or _t.time() >= deadline:
            return None
        server.engine.wait_entry(f"__stream__:{names[0]}").wait_for(
            min(0.05, max(0.0, deadline - _t.time()))
        )


@register("XGROUP")
@_stream_cmd
def cmd_xgroup(server, ctx, args):
    sub = bytes(args[0]).upper()
    st = _stream(server, _s(args[1]))
    if sub == b"CREATE":
        # MKSTREAM tolerated: records are created on first touch anyway
        st.create_group(_s(args[2]), from_id=_s(args[3]) if len(args) > 3 else "$")
        return "+OK"
    if sub == b"DESTROY":
        st.remove_group(_s(args[2]))
        return 1
    if sub == b"CREATECONSUMER":
        return 1 if st.create_consumer(_s(args[2]), _s(args[3])) else 0
    if sub == b"DELCONSUMER":
        return st.remove_consumer(_s(args[2]), _s(args[3]))
    if sub == b"SETID":
        st.set_group_id(_s(args[2]), _s(args[3]))
        return "+OK"
    raise RespError(f"ERR Unknown XGROUP subcommand or wrong number of arguments for '{_s(args[0])}'")


@register("XREADGROUP")
@_stream_cmd
def cmd_xreadgroup(server, ctx, args):
    """XREADGROUP GROUP g consumer [COUNT n] [BLOCK ms] [NOACK] STREAMS k id."""
    if bytes(args[0]).upper() != b"GROUP":
        raise RespError("ERR syntax error")
    group, consumer = _s(args[1]), _s(args[2])
    count, block, noack = None, None, False
    i = 3
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"COUNT":
            count = _int(args[i + 1])
            i += 2
        elif opt == b"BLOCK":
            block = _int(args[i + 1]) / 1000.0
            i += 2
        elif opt == b"NOACK":
            noack = True
            i += 1
        elif opt == b"STREAMS":
            i += 1
            break
        else:
            raise RespError("ERR syntax error")
    else:
        raise RespError("ERR syntax error")
    names, ids = _xread_streams(args, i)
    import time as _t

    deadline = None if block is None else _t.time() + block
    while True:
        out = []
        for nm, fid in zip(names, ids):
            st = _stream(server, nm)
            # non-blocking sweep across ALL streams: blocking inside one
            # stream would starve data already waiting in the next
            d = st.read_group(group, consumer, count=count, timeout=0.0, from_id=fid)
            if d:
                if noack:
                    st.ack(group, *d.keys())
                out.append([nm.encode(), _xentries(d)])
        if out:
            return out
        if deadline is None or _t.time() >= deadline:
            return None
        server.engine.wait_entry(f"__stream__:{names[0]}").wait_for(
            min(0.05, max(0.0, deadline - _t.time()))
        )


@register("XACK")
@_stream_cmd
def cmd_xack(server, ctx, args):
    return _stream(server, _s(args[0])).ack(_s(args[1]), *[_s(i) for i in args[2:]])


@register("XPENDING")
@_stream_cmd
def cmd_xpending(server, ctx, args):
    st = _stream(server, _s(args[0]))
    group = _s(args[1])
    if len(args) == 2:  # summary form
        s = st.pending_summary(group)
        consumers = [
            [c.encode(), str(n).encode()] for c, n in sorted(s["consumers"].items())
        ]
        return [
            s["total"],
            s["min_id"].encode() if s["min_id"] else None,
            s["max_id"].encode() if s["max_id"] else None,
            consumers or None,
        ]
    # extended: [IDLE ms] start end count [consumer]
    i = 2
    min_idle = 0.0
    if bytes(args[i]).upper() == b"IDLE":
        min_idle = _int(args[i + 1]) / 1000.0
        i += 2
    lo, hi, count = _s(args[i]), _s(args[i + 1]), _int(args[i + 2])
    consumer = _s(args[i + 3]) if len(args) > i + 3 else None
    # idle filters BEFORE count (scanning order): counting first could
    # return empty while matching idle entries exist past the cut
    rows = st.pending_range(group, lo, hi, count=None, consumer=consumer)
    rows = [r for r in rows if r["idle"] >= min_idle][:count]
    return [
        [r["id"].encode(), r["consumer"].encode(),
         int(r["idle"] * 1000), r["delivered"]]
        for r in rows
    ]


@register("XCLAIM")
@_stream_cmd
def cmd_xclaim(server, ctx, args):
    st = _stream(server, _s(args[0]))
    group, consumer = _s(args[1]), _s(args[2])
    min_idle = _int(args[3]) / 1000.0
    ids = []
    justid = force = False
    i = 4
    while i < len(args):
        a = bytes(args[i]).upper()
        if a == b"JUSTID":
            justid = True
            i += 1
        elif a == b"FORCE":
            force = True
            i += 1
        elif a in (b"IDLE", b"TIME", b"RETRYCOUNT", b"LASTID"):
            # PEL metadata knobs: accepted for wire compatibility; delivery
            # stamps are managed server-side
            i += 2
        else:
            ids.append(_s(args[i]))
            i += 1
    claimed = st.claim(group, consumer, min_idle, *ids, force=force)
    if justid:
        return [i.encode() for i in claimed]
    return _xentries(claimed)


@register("XAUTOCLAIM")
@_stream_cmd
def cmd_xautoclaim(server, ctx, args):
    st = _stream(server, _s(args[0]))
    group, consumer = _s(args[1]), _s(args[2])
    min_idle = _int(args[3]) / 1000.0
    start = _s(args[4])
    count = 100
    justid = False
    i = 5
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"COUNT":
            count = _int(args[i + 1])
            i += 2
        elif opt == b"JUSTID":
            justid = True
            i += 1
        else:
            raise RespError("ERR syntax error")
    cursor, claimed = st.auto_claim(group, consumer, min_idle, start_id=start, count=count)
    body = [i.encode() for i in claimed] if justid else _xentries(claimed)
    return [cursor.encode(), body, []]


@register("XINFO")
@_stream_cmd
def cmd_xinfo(server, ctx, args):
    sub = bytes(args[0]).upper()
    st = _stream(server, _s(args[1]))
    if sub == b"STREAM":
        last = st.last_id()
        return [
            b"length", st.size(),
            b"last-generated-id", (last or "0-0").encode(),
            b"groups", len(st.list_groups()),
        ]
    if sub == b"GROUPS":
        out = []
        for g in st.list_groups():
            s = st.pending_summary(g)
            out.append([
                b"name", g.encode(),
                b"consumers", len(st.list_consumers(g)),
                b"pending", s["total"],
            ])
        return out
    if sub == b"CONSUMERS":
        group = _s(args[2])
        s = st.pending_summary(group)
        return [
            [b"name", c.encode(), b"pending", s["consumers"].get(c, 0)]
            for c in st.list_consumers(group)
        ]
    raise RespError(f"ERR syntax error in XINFO {_s(args[0])}")


# -- typed geo verbs (RedissonGeo.java wire parity) --------------------------

def _geo(server, name: str):
    return _typed_handle(server, "get_geo", name)


@register("GEOADD")
def cmd_geoadd(server, ctx, args):
    if (len(args) - 1) % 3:
        raise RespError("ERR syntax error")
    g = _geo(server, _s(args[0]))
    n = 0
    for i in range(1, len(args), 3):
        n += g.add(float(args[i]), float(args[i + 1]), bytes(args[i + 2]))
    return n


@register("GEOPOS")
def cmd_geopos(server, ctx, args):
    g = _geo(server, _s(args[0]))
    pos = g.pos(*[bytes(m) for m in args[1:]])
    out = []
    for m in args[1:]:
        p = pos.get(bytes(m))
        out.append(None if p is None else [repr(p[0]).encode(), repr(p[1]).encode()])
    return out


@register("GEODIST")
def cmd_geodist(server, ctx, args):
    unit = _s(args[3]).lower() if len(args) > 3 else "m"
    d = _geo(server, _s(args[0])).dist(bytes(args[1]), bytes(args[2]), unit=unit)
    return None if d is None else _fnum(round(d, 4))


@register("GEOSEARCH")
def cmd_geosearch(server, ctx, args):
    """GEOSEARCH key <FROMMEMBER m | FROMLONLAT lon lat>
    <BYRADIUS r unit | BYBOX w h unit> [ASC|DESC] [COUNT n [ANY]]
    [WITHCOORD] [WITHDIST]."""
    g = _geo(server, _s(args[0]))
    i = 1
    member, lonlat = None, None
    shape = None
    order, count = "ASC", None
    withcoord = withdist = False
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"FROMMEMBER":
            member = bytes(args[i + 1])
            i += 2
        elif opt == b"FROMLONLAT":
            lonlat = (float(args[i + 1]), float(args[i + 2]))
            i += 3
        elif opt == b"BYRADIUS":
            shape = ("radius", float(args[i + 1]), _s(args[i + 2]).lower())
            i += 3
        elif opt == b"BYBOX":
            shape = ("box", float(args[i + 1]), float(args[i + 2]), _s(args[i + 3]).lower())
            i += 4
        elif opt in (b"ASC", b"DESC"):
            order = _s(args[i]).upper()
            i += 1
        elif opt == b"COUNT":
            count = _int(args[i + 1])
            i += 2
            if i < len(args) and bytes(args[i]).upper() == b"ANY":
                i += 1
        elif opt == b"WITHCOORD":
            withcoord = True
            i += 1
        elif opt == b"WITHDIST":
            withdist = True
            i += 1
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    if shape is None or (member is None and lonlat is None):
        raise RespError("ERR syntax error")
    if member is not None:
        p = g.pos(member).get(member)
        if p is None:
            raise RespError("ERR could not decode requested zset member")
        lonlat = p
    if shape[0] == "radius":
        pairs = list(
            g.search_radius_with_distance(
                lonlat[0], lonlat[1], shape[1], unit=shape[2], count=count, order=order
            ).items()
        )
        pairs.sort(key=lambda p: p[1], reverse=order == "DESC")  # dicts drop order
    else:
        from redisson_tpu.client.objects.geo import _UNITS, _haversine_m

        members = g.search_box(lonlat[0], lonlat[1], shape[1], shape[2], unit=shape[3])
        u = _UNITS[shape[3]]
        pairs = []
        for m in members:
            p = g.pos(m).get(m)
            dm = float(_haversine_m(lonlat[0], lonlat[1], p[0], p[1])) if p else 0.0
            pairs.append((m, dm / u))
        pairs.sort(key=lambda t: t[1], reverse=order == "DESC")
        if count is not None:
            pairs = pairs[:count]
    out = []
    for m, dist in pairs:
        m = m if isinstance(m, (bytes, bytearray)) else str(m).encode()
        if not (withcoord or withdist):
            out.append(m)
            continue
        row = [m]
        if withdist:
            row.append(_fnum(round(dist, 4)))
        if withcoord:
            p = g.pos(m).get(m)
            row.append([repr(p[0]).encode(), repr(p[1]).encode()] if p else None)
        out.append(row)
    return out


@register("GEOSEARCHSTORE")
def cmd_geosearchstore(server, ctx, args):
    """GEOSEARCHSTORE dest src FROMLONLAT lon lat BYRADIUS r unit — the
    store-variant subset the reference's searchStore covers."""
    dest, src = _s(args[0]), _s(args[1])
    if bytes(args[2]).upper() != b"FROMLONLAT" or bytes(args[5]).upper() != b"BYRADIUS":
        raise RespError("ERR syntax error (only FROMLONLAT ... BYRADIUS supported)")
    g = _geo(server, src)
    return g.store_search_radius_to(
        dest, float(args[3]), float(args[4]), float(args[6]), unit=_s(args[7]).lower()
    )


def _georadius(server, ctx, args, by_member: bool, allow_store: bool = True):
    """Legacy GEORADIUS[BYMEMBER] translated onto the GEOSEARCH engine
    (Redis 6.2 deprecates these in favor of GEOSEARCH; the reference's
    RedissonGeo still drives them — client/protocol/RedisCommands.java
    GEORADIUS defs).  STORE/STOREDIST subset: plain STORE only."""
    key = args[0]
    if by_member:
        head = [key, b"FROMMEMBER", args[1]]
        i = 4
        radius, unit = args[2], args[3]
    else:
        head = [key, b"FROMLONLAT", args[1], args[2]]
        i = 5
        radius, unit = args[3], args[4]
    head += [b"BYRADIUS", radius, unit]
    store = None
    tail = []
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt in (b"WITHCOORD", b"WITHDIST", b"ASC", b"DESC"):
            tail.append(args[i])
            i += 1
        elif opt == b"WITHHASH":
            i += 1  # geohash integers are not materialized here; ignored
        elif opt == b"COUNT":
            tail += [args[i], args[i + 1]]
            i += 2
            if i < len(args) and bytes(args[i]).upper() == b"ANY":
                tail.append(args[i])
                i += 1
        elif opt in (b"STORE", b"STOREDIST"):
            if not allow_store:
                raise RespError(
                    "ERR STORE option in GEORADIUS is not compatible with "
                    "the _RO variant"
                )
            if opt == b"STOREDIST":
                raise RespError("ERR STOREDIST is not supported; use STORE")
            store = _s(args[i + 1])
            i += 2
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    if store is not None:
        g = _geo(server, _s(key))
        if by_member:
            p = g.pos(bytes(args[1])).get(bytes(args[1]))
            if p is None:
                raise RespError("ERR could not decode requested zset member")
            lon, lat = p
        else:
            lon, lat = float(args[1]), float(args[2])
        return g.store_search_radius_to(
            store, lon, lat, float(radius), unit=_s(unit).lower()
        )
    return cmd_geosearch(server, ctx, head + tail)


@register("GEORADIUS")
def cmd_georadius(server, ctx, args):
    return _georadius(server, ctx, args, by_member=False)


@register("GEORADIUS_RO")
def cmd_georadius_ro(server, ctx, args):
    return _georadius(server, ctx, args, by_member=False, allow_store=False)


@register("GEORADIUSBYMEMBER")
def cmd_georadiusbymember(server, ctx, args):
    return _georadius(server, ctx, args, by_member=True)


@register("GEORADIUSBYMEMBER_RO")
def cmd_georadiusbymember_ro(server, ctx, args):
    return _georadius(server, ctx, args, by_member=True, allow_store=False)


