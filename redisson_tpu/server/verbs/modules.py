"""Redis-stack module verbs: JSON.* (RedisJSON role) and FT.* (RediSearch role).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""


from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.common import _fnum

# -- redis-stack module verbs: JSON.* (RedisJSON role — RedissonJsonBucket
# -- drives these same verbs in the reference) -------------------------------

def _json(server, name: str):
    from redisson_tpu.client.objects.binarystream import JsonBucket

    return JsonBucket(server.engine, name)  # codec-free: documents are parsed JSON


def _json_cmd(fn):
    """Map JsonBucket exceptions (bad paths, type mismatches) to ERR replies."""
    import functools

    @functools.wraps(fn)
    def wrapper(server, ctx, args):
        import json as _j

        try:
            return fn(server, ctx, args, _j)
        except (KeyError, IndexError) as e:
            raise RespError(f"ERR Path does not exist: {e.args[0] if e.args else e}")
        except (TypeError, ValueError) as e:
            raise RespError(f"ERR {e}")

    return wrapper


@register("JSON.SET")
@_json_cmd
def cmd_json_set(server, ctx, args, _j):
    """JSON.SET key path json [NX|XX]."""
    name, path = _s(args[0]), _s(args[1])
    value = _j.loads(bytes(args[2]))
    mode = bytes(args[3]).upper() if len(args) > 3 else None
    jb = _json(server, name)
    if mode in (b"NX", b"XX"):
        existing = jb.get(path)  # returns None for missing paths, never raises
        if (mode == b"NX" and existing is not None) or (mode == b"XX" and existing is None):
            return None
    elif mode is not None:
        raise RespError("ERR syntax error")
    jb.set(path, value)
    return "+OK"


@register("JSON.GET")
@_json_cmd
def cmd_json_get(server, ctx, args, _j):
    """JSON.GET key [path ...] — one path returns its value; several return
    a {path: value} object (RedisJSON reply shape)."""
    jb = _json(server, _s(args[0]))
    paths = [_s(p) for p in args[1:]] or ["$"]
    # JsonBucket.get swallows path errors and returns None; reply nil like
    # RedisJSON (a stored JSON null also reads nil — simplified path
    # semantics, the same trade the handle itself makes)
    if len(paths) == 1:
        v = jb.get(paths[0])
        return None if v is None else _j.dumps(v).encode()
    return _j.dumps({p: jb.get(p) for p in paths}).encode()


@register("JSON.DEL")
@_json_cmd
def cmd_json_del(server, ctx, args, _j):
    jb = _json(server, _s(args[0]))
    return 1 if jb.delete(_s(args[1]) if len(args) > 1 else "$") else 0


@register("JSON.TYPE")
@_json_cmd
def cmd_json_type(server, ctx, args, _j):
    t = _json(server, _s(args[0])).type(_s(args[1]) if len(args) > 1 else "$")
    return None if t is None else t.encode()


@register("JSON.NUMINCRBY")
@_json_cmd
def cmd_json_numincrby(server, ctx, args, _j):
    v = _json(server, _s(args[0])).increment_and_get(_s(args[1]), _j.loads(bytes(args[2])))
    return _j.dumps(v).encode()


@register("JSON.STRAPPEND")
@_json_cmd
def cmd_json_strappend(server, ctx, args, _j):
    return _json(server, _s(args[0])).string_append(_s(args[1]), _j.loads(bytes(args[2])))


@register("JSON.STRLEN")
@_json_cmd
def cmd_json_strlen(server, ctx, args, _j):
    return _json(server, _s(args[0])).string_size(_s(args[1]) if len(args) > 1 else "$")


@register("JSON.ARRAPPEND")
@_json_cmd
def cmd_json_arrappend(server, ctx, args, _j):
    vals = [_j.loads(bytes(a)) for a in args[2:]]
    return _json(server, _s(args[0])).array_append(_s(args[1]), *vals)


@register("JSON.ARRINSERT")
@_json_cmd
def cmd_json_arrinsert(server, ctx, args, _j):
    vals = [_j.loads(bytes(a)) for a in args[3:]]
    return _json(server, _s(args[0])).array_insert(_s(args[1]), _int(args[2]), *vals)


@register("JSON.ARRLEN")
@_json_cmd
def cmd_json_arrlen(server, ctx, args, _j):
    return _json(server, _s(args[0])).array_size(_s(args[1]) if len(args) > 1 else "$")


@register("JSON.ARRPOP")
@_json_cmd
def cmd_json_arrpop(server, ctx, args, _j):
    idx = _int(args[2]) if len(args) > 2 else -1
    v = _json(server, _s(args[0])).array_pop(_s(args[1]) if len(args) > 1 else "$", idx)
    return None if v is None else _j.dumps(v).encode()


@register("JSON.ARRTRIM")
@_json_cmd
def cmd_json_arrtrim(server, ctx, args, _j):
    return _json(server, _s(args[0])).array_trim(_s(args[1]), _int(args[2]), _int(args[3]))


@register("JSON.ARRINDEX")
@_json_cmd
def cmd_json_arrindex(server, ctx, args, _j):
    start = _int(args[3]) if len(args) > 3 else 0
    stop = _int(args[4]) if len(args) > 4 else 0
    return _json(server, _s(args[0])).array_index_of(
        _s(args[1]), _j.loads(bytes(args[2])), start, stop
    )


@register("JSON.OBJKEYS")
@_json_cmd
def cmd_json_objkeys(server, ctx, args, _j):
    ks = _json(server, _s(args[0])).object_keys(_s(args[1]) if len(args) > 1 else "$")
    return None if ks is None else [k.encode() for k in ks]


@register("JSON.OBJLEN")
@_json_cmd
def cmd_json_objlen(server, ctx, args, _j):
    return _json(server, _s(args[0])).object_size(_s(args[1]) if len(args) > 1 else "$")


@register("JSON.CLEAR")
@_json_cmd
def cmd_json_clear(server, ctx, args, _j):
    return _json(server, _s(args[0])).clear(_s(args[1]) if len(args) > 1 else "$")


@register("JSON.TOGGLE")
@_json_cmd
def cmd_json_toggle(server, ctx, args, _j):
    v = _json(server, _s(args[0])).toggle(_s(args[1]))
    return None if v is None else int(v)


@register("JSON.MERGE")
@_json_cmd
def cmd_json_merge(server, ctx, args, _j):
    _json(server, _s(args[0])).merge(_s(args[1]), _j.loads(bytes(args[2])))
    return "+OK"


# -- redis-stack module verbs: FT.* (RediSearch role — RedissonSearch.java
# -- drives these same verbs in the reference) -------------------------------

def _ft(server):
    from redisson_tpu.services.search import SearchService

    return server.engine.service("search", lambda: SearchService(server.engine))


import re as _knn_re

# `(<filter>)=>[KNN <k> @<field> $<param> [AS <alias>]]` — the RediSearch
# vector-query arm (dialect 2).  The filter half feeds the ordinary query
# planner; its candidate set lowers onto the score matrix as an additive
# -inf bias (services/search.knn), so hybrid queries stay ONE kernel.
_KNN_ARM = _knn_re.compile(
    r"^\s*(?:\((?P<filt>.*)\)|(?P<star>\*))\s*=>\s*\[\s*KNN\s+"
    r"(?P<k>\d+)\s+@(?P<field>\w+)\s+\$(?P<param>\w+)"
    r"(?:\s+AS\s+(?P<alias>\w+))?\s*\]\s*$",
    _knn_re.IGNORECASE | _knn_re.DOTALL,
)


def _ft_split_knn(q: str):
    """Split a query into (filter-query, knn-spec|None).  Non-KNN queries
    pass through unchanged."""
    m = _KNN_ARM.match(q)
    if m is None:
        return q, None
    filt = "*" if m.group("star") else (m.group("filt") or "*")
    return filt, {
        "k": int(m.group("k")),
        "field": m.group("field"),
        "param": m.group("param"),
        "alias": m.group("alias"),
    }


def _ft_parse_query(q: str, schema: dict):
    """RediSearch query subset -> Condition tree: `*`, `@f:[lo hi]` numeric
    ranges ('(' = exclusive, ±inf), `@f:{tag|tag}`, `@f:text`, `@f:(txt)`,
    bare words (full-text across every TEXT field); top-level terms AND."""
    import re as _re

    from redisson_tpu.services.search import And, Eq, In, Or, Range, Text

    q = q.strip()
    if q in ("*", ""):
        return None
    tokens = _re.findall(
        r"@\w+:\[[^\]]*\]|@\w+:\{[^}]*\}|@\w+:\([^)]*\)|@\w+:\S+|\S+", q
    )

    def bound(s):
        inc = not s.startswith("(")
        s = s.lstrip("(")
        if s in ("-inf", "inf", "+inf"):
            return (float("-inf") if s == "-inf" else float("inf")), inc
        return float(s), inc

    terms = []
    for t in tokens:
        if t.startswith("@"):
            fld, _, rest = t[1:].partition(":")
            if rest.startswith("["):
                body = rest[1:-1].split()
                if len(body) != 2:
                    raise RespError("ERR Syntax error in numeric range")
                (lo, lo_inc), (hi, hi_inc) = bound(body[0]), bound(body[1])
                terms.append(Range(fld, lo, hi, lo_inc, hi_inc))
            elif rest.startswith("{"):
                vals = [v.strip() for v in rest[1:-1].split("|") if v.strip()]
                if not vals:
                    raise RespError("ERR syntax error: empty tag set")
                terms.append(Eq(fld, vals[0]) if len(vals) == 1 else In(fld, vals))
            elif rest.startswith("("):
                terms.append(Text(fld, rest[1:-1]))
            else:
                terms.append(Text(fld, rest))
        else:
            text_fields = [f for f, ty in schema.items() if ty == "TEXT"]
            if not text_fields:
                raise RespError(f"ERR no TEXT field for bare term '{t}'")
            parts = [Text(f, t) for f in text_fields]
            terms.append(parts[0] if len(parts) == 1 else Or(parts))
    return terms[0] if len(terms) == 1 else And(terms)


def _ft_invalidate(server, ctx, index_name: str) -> None:
    """Index DDL / ingest invalidates the index's synthetic QUERY KEY
    (services/search.query_key): tracked FT.SEARCH results near-cache
    client-side and must go stale whenever the index can change.  Plain
    writes under the index prefixes invalidate through the TrackingTable
    post-dispatch hook; DDL verbs call this directly."""
    track = getattr(server, "tracking", None)
    if track is None or not track.active:
        return
    svc = _ft(server)
    try:
        track.note_write([svc.query_key(svc.resolve(index_name))], None)
    except Exception:  # noqa: BLE001 — invalidation must not fail the verb
        pass


def _ft_track_read(server, ctx, index_name: str) -> None:
    """Register a tracked connection's interest in the index's query key —
    the FT analog of the pre-dispatch read registration (FT.* is keyless,
    so the generic hook never sees it)."""
    track = getattr(server, "tracking", None)
    if track is None or not track.active or ctx.tracking is None:
        return
    svc = _ft(server)
    try:
        track.note_read(ctx, [svc.query_key(svc.resolve(index_name))])
    except Exception:  # noqa: BLE001
        pass


def _ft_cmd(fn):
    """Map malformed FT arguments/queries to syntax errors, missing indexes
    to the RediSearch wording — never 'ERR internal'."""
    import functools

    @functools.wraps(fn)
    def wrapper(server, ctx, args):
        try:
            return fn(server, ctx, args)
        except KeyError:
            raise RespError("ERR Unknown Index name")
        except (ValueError, IndexError) as e:
            raise RespError(f"ERR syntax error: {e}")

    return wrapper


@register("FT.CREATE")
@_ft_cmd
def cmd_ft_create(server, ctx, args):
    """FT.CREATE idx [ON HASH] [PREFIX n p...] SCHEMA f TYPE [SORTABLE] ...

    VECTOR attributes use the RediSearch shape:
    ``f VECTOR {FLAT|IVF} <nargs> TYPE {FLOAT32|FLOAT16|INT8} DIM d
    DISTANCE_METRIC {L2|COSINE|IP} [NLIST n] [NPROBE p] [TRAIN_MIN t]
    [SHARDS s]``
    (the nargs pairs may arrive in any order).  IVF routes queries through
    a trained coarse-centroid bank and scores only the top-NPROBE cells;
    FLOAT16/INT8 compress the bank at upload and dequantize in-kernel;
    SHARDS s > 1 splits the bank row-wise across s local devices with an
    on-device top-k merge (ISSUE 15) — all three axes compose
    (services/vector.py).  Each VECTOR field gets a device-resident
    embedding bank placed on the index's slot-owner device (per shard
    when sharded)."""
    name = _s(args[0])
    prefixes = [""]
    i = 1
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"ON":
            if bytes(args[i + 1]).upper() != b"HASH":
                raise RespError("ERR only ON HASH indexes are supported")
            i += 2
        elif opt == b"PREFIX":
            n = _int(args[i + 1])
            prefixes = [_s(p) for p in args[i + 2 : i + 2 + n]]
            i += 2 + n
        elif opt == b"SCHEMA":
            i += 1
            break
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    else:
        raise RespError("ERR SCHEMA is required")
    schema = {}
    vector = {}
    while i < len(args):
        fld = _s(args[i])
        ty = bytes(args[i + 1]).upper().decode()
        if ty == "VECTOR":
            algo = _s(args[i + 2]).upper()
            nargs = _int(args[i + 3])
            if nargs % 2 or i + 4 + nargs > len(args):
                raise RespError("ERR bad vector attribute count")
            attrs = {}
            for j in range(i + 4, i + 4 + nargs, 2):
                attrs[_s(args[j]).upper()] = _s(args[j + 1])
            missing = {"TYPE", "DIM", "DISTANCE_METRIC"} - set(attrs)
            if missing:
                raise RespError(
                    f"ERR vector attribute(s) missing: {sorted(missing)}"
                )
            vector[fld] = {
                "dim": _int(attrs["DIM"].encode()),
                "metric": attrs["DISTANCE_METRIC"],
                "dtype": attrs["TYPE"],
                "algo": algo,
            }
            for opt_attr, key in (("NLIST", "nlist"), ("NPROBE", "nprobe"),
                                  ("TRAIN_MIN", "train_min"),
                                  ("SHARDS", "shards")):
                if opt_attr in attrs:
                    vector[fld][key] = _int(attrs[opt_attr].encode())
            schema[fld] = "VECTOR"
            i += 4 + nargs
        elif ty in ("TEXT", "TAG", "NUMERIC"):
            schema[fld] = ty
            i += 2
        else:
            raise RespError(f"ERR unsupported field type '{ty}'")
        if i < len(args) and bytes(args[i]).upper() == b"SORTABLE":
            i += 1  # everything is sortable here
    try:
        _ft(server).create(name, schema, prefixes, doc_mode="hash",
                           vector=vector)
    except ValueError as e:
        raise RespError(f"ERR {e}")
    _ft_invalidate(server, ctx, name)
    return "+OK"


@register("FT.DROPINDEX")
@_ft_cmd
def cmd_ft_dropindex(server, ctx, args):
    _ft_invalidate(server, ctx, _s(args[0]))  # before the name resolves away
    if not _ft(server).drop_index(_s(args[0])):
        raise RespError("ERR Unknown Index name")
    return "+OK"


@register("FT._LIST")
@_ft_cmd
def cmd_ft_list(server, ctx, args):
    return [n.encode() for n in _ft(server).index_names()]


@register("FT.INFO")
@_ft_cmd
def cmd_ft_info(server, ctx, args):
    svc = _ft(server)
    idx = svc._idx(_s(args[0]))  # KeyError -> Unknown Index via _ft_cmd
    svc.sync(_s(args[0]))
    info = svc.info(_s(args[0]))
    vec_rows = {r["field"]: r for r in info.get("vector_fields", [])}
    flat_schema = []
    for f, ty in info["schema"].items():
        row = [f.encode(), b"type", ty.encode()]
        vr = vec_rows.get(f)
        if vr is not None:
            # the vector attribute's full shape: dim/metric/rows/bytes —
            # the per-field half of the HBM ledger FT.INFO exposes.
            # device_bytes is the QUANTIZED (actual) residency, not the
            # logical f32 size — compressed banks report what they hold
            row += [
                b"algorithm", vr["algo"].encode(),
                b"data_type", vr["dtype"].encode(),
                b"dim", vr["dim"],
                b"distance_metric", vr["metric"].encode(),
                b"rows", vr["rows"],
                b"device_bytes", vr["device_bytes"],
            ]
            if vr["algo"] == "IVF":
                row += [
                    b"nlist", vr["nlist"],
                    b"nprobe", vr["nprobe"],
                    b"trained", 1 if vr["trained"] else 0,
                    b"index_device_bytes", vr["index_device_bytes"],
                ]
            if "shards" in vr:
                # mesh-sharded bank (ISSUE 15): shard count + one nested
                # row per shard — rows / owning device / residency, the
                # per-shard half of the HBM ledger
                row += [
                    b"shards", vr["shards"],
                    b"shard_rows", [
                        [
                            b"shard", sr["shard"],
                            b"rows", sr["rows"],
                            b"device", sr["device"],
                            b"device_bytes", sr["device_bytes"],
                            b"index_device_bytes",
                            sr["index_device_bytes"],
                        ]
                        for sr in vr.get("shard_rows", [])
                    ],
                ]
        flat_schema.append(row)
    out = [
        b"index_name", info["name"].encode(),
        b"num_docs", info["num_docs"],
        b"attributes", flat_schema,
        b"prefixes", [p.encode() for p in info["prefixes"]],
    ]
    if "vector_device_bytes" in info:
        out += [b"vector_device_bytes", info["vector_device_bytes"]]
        out += [b"vector_index_bytes", info.get("vector_index_bytes", 0)]
    return out


def _ft_field_blob(v) -> bytes:
    """Reply encoding of one stored field value — raw bytes (vector blobs)
    pass through untouched, everything else stringifies."""
    return bytes(v) if isinstance(v, (bytes, bytearray)) else str(v).encode()


def _ft_score_bytes(d: float) -> bytes:
    """Distance formatting for KNN replies: fixed 4-decimal text, so the
    armed (device f32) and disarmed (NumPy f32) paths — which may differ in
    the last ulp from reduction order — encode identically on the wire."""
    return (b"%.4f" % d)


def _ft_parse_search_opts(args, i):
    """Shared FT.SEARCH/FT.MSEARCH option tail: NOCONTENT / SORTBY / LIMIT /
    PARAMS / DIALECT / NPROBE / WITHCURSOR [COUNT n]."""
    opts = {
        "nocontent": False, "sort_by": None, "desc": False,
        "off": 0, "lim": 10, "params": {}, "withcursor": False,
        "cursor_count": 10, "nprobe": None,
    }
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"NOCONTENT":
            opts["nocontent"] = True
            i += 1
        elif opt == b"SORTBY":
            opts["sort_by"] = _s(args[i + 1])
            i += 2
            if i < len(args) and bytes(args[i]).upper() in (b"ASC", b"DESC"):
                opts["desc"] = bytes(args[i]).upper() == b"DESC"
                i += 1
        elif opt == b"LIMIT":
            opts["off"], opts["lim"] = _int(args[i + 1]), _int(args[i + 2])
            i += 3
        elif opt == b"PARAMS":
            n = _int(args[i + 1])
            if n % 2:
                raise RespError("ERR PARAMS count must be even")
            for j in range(i + 2, i + 2 + n, 2):
                opts["params"][_s(args[j])] = bytes(args[j + 1])
            i += 2 + n
        elif opt == b"DIALECT":
            i += 2  # accepted for driver compatibility; grammar is fixed
        elif opt == b"NPROBE":
            # per-query IVF probe width (the recall/latency dial); rejected
            # downstream for non-IVF fields
            opts["nprobe"] = _int(args[i + 1])
            if opts["nprobe"] <= 0:
                raise RespError("ERR NPROBE must be positive")
            i += 2
        elif opt == b"WITHCURSOR":
            opts["withcursor"] = True
            i += 1
            if i + 1 < len(args) and bytes(args[i]).upper() == b"COUNT":
                opts["cursor_count"] = _int(args[i + 1])
                i += 2
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    return opts


def _ft_knn_query_vectors(server, idx, knn, params, expect_multiple=False):
    """Decode the KNN arm's $param blob into (Q, dim) float32 queries."""
    import numpy as np

    spec = idx.vector_specs.get(knn["field"])
    if spec is None:
        raise RespError(
            f"ERR '{knn['field']}' is not a VECTOR field of '{idx.name}'"
        )
    blob = params.get(knn["param"])
    if blob is None:
        raise RespError(f"ERR missing PARAMS value for ${knn['param']}")
    if len(blob) == 0 or len(blob) % (spec.dim * 4):
        raise RespError(
            f"ERR vector blob of {len(blob)} bytes does not pack DIM "
            f"{spec.dim} float32 vectors"
        )
    q = np.frombuffer(blob, dtype="<f4").reshape(-1, spec.dim)
    if not expect_multiple and q.shape[0] != 1:
        raise RespError("ERR FT.SEARCH KNN takes exactly one query vector")
    return np.ascontiguousarray(q, np.float32)


def _ft_knn_reply(idx, hits, opts, score_field):
    """One query's [(doc_id, dist), ...] -> the FT.SEARCH reply rows.

    Plain mode returns the flat RediSearch shape
    ``[total, id, [f, v, ..., score_field, score], ...]`` (LIMIT applies to
    the k hits).  WITHCURSOR returns ``[[n, [id, flds], ...], cid]`` — rows
    nest so FT.CURSOR READ pages the SAME shape (k > COUNT spills into the
    cursor; services/search cursor expiry/cap applies)."""
    rows = []
    for doc_id, dist in hits:
        fields = idx.docs.get(doc_id)
        if opts["nocontent"]:
            flat = [score_field.encode(), _ft_score_bytes(dist)]
        else:
            flat = []
            for k, v in (fields or {}).items():
                flat += [str(k).encode(), _ft_field_blob(v)]
            flat += [score_field.encode(), _ft_score_bytes(dist)]
        rows.append([doc_id.encode(), flat])
    return rows


@register("FT.SEARCH")
@_ft_cmd
def cmd_ft_search(server, ctx, args):
    """FT.SEARCH idx query [NOCONTENT] [SORTBY f [ASC|DESC]] [LIMIT off n]
    [PARAMS n k v ...] [DIALECT d] [WITHCURSOR [COUNT n]]
    -> [total, id, [f, v, ...], ...] (RediSearch reply shape).

    The KNN arm ``(filter)=>[KNN k @f $vec]`` scores on the index's
    device-resident embedding bank as ONE matmul-top-k kernel and replies
    lazily: the (dist, idx) kernel outputs ride the frame-grouped readback
    (LazyReply), so M concurrent KNN frames cost <= M+1 blocking syncs.
    Results carry ``__<field>_score`` (distance, 4 decimals, ascending).
    WITHCURSOR pages k > COUNT hits through FT.CURSOR READ (nested-row
    shape, see _ft_knn_reply)."""
    from redisson_tpu.server.registry import LazyReply

    svc = _ft(server)
    idx = svc._idx(_s(args[0]))  # KeyError -> Unknown Index via _ft_cmd
    _ft_track_read(server, ctx, _s(args[0]))
    svc.sync(svc.resolve(_s(args[0])))
    qstr, knn = _ft_split_knn(_s(args[1]))
    opts = _ft_parse_search_opts(args, 2)
    cond = _ft_parse_query(qstr, idx.schema)

    if knn is None:
        if opts["withcursor"]:
            raise RespError("ERR WITHCURSOR requires a KNN query")
        res = svc.search(_s(args[0]), cond, sort_by=opts["sort_by"],
                         descending=opts["desc"], offset=opts["off"],
                         limit=opts["lim"])
        out = [res.total]
        for doc_id, fields in res.docs:
            out.append(doc_id.encode())
            if not opts["nocontent"]:
                flat = []
                for k, v in fields.items():
                    flat += [str(k).encode(), _ft_field_blob(v)]
                out.append(flat)
        return out

    # -- KNN path -------------------------------------------------------------
    if knn["k"] <= 0:
        raise RespError("ERR KNN k must be positive")
    if opts["sort_by"] is not None and opts["sort_by"] != (
        knn["alias"] or f"__{knn['field']}_score"
    ):
        raise RespError("ERR KNN results sort by the vector score")
    q = _ft_knn_query_vectors(server, idx, knn, opts["params"])
    try:
        device, finish = svc.knn(
            _s(args[0]), knn["field"], q, knn["k"], condition=cond,
            nprobe=opts["nprobe"],
        )
    except ValueError as e:
        raise RespError(f"ERR {e}")
    score_field = knn["alias"] or f"__{knn['field']}_score"

    def encode(vals):
        hits = finish(vals)[0]
        if opts["desc"]:
            hits = hits[::-1]  # SORTBY <score> DESC: farthest-first paging
        rows = _ft_knn_reply(idx, hits, opts, score_field)
        if opts["withcursor"]:
            count = max(1, opts["cursor_count"])
            batch, rest = rows[:count], rows[count:]
            cid = svc.cursor_create(rest) if rest else 0
            return [[len(batch)] + batch, cid]
        rows = rows[opts["off"] : opts["off"] + opts["lim"]]
        out = [len(hits)]
        for doc_id, flat in rows:
            out.append(doc_id)
            out.append(flat)
        return out

    if device is None:  # disarmed (RTPU_NO_VECTOR) or empty index/filter
        return encode(None)
    return LazyReply(device=device, finish=encode)


@register("FT.MSEARCH")
@_ft_cmd
def cmd_ft_msearch(server, ctx, args):
    """FT.MSEARCH idx query [PARAMS ...] — the batched multi-query KNN
    path: the $param blob packs Q stacked float32 vectors (Q*dim*4 bytes)
    and the whole batch scores as ONE stacked matmul-top-k dispatch (a
    coalesced run of same-index KNN frames in a single command).  Reply:
    ``[Q, [id, score, id, score, ...] per query]`` — ids+scores only, the
    throughput projection."""
    from redisson_tpu.server.registry import LazyReply

    svc = _ft(server)
    idx = svc._idx(_s(args[0]))
    _ft_track_read(server, ctx, _s(args[0]))
    svc.sync(svc.resolve(_s(args[0])))
    qstr, knn = _ft_split_knn(_s(args[1]))
    if knn is None:
        raise RespError("ERR FT.MSEARCH requires a KNN query")
    opts = _ft_parse_search_opts(args, 2)
    if opts["withcursor"]:
        raise RespError("ERR FT.MSEARCH does not support WITHCURSOR")
    cond = _ft_parse_query(qstr, idx.schema)
    q = _ft_knn_query_vectors(server, idx, knn, opts["params"],
                              expect_multiple=True)
    try:
        device, finish = svc.knn(
            _s(args[0]), knn["field"], q, knn["k"], condition=cond,
            nprobe=opts["nprobe"],
        )
    except ValueError as e:
        raise RespError(f"ERR {e}")

    def encode(vals):
        per_query = finish(vals)
        out = [len(per_query)]
        for hits in per_query:
            flat = []
            for doc_id, dist in hits:
                flat += [doc_id.encode(), _ft_score_bytes(dist)]
            out.append(flat)
        return out

    if device is None:
        return encode(None)
    return LazyReply(device=device, finish=encode)


@register("FT.AGGREGATE")
@_ft_cmd
def cmd_ft_aggregate(server, ctx, args):
    """FT.AGGREGATE idx query [GROUPBY 1 @f REDUCE op n [@f] AS name ...]
    [SORTBY n @f [ASC|DESC]] [LIMIT off n] [WITHCURSOR [COUNT n]]."""
    svc = _ft(server)
    idx = svc._idx(_s(args[0]))  # KeyError -> Unknown Index via _ft_cmd
    svc.sync(svc.resolve(_s(args[0])))
    cond = _ft_parse_query(_s(args[1]), idx.schema)
    group_by, reducers = None, {}
    sort_by, desc = None, False
    off, lim = 0, None
    withcursor, cursor_count = False, 1000
    i = 2
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"WITHCURSOR":
            withcursor = True
            i += 1
            if i + 1 < len(args) and bytes(args[i]).upper() == b"COUNT":
                cursor_count = _int(args[i + 1])
                i += 2
        elif opt == b"GROUPBY":
            if _int(args[i + 1]) != 1:
                raise RespError("ERR GROUPBY supports exactly one property")
            group_by = _s(args[i + 2]).lstrip("@")
            i += 3
        elif opt == b"REDUCE":
            op = _s(args[i + 1]).lower()
            if op not in ("count", "sum", "avg", "min", "max"):
                raise RespError(f"ERR unsupported reducer '{op}'")
            nargs = _int(args[i + 2])
            fld = _s(args[i + 3]).lstrip("@") if nargs else None
            i += 3 + nargs
            name = f"{op}({fld or ''})"
            if i < len(args) and bytes(args[i]).upper() == b"AS":
                name = _s(args[i + 1])
                i += 2
            reducers[name] = (op, fld)
        elif opt == b"SORTBY":
            n = _int(args[i + 1])
            sort_by = _s(args[i + 2]).lstrip("@")
            if n > 1:
                desc = bytes(args[i + 3]).upper() == b"DESC"
            i += 2 + n
        elif opt == b"LIMIT":
            off, lim = _int(args[i + 1]), _int(args[i + 2])
            i += 3
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    rows = svc.aggregate(_s(args[0]), cond, group_by=group_by,
                         reducers=reducers or None, sort_by=sort_by,
                         descending=desc, offset=off, limit=lim)
    flat_rows = []
    for row in rows:
        flat = []
        for k, v in row.items():
            flat += [str(k).encode(), str(v).encode()]
        flat_rows.append(flat)
    if withcursor:
        batch, rest = flat_rows[:cursor_count], flat_rows[cursor_count:]
        cid = svc.cursor_create(rest) if rest else 0
        return [[len(batch)] + batch, cid]
    return [len(flat_rows)] + flat_rows


@register("FT.CURSOR")
@_ft_cmd
def cmd_ft_cursor(server, ctx, args):
    """FT.CURSOR READ idx cid [COUNT n] | FT.CURSOR DEL idx cid — pages a
    WITHCURSOR aggregation (RediSearch cursor API)."""
    svc = _ft(server)
    sub = bytes(args[0]).upper()
    cid = _int(args[2])
    if sub == b"READ":
        count = 1000
        if len(args) > 4 and bytes(args[3]).upper() == b"COUNT":
            count = _int(args[4])
        rows, nxt = svc.cursor_read(cid, count)  # KeyError -> unknown cursor
        return [[len(rows)] + rows, nxt]
    if sub == b"DEL":
        svc.cursor_del(cid)
        return "+OK"
    raise RespError("ERR syntax error")


@register("FT.ALTER")
@_ft_cmd
def cmd_ft_alter(server, ctx, args):
    """FT.ALTER idx SCHEMA ADD field type [SORTABLE]."""
    if (
        len(args) < 5
        or bytes(args[1]).upper() != b"SCHEMA"
        or bytes(args[2]).upper() != b"ADD"
    ):
        raise RespError("ERR syntax error")
    ty = bytes(args[4]).upper().decode()
    if ty not in ("TEXT", "TAG", "NUMERIC"):
        raise RespError(f"ERR unsupported field type '{ty}'")
    try:
        _ft(server).alter(_s(args[0]), _s(args[3]), ty)
    except ValueError as e:
        raise RespError(f"ERR {e}")
    _ft_invalidate(server, ctx, _s(args[0]))
    return "+OK"


@register("FT.ALIASADD")
@_ft_cmd
def cmd_ft_aliasadd(server, ctx, args):
    try:
        _ft(server).alias_add(_s(args[0]), _s(args[1]))
    except ValueError as e:
        raise RespError(f"ERR {e}")
    return "+OK"


@register("FT.ALIASUPDATE")
@_ft_cmd
def cmd_ft_aliasupdate(server, ctx, args):
    _ft(server).alias_update(_s(args[0]), _s(args[1]))
    return "+OK"


@register("FT.ALIASDEL")
@_ft_cmd
def cmd_ft_aliasdel(server, ctx, args):
    try:
        _ft(server).alias_del(_s(args[0]))
    except ValueError as e:
        raise RespError(f"ERR {e}")
    return "+OK"


@register("FT.SYNUPDATE")
@_ft_cmd
def cmd_ft_synupdate(server, ctx, args):
    """FT.SYNUPDATE idx group_id [SKIPINITIALSCAN] term... — terms join the
    synonym group; query-time TEXT matching expands through groups
    (services/search.py SearchIndex.syn_update)."""
    idx = _ft(server)._idx(_s(args[0]))
    group = _s(args[1])
    terms = [_s(a) for a in args[2:]]
    if terms and terms[0].upper() == "SKIPINITIALSCAN":
        terms = terms[1:]  # groups apply query-side: no rescan either way
    if not terms:
        raise RespError("ERR FT.SYNUPDATE needs at least one term")
    idx.syn_update(group, terms)
    _ft_invalidate(server, ctx, _s(args[0]))
    return "+OK"


@register("FT.SYNDUMP")
@_ft_cmd
def cmd_ft_syndump(server, ctx, args):
    """FT.SYNDUMP idx -> flat [term, [group...], ...] (RediSearch shape)."""
    idx = _ft(server)._idx(_s(args[0]))
    out = []
    for term, groups in sorted(idx.syn_dump().items()):
        out.append(term.encode())
        out.append([g.encode() for g in groups])
    return out


@register("FT.CONFIG")
def cmd_ft_config(server, ctx, args):
    """FT.CONFIG GET|SET option [value] — a real settings map (per-server),
    accepted for driver compatibility; options do not alter the engine's
    search behavior and say so in FT.INFO-style introspection."""
    sub = bytes(args[0]).upper() if args else b""
    cfg = server.__dict__.setdefault("_ft_config", {"MAXEXPANSIONS": "200"})
    if sub == b"SET" and len(args) >= 3:
        cfg[_s(args[1]).upper()] = _s(args[2])
        return "+OK"
    if sub == b"GET" and len(args) >= 2:
        pat = _s(args[1]).upper()
        items = cfg.items() if pat == "*" else [(pat, cfg.get(pat))]
        return [[k.encode(), (v or "").encode()] for k, v in items if v is not None]
    raise RespError("ERR FT.CONFIG GET|SET option [value]")


@register("FT.DICTADD")
@_ft_cmd
def cmd_ft_dictadd(server, ctx, args):
    return _ft(server).dict_add(_s(args[0]), *[_s(a) for a in args[1:]])


@register("FT.DICTDEL")
@_ft_cmd
def cmd_ft_dictdel(server, ctx, args):
    return _ft(server).dict_del(_s(args[0]), *[_s(a) for a in args[1:]])


@register("FT.DICTDUMP")
@_ft_cmd
def cmd_ft_dictdump(server, ctx, args):
    return [t.encode() for t in _ft(server).dict_dump(_s(args[0]))]


@register("FT.SPELLCHECK")
@_ft_cmd
def cmd_ft_spellcheck(server, ctx, args):
    """FT.SPELLCHECK idx query [DISTANCE d] [TERMS INCLUDE|EXCLUDE dict]...
    -> [["TERM", term, [[score, suggestion], ...]], ...]."""
    include, exclude = [], []
    distance = 1
    i = 2
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"DISTANCE":
            distance = _int(args[i + 1])
            if not 1 <= distance <= 4:
                raise RespError("ERR invalid distance, must be between 1 and 4")
            i += 2
        elif opt == b"TERMS":
            mode = bytes(args[i + 1]).upper()
            (include if mode == b"INCLUDE" else exclude).append(_s(args[i + 2]))
            i += 3
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    res = _ft(server).spellcheck(
        _s(args[0]), _s(args[1]), include=include, exclude=exclude,
        distance=distance,
    )
    return [
        [b"TERM", term.encode(),
         [[_fnum(score), sugg.encode()] for score, sugg in suggs]]
        for term, suggs in res.items()
    ]


