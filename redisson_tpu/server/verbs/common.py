"""Shared verb preludes: record-handle accessors, argument parsing, reply
formatting, and the blocking-wait loop used across verb families.

This is THE one home for helpers more than one family needs — the r3 advisor
found `_znumkeys` vs `_bmpop_prelude` diverging when prelude logic was
duplicated per-section; keeping validation here makes that impossible.
"""

import threading
from typing import List

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import _int, _s

# EXEC bodies run handlers inline on one worker; blocking verbs inside an
# EXEC degrade to a single poll (Redis semantics) via this flag
_exec_tls = threading.local()


def _typed_handle(server, factory: str, name: str):
    from redisson_tpu.client.codec import BytesCodec

    return getattr(server.local_client(), factory)(name, codec=BytesCodec())


def _deque(server, name: str):
    return _typed_handle(server, "get_deque", name)


def _zset(server, name: str):
    return _typed_handle(server, "get_scored_sorted_set", name)


def _bitset(server, name: str):
    from redisson_tpu.client.objects.bitset import BitSet

    return BitSet(server.engine, name)


def _fnum(x: float) -> bytes:
    """Redis float reply formatting: integral values print without '.0'."""
    return (str(int(x)) if float(x) == int(x) else repr(float(x))).encode()


def _glob_match(pattern: str, value: str) -> bool:
    import fnmatch

    return fnmatch.fnmatchcase(value, pattern)


def _scan_page(items: List[bytes], cursor: int, count: int):
    """Cursor = offset into the sorted item list (stable enough under the
    weakly-consistent SCAN contract the reference also provides)."""
    nxt = cursor + count
    page = items[cursor:nxt]
    return [b"0" if nxt >= len(items) else str(nxt).encode(), page]


def _scan_opts(args, start: int):
    pattern, count, novalues = None, 10, False
    i = start
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"MATCH":
            pattern = _s(args[i + 1])
            i += 2
        elif opt == b"COUNT":
            count = max(1, _int(args[i + 1]))
            i += 2
        elif opt == b"NOVALUES":
            novalues = True
            i += 1
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    return pattern, count, novalues


def _znumkeys(server, args, at=0):
    n = _int(args[at])
    if n <= 0:
        raise RespError("ERR numkeys should be greater than 0")
    if len(args) < at + 1 + n:
        raise RespError("ERR Number of keys can't be greater than number of args")
    names = [_s(k) for k in args[at + 1 : at + 1 + n]]
    return n, names, at + 1 + n


def _signal_waiters(server, name: str) -> None:
    """Wake queue-family waiters (pushes through Deque handles signal
    automatically; ZADD must wake BZPOP*)."""
    server.engine.signal_queue_waiters(name)


def _block_loop(server, first_key: str, poll_once, timeout: float):
    """Shared BLPOP/BRPOP/BZPOP/BLMOVE wait loop.  timeout<=0 = forever
    (the reference marks these isBlockingCommand: they bypass ping timeouts
    and hold their connection; here they hold one slow-pool worker)."""
    import time as _t

    if getattr(_exec_tls, "in_exec", False):
        # blocking verbs inside MULTI/EXEC act as an immediate-timeout poll
        return poll_once()
    deadline = None if timeout <= 0 else _t.time() + timeout
    entry = server.engine.queue_wait_entry(first_key)
    while not getattr(server, "_closing", False):
        r = poll_once()
        if r is not None:
            return r
        remaining = None if deadline is None else deadline - _t.time()
        if remaining is not None and remaining <= 0:
            return None
        entry.wait_for(min(0.05, remaining) if remaining is not None else 0.05)
    return None  # server stopping: unpark, reply nil
