"""Node admin/info, replication, checkpoint, script/function verbs (redisnode + RScript/RFunction surface).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

import threading
import time

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.collections import cmd_lmpop, cmd_zmpop
from redisson_tpu.server.verbs.common import _block_loop, _exec_tls, _glob_match

# -- admin / node info (redisnode/* surface) ---------------------------------

@register("TIME")
def cmd_time(server, ctx, args):
    t = time.time()
    return [str(int(t)).encode(), str(int((t % 1) * 1e6)).encode()]


@register("INFO")
def cmd_info(server, ctx, args):
    """INFO [section] — the default sections, or one named section.
    ``INFO commandstats`` (ISSUE 12 satellite) renders per-verb
    calls/usec/usec_per_call from the MetricsRegistry command timers."""
    if args:
        section = _s(args[0]).lower()
        if section == "commandstats":
            return server.commandstats_text().encode()
        if section in ("all", "everything"):
            return (server.info_text() + server.commandstats_text()).encode()
    return server.info_text().encode()


@register("MEMORY")
def cmd_memory(server, ctx, args):
    sub = bytes(args[0]).upper() if args else b""
    if sub == b"USAGE":
        rec = server.engine.store.get(_s(args[1]))
        if rec is None:
            return None
        total = 0
        for arr in rec.arrays.values():
            total += int(getattr(arr, "nbytes", 0) or 0)
        import sys

        if rec.host is not None:
            total += sys.getsizeof(rec.host)
        return total
    if sub == b"STATS":
        return [b"keys.count", len(server.engine.store)]
    return "+OK"


@register("CLUSTER")
def cmd_cluster(server, ctx, args):
    sub = bytes(args[0]).upper() if args else b""
    if sub == b"SLOTS":
        return server.cluster_slots()
    if sub == b"MYID":
        return server.node_id.encode()
    if sub == b"INFO":
        state = "ok" if server.cluster_view else "ok"
        return f"cluster_enabled:{1 if server.cluster_view else 0}\r\ncluster_state:{state}\r\n".encode()
    if sub == b"SETVIEW":
        # SETVIEW [TOKEN <n>] <from> <to> <host> <port> <node_id> ...
        # (5-tuples) — the topology/launcher (harness.ClusterRunner,
        # server/monitor.py) installs the slot map on every node; the
        # reference's analog is each node's view from CLUSTER NODES gossip.
        # TOKEN carries the writing coordinator's FENCING token (its
        # FencedLock leadership token): a view stamped with a LOWER token
        # than the last accepted one is a stale ex-leader's late write and
        # is rejected — the fencing discipline that makes coordinator HA
        # safe (a paused leader resuming after its lease lapsed cannot
        # clobber its successor's topology).
        rest = args[1:]
        token = None
        if rest and bytes(rest[0]).upper() == b"TOKEN":
            token = _int(rest[1])
            rest = rest[2:]
        if len(rest) % 5 != 0:
            raise RespError("ERR SETVIEW expects 5-tuples")
        if token is not None:
            if token < server.view_epoch:
                raise RespError(
                    f"STALEVIEW token {token} < accepted epoch {server.view_epoch}"
                )
            server.view_epoch = token
        view = []
        for i in range(0, len(rest), 5):
            view.append(
                (
                    _int(rest[i]),
                    _int(rest[i + 1]),
                    _s(rest[i + 2]),
                    _int(rest[i + 3]),
                    _s(rest[i + 4]),
                )
            )
        server.cluster_view = view
        return "+OK"
    if sub == b"RESET":
        server.cluster_view = []
        return "+OK"
    # -- live slot migration (MIGRATING/IMPORTING window + drain) ------------
    if sub == b"SETSLOT":
        # SETSLOT <slot> MIGRATING <host:port> | IMPORTING <host:port> |
        #         STABLE | NODE <host:port> <node_id>   [EPOCH <n>]
        # EPOCH is the journaled coordinator's per-migration fencing token
        # (server.fence_slot_epoch): re-issue with the SAME epoch is the
        # idempotent resume path; a LOWER epoch is a stale coordinator and
        # replies STALEEPOCH before any state changes.
        slot = _int(args[1])
        mode = bytes(args[2]).upper()
        rest = list(args[3:])
        epoch = None
        if len(rest) >= 2 and bytes(rest[-2]).upper() == b"EPOCH":
            epoch = _int(rest[-1])
            rest = rest[:-2]
        server.fence_slot_epoch(slot, epoch)
        if mode == b"MIGRATING":
            server.set_slot_migrating(slot, _s(rest[0]), epoch)
            return "+OK"
        if mode == b"IMPORTING":
            server.set_slot_importing(slot, _s(rest[0]))
            return "+OK"
        if mode == b"STABLE":
            server.set_slot_stable(slot, epoch)
            return "+OK"
        if mode == b"NODE":
            # finalize locally: point the slot at its new owner in this
            # node's view and clear the window state (the orchestrator also
            # pushes a full SETVIEW; NODE keeps single-node finalization
            # correct even before that lands)
            addr, nid = _s(rest[0]), _s(rest[1])
            host, port = addr.rsplit(":", 1)
            new_view = []
            for lo, hi, h, p, vnid in server.cluster_view:
                if lo <= slot <= hi:
                    # split the range around the reassigned slot
                    if lo <= slot - 1:
                        new_view.append((lo, slot - 1, h, p, vnid))
                    new_view.append((slot, slot, host, int(port), nid))
                    if slot + 1 <= hi:
                        new_view.append((slot + 1, hi, h, p, vnid))
                else:
                    new_view.append((lo, hi, h, p, vnid))
            server.cluster_view = new_view
            server.set_slot_stable(slot, epoch)
            return "+OK"
        raise RespError("ERR SETSLOT expects MIGRATING|IMPORTING|STABLE|NODE")
    if sub == b"WINDOWS":
        # live migration-window state, over the wire: the cross-process
        # soak (chaos/soak.py ClusterProcSoakHarness) asserts "all slots
        # STABLE" on real server processes, where reaching into
        # server.migrating_slots directly is impossible by design.
        # Reply: [["MIGRATING", slot, target], ..., ["IMPORTING", slot, src]]
        out = [
            [b"MIGRATING", s, t.encode()]
            for s, t in sorted(server.migrating_slots.items())
        ]
        out += [
            [b"IMPORTING", s, src.encode()]
            for s, src in sorted(server.importing_slots.items())
        ]
        out += [
            [b"RECOVERING", s, t.encode()]
            for s, t in sorted(server.recovering_slots.items())
        ]
        # target-side import-journal state (ISSUE 13): an operator can see
        # an in-flight import from the RECEIVING end — epoch, phase,
        # batches made durable pre-ack, and the draining source.  Rows
        # disappear when the migration's last slot goes STABLE (the
        # journal terminalizes), so "no windows" keeps meaning "settled".
        out += [
            [b"IMPORTJOURNAL", epoch, phase.encode(), batches, src.encode()]
            for epoch, phase, batches, src in server.import_journal_rows()
        ]
        return out
    if sub == b"COUNTKEYSINSLOT":
        return len(server.slot_names(_int(args[1])))
    if sub == b"GETKEYSINSLOT":
        names = server.slot_names(_int(args[1]))
        limit = _int(args[2]) if len(args) > 2 else len(names)
        return [n.encode() for n in names[:limit]]
    if sub == b"MIGRATESLOT":
        # drain one MIGRATING slot (optional batch limit; <=0 = fully)
        limit = _int(args[2]) if len(args) > 2 else 0
        return server.migrate_slot_batch(_int(args[1]), limit)
    if sub == b"DEVICES":
        # device-sharded serving state (ISSUE 8), over the wire: per-device
        # slot counts + device labels so tooling (bench config5d, the
        # device-shard soak) can audit the placement without in-process
        # access.  Reply: [n_devices, [dev_id, slots_owned, label,
        # [QOS, infl_ops_i, infl_ops_b, infl_bytes_i, infl_bytes_b,
        #  dispatched_i, dispatched_b]]...] — the trailing QOS row is the
        # lane's per-deadline-class scheduler ledger (ISSUE 10; appended so
        # pre-QoS consumers indexing row[0..2] keep working).  A server
        # without placement replies [0].
        p = server.engine.placement
        if p is None:
            return [0]
        counts = p.slot_counts()
        lanes = server.engine.lanes
        out = [p.n_devices]
        for i, d in enumerate(p.devices):
            row = [getattr(d, "id", i), counts[i], str(d).encode()]
            if lanes is not None:
                lane = lanes.lane(d)
                row.append([b"QOS"] + lane.qos.wire_row())
                # device fault ledger (ISSUE 19) — appended AFTER the QOS
                # row, same discipline: pre-fault consumers indexing
                # row[0..3] keep working.  [FAULTS, quarantined,
                # consec_faults, total_faults, last_fault_kind]
                row.append([
                    b"FAULTS", int(lane.quarantined), lane.consec_faults,
                    lane.total_faults, lane.last_fault_kind.encode(),
                ])
            out.append(row)
        return out
    if sub == b"QOS":
        # CLUSTER QOS REBALANCE <tenant> <rate> [<burst>] (ISSUE 18): the
        # fleet budget actuator — a supervisor control loop pushes each
        # node's share of a tenant's GLOBAL rate here (cluster/qos_control
        # re-splits it proportional to observed per-node demand).  Applies
        # the override via the scheduler's per-tenant hook; a control-plane
        # push, not consensus.
        if len(args) > 1 and bytes(args[1]).upper() == b"REBALANCE":
            if len(args) < 4:
                raise RespError(
                    "ERR CLUSTER QOS REBALANCE <tenant> <rate> [<burst>] "
                    "[WEIGHT <w>]"
                )
            # WEIGHT <w> (ISSUE 19 satellite): the tenant's service-class
            # weight (gold=2.0 / silver=1.0 style) — stored on the bucket
            # state, consumed by the supervisor's demand split; the rate
            # retarget itself stays token-preserving regardless.
            rest = list(args[2:])
            weight = None
            if len(rest) >= 2 and bytes(rest[-2]).upper() == b"WEIGHT":
                try:
                    weight = float(rest[-1])
                except ValueError:
                    raise RespError("ERR value is not a valid float") from None
                rest = rest[:-2]
            tenant = _s(rest[0]) if rest else ""
            try:
                rate = float(rest[1])
                burst = float(rest[2]) if len(rest) > 2 else None
            except (IndexError, ValueError):
                raise RespError("ERR value is not a valid float") from None
            if weight is not None:
                server.scheduler.set_tenant_weight(tenant, weight)
            server.scheduler.set_tenant_rate(tenant, rate, burst)
            return b"OK"
        # global window-scheduler state (ISSUE 10): armed flag, shed
        # totals, per-class in-flight, the per-device-stream rows
        # (ISSUE 18), and the per-tenant bucket table.
        # Reply: [armed, shed_ops, shed_frames,
        #         [class, infl_frames, infl_ops, infl_bytes]...,
        #         [b"STREAM", name, infl_ops, dispatched_ops]...,
        #         [b"TENANT", name, bucket_level, admitted, shed_ops,
        #          shed_frames]...]
        # STREAM rows aggregate over the engine's device lanes; their
        # b"STREAM" tag keeps row[0] distinct from the class rows so
        # pre-stream parsers (OccupancyLoadBalancer._qos_infl_ops) skip
        # them unchanged.
        sched = server.scheduler
        led = sched.ledger
        out = [1 if sched.armed else 0, sched.shed_ops, sched.shed_frames]
        for cls in ("interactive", "bulk"):
            out.append([
                cls.encode(), led.frames[cls], led.ops[cls], led.nbytes[cls],
            ])
        lanes = server.engine.lanes
        if lanes is not None:
            agg = {}
            for lane in lanes.lanes():
                for tag, name, infl, disp in lane.qos.stream_rows():
                    cur = agg.setdefault(name, [0, 0])
                    cur[0] += infl
                    cur[1] += disp
            for name in (b"interactive", b"bulk"):
                if name in agg:
                    out.append([b"STREAM", name] + agg[name])
        for name, level, admitted, shed_ops, shed_frames, weight \
                in sched.tenant_table():
            # weight rides as a trailing element (ISSUE 19 satellite):
            # parse_tenant_table's len>=6 contract tolerates — and now
            # surfaces — it, so pre-weight consumers keep working.
            out.append([
                b"TENANT", name.encode(), int(level), admitted,
                shed_ops, shed_frames, f"{weight:g}".encode(),
            ])
        return out
    if sub == b"DEVMOVE":
        # DEVMOVE <dev_index> [EPOCH <n>] <slot>... — fenced slot -> device
        # handoff inside THIS process (the device-rebalance wire verb: a
        # move is just a placement handoff riding the migration fencing
        # epochs; a stale coordinator's lower epoch replies STALEEPOCH).
        # Returns the number of records whose banks actually moved.
        from redisson_tpu.server.placement import PlacementStaleEpoch

        if server.engine.placement is None:
            raise RespError("ERR placement is not enabled on this server")
        rest = list(args[1:])
        dev_index = _int(rest[0])
        rest = rest[1:]
        epoch = None
        if rest and bytes(rest[0]).upper() == b"EPOCH":
            epoch = _int(rest[1])
            rest = rest[2:]
        moved = 0
        try:
            for s in (_int(a) for a in rest):
                moved += server.engine.move_slot_records(s, dev_index, epoch)
        except PlacementStaleEpoch as e:
            raise RespError(str(e))
        except ValueError as e:
            raise RespError(f"ERR {e}")
        return moved
    if sub == b"DEVPROBE":
        # DEVPROBE <dev_index> (ISSUE 19) — one REAL tiny dispatch+readback
        # through the device's lane; both chaos chokepoints (occupancy
        # enter, readback) consult, so a still-faulted device stays
        # quarantined while a clean pass un-quarantines it.
        # Reply: [passed, quarantined] — tooling polls this for recovery.
        return _dev_probe(server, _int(args[1]))
    if sub == b"DEVEVACUATE":
        # DEVEVACUATE <dev_index> [DIR <journal_dir>] (ISSUE 19) — evacuate
        # every slot owned by <dev_index> onto the surviving non-quarantined
        # devices through the journaled device rebalance (kill-at-every-
        # phase resumable; keyed traffic on moving slots rides the existing
        # TRYAGAIN fence).  Reply: [moved_records, evacuated_slots, epoch]
        # (epoch -1 when unjournaled).
        from redisson_tpu.server import migration as mig

        if server.engine.placement is None:
            raise RespError("ERR placement is not enabled on this server")
        rest = list(args[1:])
        dev_index = _int(rest[0])
        journal_dir = None
        if len(rest) >= 3 and bytes(rest[1]).upper() == b"DIR":
            journal_dir = _s(rest[2])
        try:
            moved, targets, epoch = mig.evacuate_device(
                server.engine, dev_index, journal_dir=journal_dir
            )
        except ValueError as e:
            raise RespError(f"ERR {e}")
        return [moved, len(targets), -1 if epoch is None else epoch]
    if sub == b"MIGRATESLOTS":
        # MIGRATESLOTS [EPOCH <n>] <slot>... — drain MANY migrating slots
        # in one store scan (the orchestrator's bulk form: a reshard of
        # hundreds of slots must not pay a full keyspace scan per slot).
        # EPOCH fences every named slot like SETSLOT EPOCH does.
        rest = list(args[1:])
        epoch = None
        if rest and bytes(rest[0]).upper() == b"EPOCH":
            epoch = _int(rest[1])
            rest = rest[2:]
        slots = [_int(a) for a in rest]
        for s in slots:
            server.fence_slot_epoch(s, epoch)
        return server.migrate_slot_batch(slots)
    if sub == b"RESIDENCY":
        # Tiered-HBM residency plane (ISSUE 20), over the wire.
        #   CLUSTER RESIDENCY                      — the ledger table:
        #     [armed, budget_bytes,
        #      [b"DEV", dev, hot_bytes, warm_bytes, cold_bytes]...,
        #      [b"CTR", promotions, demotions_warm, demotions_cold,
        #       cold_loads, fault_in_ms_total, fault_in_ms_max]]
        #   CLUSTER RESIDENCY TIER <key>           — "hot"/"warm"/"cold"
        #   CLUSTER RESIDENCY DEMOTE <key> [COLD]  — force one demotion
        #   CLUSTER RESIDENCY SWEEP                — one on-demand sweep:
        #     [demoted, colded, freed_bytes]
        #   CLUSTER RESIDENCY SHED <dev> [COUNT n] [DIR d] — move up to n
        #     of <dev>'s slots onto the survivors through the journaled
        #     fenced device rebalance (the pressure-rebalancer's actuator):
        #     [records_moved, slots_moved]
        from redisson_tpu.core import residency as _res

        mgr = server.engine.residency
        if len(args) > 1:
            op = bytes(args[1]).upper()
            if op == b"TIER":
                if len(args) < 3:
                    raise RespError("ERR CLUSTER RESIDENCY TIER <key>")
                if mgr is None:
                    # disarmed plane: everything is HOT by construction
                    return _res.HOT.encode()
                t = mgr.tier_of(_s(args[2]))
                if t is None:
                    raise RespError("ERR no such key")
                return t.encode()
            if op == b"SHED":
                # a placement op, deliberately legal with the manager off —
                # an operator can pre-drain a device before arming tiers
                from redisson_tpu.server import migration as mig

                if server.engine.placement is None:
                    raise RespError(
                        "ERR placement is not enabled on this server"
                    )
                rest = list(args[2:])
                if not rest:
                    raise RespError(
                        "ERR CLUSTER RESIDENCY SHED <dev> [COUNT n] [DIR d]"
                    )
                dev_index = _int(rest[0])
                rest = rest[1:]
                count = 8
                journal_dir = None
                while rest:
                    word = bytes(rest[0]).upper()
                    if word == b"COUNT" and len(rest) >= 2:
                        count = _int(rest[1])
                        rest = rest[2:]
                    elif word == b"DIR" and len(rest) >= 2:
                        journal_dir = _s(rest[1])
                        rest = rest[2:]
                    else:
                        raise RespError(
                            "ERR CLUSTER RESIDENCY SHED <dev> "
                            "[COUNT n] [DIR d]"
                        )
                try:
                    targets = mig.shed_plan(
                        server.engine.placement, dev_index, count
                    )
                    moved = mig.rebalance_devices(
                        server.engine, targets, journal_dir=journal_dir
                    ) if targets else 0
                except ValueError as e:
                    raise RespError(f"ERR {e}")
                return [moved, len(targets)]
            if mgr is None:
                raise RespError(
                    "ERR residency plane is not enabled "
                    "(CONFIG SET residency-enabled yes)"
                )
            if op == b"DEMOTE":
                if len(args) < 3:
                    raise RespError(
                        "ERR CLUSTER RESIDENCY DEMOTE <key> [COLD]"
                    )
                cold = len(args) > 3 and bytes(args[3]).upper() == b"COLD"
                return 1 if mgr.demote(_s(args[2]), cold=cold,
                                       force=True) else 0
            if op == b"SWEEP":
                swept = mgr.sweep()
                return [swept["demoted"], swept["colded"],
                        int(swept["freed_bytes"])]
            raise RespError("ERR unknown CLUSTER RESIDENCY subcommand")
        armed = 1 if (mgr is not None and _res.tier_enabled()) else 0
        out = [armed, int(_res.DEVICE_BUDGET_BYTES)]
        if mgr is None:
            return out
        census = mgr.census()
        devs: dict = {}
        for k, v in census.items():
            if k.startswith("residency_bytes_dev"):
                num, _, tier = k[len("residency_bytes_dev"):].partition("_")
                devs.setdefault(int(num), {})[tier] = int(v)
        for d in sorted(devs):
            row = devs[d]
            out.append([b"DEV", d, row.get("hot", 0), row.get("warm", 0),
                        row.get("cold", 0)])
        out.append([
            b"CTR", mgr.promotions, mgr.demotions_warm, mgr.demotions_cold,
            mgr.cold_loads, f"{mgr.fault_in_ms_total:g}".encode(),
            f"{mgr.fault_in_ms_max:g}".encode(),
        ])
        return out
    raise RespError("ERR unknown CLUSTER subcommand")


def _dev_probe(server, dev_index: int):
    """One end-to-end probe dispatch on a device's lane (ISSUE 19): occupy
    the lane (the chaos kernel-launch chokepoint), run a trivial kernel on
    the device, read it back through ``ReadbackFuture`` (the hung-transfer /
    watchdog chokepoint).  Every fault path already attributes itself to the
    lane's quarantine ledger, so a failed probe only reports — it never
    double-counts.  A verified pass un-quarantines the lane."""
    from redisson_tpu.core import ioplane

    p = server.engine.placement
    lanes = server.engine.lanes
    if p is None or lanes is None:
        raise RespError("ERR placement is not enabled on this server")
    if not (0 <= dev_index < p.n_devices):
        raise RespError(f"ERR device index {dev_index} outside placement")
    device = p.devices[dev_index]
    lane = lanes.lane(device)
    try:
        with lane.occupy(1):
            import jax
            import jax.numpy as jnp

            val = jax.device_put(jnp.arange(8, dtype=jnp.int32), device) + 1
        out = ioplane.ReadbackFuture((val,)).result()
        import numpy as np

        # result() unwraps a single-output future to the array itself
        ok = int(np.asarray(out).sum()) == 36  # sum(1..8)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException:  # noqa: BLE001 — a failing probe is the answer
        return [0, int(lane.quarantined)]
    if ok:
        lane.unquarantine()
    return [1 if ok else 0, int(lane.quarantined)]


@register("ASKING")
def cmd_asking(server, ctx, args):
    """One-shot admission for the NEXT command on this connection into an
    IMPORTING slot (the redirect half of the ASK protocol)."""
    ctx.asking = True
    return "+OK"


def _tracking_invalidator(server):
    """apply_records on_applied hook: transfer frames (migration imports,
    replication pushes) mutate the keyspace exactly like writes, so tracked
    readers on this node must invalidate — the hole that would otherwise
    leave a near cache stale forever is a reader registered on the IMPORT
    side while the record's newer state arrives by drain, not by verb."""
    tracking = getattr(server, "tracking", None)
    if tracking is None or not tracking.active:
        return None
    return lambda names: tracking.note_write(list(names), None)


def _bank_resync(server, names) -> None:
    """Hydration-awareness seam (ISSUE 17, services/vector.py): a full-ship
    replaces a vector_bank record's arrays behind any service-level bank
    object bound to it — resync the bank's host mirror / row count so a
    later (e.g. post-promotion) query never scores a stale mirror."""
    services = getattr(server.engine, "_services", None)
    if not services or services.get("search") is None or not names:
        return
    try:
        from redisson_tpu.services.vector import sync_banks_from_records

        sync_banks_from_records(server.engine, names)
    except Exception:
        pass  # observability seam: never fail the apply


def _replica_on_applied(server):
    """Composite on_applied for replica-side apply_records: tracked readers
    invalidate (replica-side tracking tables stay coherent across the push
    stream) and service banks re-adopt externally installed records."""
    tracking_cb = _tracking_invalidator(server)

    def on_applied(names):
        if tracking_cb is not None:
            tracking_cb(names)
        _bank_resync(server, names)

    return on_applied


def _stamp_recorder(server):
    """apply_records on_payload hook: adopt the push's replication stamp
    (master sweep-cut offset + wall ts) AFTER the records applied — the
    bounded-staleness answer REPLSTATE gives must never run ahead of the
    state a replica read would actually see.  Receipt time is monotonic,
    so staleness_ms needs no cross-host clock agreement."""

    def on_payload(payload):
        off = payload.get("repl_offset")
        if off is None:
            return  # scoped cover-ship: carries records, not a sweep cut
        server.repl_applied_offset = int(off)
        server.repl_applied_ts = float(payload.get("repl_ts") or 0.0)
        server.repl_applied_at = time.monotonic()

    return on_payload


def _require_replica(server, verb: str) -> None:
    """Replication-stream verbs apply only on replicas (ISSUE 17 bugfix): a
    promoted master must NEVER apply a late push from its old master — the
    promoted hydrated plane would silently regress to pre-failover state.
    Rejecting here (instead of trusting the pusher to notice the
    promotion) closes the race between REPLICAOF NO ONE and the old
    master's next sweep; the rejected pusher marks the link unhealthy and
    stops treating this node as its replica."""
    if server.role != "replica":
        raise RespError(
            f"ERR {verb} rejected: node is a master (stale replication push)"
        )


@register("IMPORTRECORDS")
def cmd_importrecords(server, ctx, args):
    """IMPORTRECORDS [EPOCH <n> [SOURCE <addr>]] <blob> — install migrated
    records (slot-migration transfer frame; the blob carries records only —
    no live-list pruning, unlike REPLPUSH).

    With EPOCH (a journaled migration's fenced drain) and a configured
    journal dir, the batch is fsync'd into this node's
    :class:`~redisson_tpu.server.migration_journal.ImportJournal` BEFORE it
    is applied or acked — the source deletes only records this node has
    made durable, which closes the target-kill gap (ISSUE 13).  When
    replicas are attached, the applied records are additionally
    REPLPUSH-covered before the ack, so a dead target's promoted replica
    carries the in-flight import forward.

    A node started WITHOUT a journal dir accepts EPOCH frames but journals
    nothing — the pre-ISSUE-13 degraded mode, kept for the manual/legacy
    migration path.  The target-kill guarantee therefore requires the
    fleet to share a journal dir; the ClusterSupervisor enforces this by
    construction (``--journal-dir`` is passed to every node it spawns)."""
    from redisson_tpu.server import replication

    rest = list(args)
    epoch = source = None
    while len(rest) > 2:
        head = bytes(rest[0]).upper()
        if head == b"EPOCH":
            epoch = _int(rest[1])
        elif head == b"SOURCE":
            source = _s(rest[1])
        else:
            break
        rest = rest[2:]
    if len(rest) != 1:
        raise RespError("ERR IMPORTRECORDS [EPOCH n [SOURCE addr]] <blob>")
    blob = bytes(rest[0])
    if epoch is not None:
        # durability point FIRST: a SIGKILL after this line loses nothing
        # the source will delete (the reply below is what authorizes it)
        server.journal_import_batch(epoch, source, blob)
    applied_names: list = []
    tracking_cb = _tracking_invalidator(server)

    def on_applied(names):
        applied_names.extend(names)
        if tracking_cb is not None:
            tracking_cb(names)

    applied = replication.apply_records(
        server.engine, blob, on_applied=on_applied
    )
    repl = server._replication
    if epoch is not None and applied_names \
            and repl is not None and repl.replicas():
        # replica-covered target (journaled imports only — the legacy
        # epoch-less path never promised it): best-effort push of JUST the
        # applied records before the ack, so failover-by-promotion starts
        # from a caught-up replica (the journal remains the proof)
        repl.cover(applied_names)
    return applied


# -- replication (server/replication.py) -------------------------------------

def _promote_flush(server) -> None:
    """Promotion barrier (ISSUE 17 bugfix): the replica's hydrated device
    plane becomes MASTER state the instant the role flips, so everything
    that could let replica-stream staleness leak in afterwards is cut
    here — half-assembled segmented pushes are dropped (their remaining
    segments are role-gate rejected anyway), tracked readers invalidate
    across the live keyspace (their entries were registered against
    replica-served values and must refetch under the promoted epoch), the
    staleness clock resets (a master is authoritative, never 'stale'), and
    service-level banks re-adopt their records under the promoted role."""
    with server._repl_xfers_lock:
        server._repl_xfers.clear()
    server.repl_applied_at = None
    names = list(server.engine.store.keys())
    cb = _tracking_invalidator(server)
    if cb is not None and names:
        try:
            cb(names)
        except Exception:
            pass
    _bank_resync(server, names)
    server.stats["promotions"] = server.stats.get("promotions", 0) + 1


@register("REPLICAOF")
def cmd_replicaof(server, ctx, args):
    """REPLICAOF NO ONE -> become master; REPLICAOF <host> <port> -> full
    sync from master, then register for the push stream."""
    if len(args) == 2 and bytes(args[0]).upper() == b"NO" and bytes(args[1]).upper() == b"ONE":
        promoted = server.role == "replica"
        if promoted and server.master_address:
            # breadcrumb for successor coordinators: an orphaned master that
            # can name the dead master it was promoted FROM is a
            # half-finished failover; a restarted stale master cannot
            server.promoted_from = server.master_address
        # role flips FIRST: from here every in-flight/late push from the old
        # master is rejected by _require_replica, THEN the promotion barrier
        # scrubs what the replica stream staged (ISSUE 17 bugfix)
        server.role = "master"
        server.master_address = None
        if promoted:
            _promote_flush(server)
        return "+OK"
    if len(args) != 2:
        raise RespError("ERR REPLICAOF <host> <port> | NO ONE")
    host, port = _s(args[0]), _int(args[1])
    from redisson_tpu.server import replication

    # nodes of one grid share credentials AND transport security: the link
    # authenticates with this node's own password and speaks TLS when this
    # node does (cluster-wide convention; server.link_client), with
    # profile-driven cadence (net/retry: lan = legacy single-shot link)
    from redisson_tpu.net.retry import replica_link_kwargs

    master = server.link_client(f"{host}:{port}", **replica_link_kwargs())
    try:
        # resumable chunked pull (ISSUE 16): a dropped link resumes at the
        # offset it reached; the blob is CRC-gated before it can apply
        blob = replication.pull_snapshot(master, timeout=60.0)
        replication.apply_records(
            server.engine, blob,
            on_applied=_tracking_invalidator(server),
        )
        # register by the address this node is KNOWN BY (advertise split):
        # the master's push link must reach a routable address, not a
        # 0.0.0.0 bind
        master.execute("REPLREGISTER", server.public_host, server.port)
    finally:
        master.close()
    server.role = "replica"
    server.master_address = f"{host}:{port}"
    # stale stamps from a PREVIOUS master's stream must not answer fresh:
    # the staleness clock restarts at the new master's first push/heartbeat
    server.repl_applied_at = None
    return "+OK"


def _reap_stale_snaps(server, now: float, keep: str = "") -> None:
    """Drop staged snapshot cuts untouched past the stale window (caller
    holds server._snap_lock) — the same discipline as _reap_stale_xfers:
    a replica that died mid-pull must not pin its cut forever."""
    stages = server._snap_stages
    from redisson_tpu.server.replication import SNAP_STAGE_STALE_S

    for k in [k for k, (_b, _c, ts) in stages.items()
              if k != keep and now - ts > SNAP_STAGE_STALE_S]:
        del stages[k]


@register("REPLSNAPSHOT")
def cmd_replsnapshot(server, ctx, args):
    """Bare REPLSNAPSHOT -> the full serialized cut (legacy one-ship path).

    Subcommands (ISSUE 16, resumable full-sync — replication.pull_snapshot
    is the client half):

      * ``BEGIN [CHUNK n]`` — serialize ONE immutable cut, stage it, reply
        ``[xfer_id, total_bytes, crc32, chunk_bytes]``;
      * ``FETCH <id> <offset>`` — the staged bytes at ``offset`` (up to the
        stage's chunk size); an unknown/reaped id answers ``SNAPEXPIRED``
        so the puller restarts from a fresh BEGIN instead of assembling a
        mixed-cut blob;
      * ``END <id>`` — release the stage (idempotent)."""
    from redisson_tpu.server import replication

    if not args:
        blob, _shipped = replication.serialize_records(server.engine)
        return blob
    sub = bytes(args[0]).upper()
    now = time.monotonic()
    if sub == b"BEGIN":
        chunk = replication.SNAPSHOT_CHUNK_BYTES
        if len(args) >= 3 and bytes(args[1]).upper() == b"CHUNK":
            chunk = max(1, _int(args[2]))
        import zlib

        blob, _shipped = replication.serialize_records(server.engine)
        with server._snap_lock:
            _reap_stale_snaps(server, now)
            while len(server._snap_stages) >= replication.SNAP_STAGE_MAX:
                # backstop only: drop the least-recently-touched stage
                stages = server._snap_stages
                del stages[min(stages, key=lambda k: stages[k][2])]
            server._snap_seq += 1
            xfer_id = f"snap-{server.node_id[:8]}-{server._snap_seq}"
            server._snap_stages[xfer_id] = [blob, chunk, now]
        return [xfer_id, len(blob), zlib.crc32(blob), chunk]
    if sub == b"FETCH":
        xfer_id, offset = _s(args[1]), _int(args[2])
        with server._snap_lock:
            _reap_stale_snaps(server, now, keep=xfer_id)
            entry = server._snap_stages.get(xfer_id)
            if entry is None:
                raise RespError(
                    f"SNAPEXPIRED unknown snapshot transfer {xfer_id}"
                )
            blob, chunk, _ts = entry
            entry[2] = now
        if not (0 <= offset <= len(blob)):
            raise RespError(
                f"ERR snapshot offset {offset} outside 0..{len(blob)}"
            )
        return blob[offset:offset + chunk]
    if sub == b"END":
        with server._snap_lock:
            server._snap_stages.pop(_s(args[1]), None)
        return "+OK"
    raise RespError(
        "ERR REPLSNAPSHOT [BEGIN [CHUNK n] | FETCH <id> <offset> | END <id>]"
    )


@register("REPLREGISTER")
def cmd_replregister(server, ctx, args):
    host, port = _s(args[0]), _int(args[1])
    server.replication_source().register(f"{host}:{port}")
    return "+OK"


@register("REPLPUSH")
def cmd_replpush(server, ctx, args):
    from redisson_tpu.server import replication

    _require_replica(server, "REPLPUSH")
    # any live push proves the link is back: reap transfers its dead
    # predecessor abandoned mid-segment (a restarted master full-ships via
    # plain REPLPUSH, so seg-only sweeping would never fire here)
    with server._repl_xfers_lock:
        _reap_stale_xfers(server, time.monotonic())
    return replication.apply_records(
        server.engine, bytes(args[0]),
        on_applied=_replica_on_applied(server),
        on_payload=_stamp_recorder(server),
    )


# staging eviction knobs (cmd_replpushseg): a transfer untouched for
# REPL_XFER_STALE_S is abandoned (its pusher's per-segment timeout is 60s,
# so 120s of silence means the source died mid-transfer); REPL_XFER_MAX is
# the hard leak backstop — far above any sane concurrent-transfer count, so
# in-progress transfers are never spuriously dropped (ADVICE r5 low: the
# old keep-at-most-4-by-insertion-order cap dropped concurrent live ones).
REPL_XFER_STALE_S = 120.0
REPL_XFER_MAX = 64


def _reap_stale_xfers(server, now: float, keep: str = "") -> None:
    """Drop staged transfers untouched past the stale window.  Caller holds
    server._repl_xfers_lock.  Runs on EVERY replication push — not just a
    new transfer's first slice — so an abandoned transfer cannot linger
    (and read as a phantom leak in the resource census) just because no
    later segmented ship ever starts."""
    xfers = server._repl_xfers
    for k in [k for k, (_slots, ts) in xfers.items()
              if k != keep and now - ts > REPL_XFER_STALE_S]:
        del xfers[k]


@register("REPLPUSHSEG")
def cmd_replpushseg(server, ctx, args):
    """REPLPUSHSEG <xfer_id> <seq> <nsegs> <chunk> — one bounded slice of an
    oversized REPLPUSH blob (a 10M-key bloom plane is ~95MB; a single
    sendall of that stalls past socket timeouts, server/replication.py
    SEGMENT_BYTES).  The final slice reassembles and applies the blob;
    intermediates stage host-side and answer +OK.  Staging evicts by
    per-transfer staleness (last-touch timestamp), never insertion order."""
    from redisson_tpu.server import replication

    _require_replica(server, "REPLPUSHSEG")
    xfer_id, seq, nsegs = _s(args[0]), _int(args[1]), _int(args[2])
    chunk = bytes(args[3])
    now = time.monotonic()
    xfers = server._repl_xfers
    with server._repl_xfers_lock:
        _reap_stale_xfers(server, now, keep=xfer_id)
        if seq == 0:
            while len(xfers) >= REPL_XFER_MAX:
                # backstop only: drop the least-recently-touched transfer
                del xfers[min(xfers, key=lambda k: xfers[k][1])]
            xfers[xfer_id] = [[None] * nsegs, now]
        entry = xfers.get(xfer_id)
        if entry is None or len(entry[0]) != nsegs or not (0 <= seq < nsegs):
            raise RespError(f"ERR unknown replication transfer {xfer_id}/{seq}")
        entry[0][seq] = chunk
        entry[1] = now
        if any(s is None for s in entry[0]):
            return "+OK"
        del xfers[xfer_id]
        blob = b"".join(entry[0])
    return replication.apply_records(
        server.engine, blob,
        on_applied=_replica_on_applied(server),
        on_payload=_stamp_recorder(server),
    )


@register("REPLPING")
def cmd_replping(server, ctx, args):
    """REPLPING <offset> <ts> — master heartbeat on a clean sweep cut: the
    replica's applied offset advances without any payload, so bounded-
    staleness replica reads stay eligible while the keyspace is idle
    (otherwise an idle master would starve every MAXSTALE bound)."""
    _require_replica(server, "REPLPING")
    server.repl_applied_offset = _int(args[0])
    try:
        server.repl_applied_ts = float(_s(args[1]))
    except (ValueError, IndexError):
        server.repl_applied_ts = 0.0
    server.repl_applied_at = time.monotonic()
    return "+OK"


@register("REPLSTATE")
def cmd_replstate(server, ctx, args):
    """REPLSTATE [MAXSTALE <ms>] -> [role, applied_offset, staleness_ms,
    view_epoch] — the bounded-staleness contract's server half (ISSUE 17).

    staleness_ms is measured from the monotonic RECEIPT of the last applied
    push/heartbeat, so it needs no cross-host clock agreement; -1 means the
    replica has never synced (always too stale).  A master answers 0 — it
    is never stale with respect to itself.  The MAXSTALE form replies the
    same shape and additionally counts replica_redirects_stale when the
    answer exceeds the client's bound: the client pipelines REPLSTATE
    MAXSTALE ahead of its read and redirects to the master on the reply."""
    max_stale = None
    if args:
        if len(args) == 2 and bytes(args[0]).upper() == b"MAXSTALE":
            max_stale = _int(args[1])
        else:
            raise RespError("ERR REPLSTATE [MAXSTALE <ms>]")
    if server.role != "replica":
        stale_ms = 0
    elif server.repl_applied_at is None:
        stale_ms = -1
    else:
        stale_ms = int((time.monotonic() - server.repl_applied_at) * 1000.0)
    if max_stale is not None and server.role == "replica" \
            and (stale_ms < 0 or stale_ms > max_stale):
        server.stats["replica_redirects_stale"] += 1
    return [
        server.role.encode(),
        int(server.repl_applied_offset),
        stale_ms,
        int(server.view_epoch),
    ]


@register("REPLFLUSH")
def cmd_replflush(server, ctx, args):
    """Ship dirty records to all replicas NOW (WAIT / syncSlaves analog)."""
    if server._replication is None:
        return 0
    return server._replication.flush()


@register("ROLE")
def cmd_role(server, ctx, args):
    """Redis ROLE parity: master -> ["master", 0, [replica addrs]];
    replica -> ["slave", host, port, "connected", 0].  Failover
    coordinators probe this to DISCOVER a dead master's replicas when they
    started after the death (a successor coordinator has no poll history)."""
    if server.role == "replica" and server.master_address:
        host, _, port = server.master_address.rpartition(":")
        return [b"slave", host.encode(), int(port), b"connected", 0]
    reps = []
    if server._replication is not None:
        reps = [a.encode() for a in server._replication.replicas()]
    promoted_from = getattr(server, "promoted_from", None)
    # 4th element is our extension past Redis ROLE: the address this master
    # was promoted FROM (empty when it never was a replica) — coordinators
    # use it to adopt half-finished failovers without mistaking a restarted
    # stale master for one
    return [b"master", 0, reps, (promoted_from or "").encode()]


@register("REPLICAS")
def cmd_replicas(server, ctx, args):
    if server._replication is None:
        return []
    return [a.encode() for a in server._replication.replicas()]


@register("METRICS")
def cmd_metrics(server, ctx, args):
    """Prometheus text exposition of the node's metrics registry.

    ``METRICS CLUSTER`` (ISSUE 12): fan the scrape out to every master in
    this node's cluster view and merge the expositions with per-node
    ``node="host:port"`` labels — the wire half of the fleet-wide
    one-pane-of-glass (``ClusterSupervisor.scrape()`` is the supervisor
    half; both ride ``utils.metrics.merge_prometheus_texts``).  A dead
    peer contributes nothing rather than failing the whole scrape."""
    if args and bytes(args[0]).upper() == b"CLUSTER":
        from redisson_tpu.utils.metrics import merge_prometheus_texts

        texts = {server.address(): server.metrics.prometheus_text()}
        seen = {(server.host, server.port)}
        for _lo, _hi, host, port, _nid in server.cluster_view:
            if (host, port) in seen:
                continue
            seen.add((host, port))
            try:
                link = server.link_client(
                    f"{host}:{port}", ping_interval=0, retry_attempts=1
                )
                try:
                    texts[f"{host}:{port}"] = bytes(
                        link.execute("METRICS", timeout=10.0)
                    ).decode()
                finally:
                    link.close()
            except Exception:  # noqa: BLE001 — dead peer: scrape the rest
                continue
        return merge_prometheus_texts(texts).encode()
    return server.metrics.prometheus_text().encode()


# -- tracing plane verbs (ISSUE 12: TRACE / SLOWLOG / LATENCY) ----------------


def _span_wire(span) -> list:
    """One stage span on the wire: [name, off_us, dur_us, [k, v, ...]]."""
    attrs = []
    if span.attrs:
        for k, v in span.attrs.items():
            attrs.append(k.encode())
            attrs.append(v if isinstance(v, int) else str(v).encode())
    return [span.name.encode(), span.off_us, span.dur_us, attrs]


def _trace_wire(tr) -> list:
    """One frame trace on the wire: [id, unix_ms, total_us, verb, n_cmds,
    class, tenant, [span, ...]] — tools/trace_dump.py renders this as a
    per-stage waterfall."""
    return [
        tr.trace_id, int(tr.ts * 1000), tr.total_us, tr.verbs.encode(),
        tr.n_cmds, (tr.qos_class or "").encode(), (tr.tenant or "").encode(),
        [_span_wire(s) for s in tr.spans],
    ]


@register("TRACE")
def cmd_trace(server, ctx, args):
    """TRACE GET [n] [BY total|<stage>] | RESET | CONFIG GET|SET k v —
    the per-frame span ring over the wire.  GET returns the slowest-n
    finished traces ordered by total duration (or by one stage's summed
    duration: BY qos / readback / dispatch / ...), each a full span tree.
    Empty while tracing is disarmed (CONFIG SET trace-enabled yes arms)."""
    sub = bytes(args[0]).upper() if args else b"GET"
    tracer = server.tracer
    if sub == b"GET":
        rest = list(args[1:])
        n = 10
        by = "total"
        if rest and bytes(rest[0]).upper() != b"BY":
            n = _int(rest[0])
            rest = rest[1:]
        if rest and bytes(rest[0]).upper() == b"BY":
            if len(rest) < 2:
                raise RespError("ERR TRACE GET ... BY needs a stage name")
            by = _s(rest[1])
        return [_trace_wire(t) for t in tracer.slowest(n, by=by)]
    if sub == b"RESET":
        tracer.reset()
        return "+OK"
    if sub == b"CONFIG":
        mode = bytes(args[1]).upper() if len(args) > 1 else b"GET"
        if mode == b"GET":
            out = []
            view = server.config_view()
            for k in ("trace-enabled", "trace-ring-capacity",
                      "slowlog-log-slower-than", "slowlog-max-len"):
                out += [k.encode(), str(view[k]).encode()]
            return out
        if mode == b"SET":
            if len(args) < 4:
                raise RespError("ERR TRACE CONFIG SET <key> <value>")
            if not server.config_set(_s(args[2]), _s(args[3])):
                raise RespError(
                    f"ERR unknown TRACE CONFIG parameter '{_s(args[2])}'"
                )
            return "+OK"
        raise RespError("ERR TRACE CONFIG expects GET|SET")
    raise RespError("ERR TRACE expects GET|RESET|CONFIG")


@register("SLOWLOG")
def cmd_slowlog(server, ctx, args):
    """SLOWLOG GET [n] | RESET | LEN — Redis parity verbs backed by the
    trace ring (threshold: CONFIG SET slowlog-log-slower-than <µs>,
    negative disables, 0 logs everything).  Each entry carries the
    per-stage breakdown instead of Redis's flat duration:
    [id, unix_ts, total_us, [verb, ncmds], [[stage, dur_us], ...]]."""
    sub = bytes(args[0]).upper() if args else b"GET"
    tracer = server.tracer
    if sub == b"GET":
        n = _int(args[1]) if len(args) > 1 else 10
        out = []
        for sid, ts, dur_us, tr, stages in tracer.slowlog_get(n):
            out.append([
                sid, ts, dur_us,
                [tr.verbs.encode(), str(tr.n_cmds).encode()],
                [[st.encode(), us] for st, us in sorted(stages.items())],
            ])
        return out
    if sub == b"LEN":
        return tracer.slowlog_len()
    if sub == b"RESET":
        tracer.slowlog_reset()
        return "+OK"
    raise RespError("ERR SLOWLOG expects GET|RESET|LEN")


@register("LATENCY")
def cmd_latency(server, ctx, args):
    """LATENCY HISTORY <event> | RESET [event ...] | LATEST — Redis parity
    over the per-STAGE samples the tracer collects (events are stage names:
    total, qos, dispatch, stage, kernel, readback, reply)."""
    sub = bytes(args[0]).upper() if args else b""
    tracer = server.tracer
    if sub == b"HISTORY":
        if len(args) < 2:
            raise RespError("ERR LATENCY HISTORY <event>")
        return [
            # (unix ts, MILLISECONDS) pairs — the Redis LATENCY contract;
            # sub-ms durations round up to 1 so a recorded sample is never
            # indistinguishable from "no latency"
            [ts, max(1, int(round(ms)))]
            for ts, ms in tracer.latency_history(_s(args[1]))
        ]
    if sub == b"RESET":
        return tracer.latency_reset([_s(a) for a in args[1:]])
    if sub == b"LATEST":
        out = []
        for ev in tracer.latency_events():
            hist = tracer.latency_history(ev)
            if not hist:
                continue
            ts, ms = hist[-1]
            worst = max(m for _t, m in hist)
            out.append([
                ev.encode(), ts,
                max(1, int(round(ms))), max(1, int(round(worst))),
            ])
        return out
    raise RespError("ERR LATENCY expects HISTORY|RESET|LATEST")


# -- checkpoint (SAVE analog; full impl in core/checkpoint.py) ---------------

@register("SAVE")
def cmd_save(server, ctx, args):
    path = _s(args[0]) if args else server.checkpoint_path
    if path is None:
        raise RespError("ERR no checkpoint path configured")
    from redisson_tpu.core import checkpoint

    checkpoint.save(server.engine, path)
    return "+OK"


@register("BGSAVE")
def cmd_bgsave(server, ctx, args):
    """Checkpoint in the background (the RDB BGSAVE role); LASTSAVE reports
    the completion time of the most recent one."""
    path = _s(args[0]) if args else server.checkpoint_path
    if path is None:
        raise RespError("ERR no checkpoint path configured")
    from redisson_tpu.core import checkpoint

    def run():
        try:
            checkpoint.save(server.engine, path)
            server.__dict__["_lastsave"] = int(time.time())
        except Exception:  # noqa: BLE001 — background save: best-effort
            pass

    threading.Thread(target=run, daemon=True, name="rtpu-bgsave").start()
    return "+Background saving started"


@register("BGREWRITEAOF")
def cmd_bgrewriteaof(server, ctx, args):
    """No AOF exists: durability is checkpoint + replication, so the rewrite
    request degrades to a background checkpoint (documented in PARITY.md)."""
    cmd_bgsave(server, ctx, args)
    return "+Background append only file rewriting started"


@register("LASTSAVE")
def cmd_lastsave(server, ctx, args):
    return int(server.__dict__.get("_lastsave", 0))


@register("SHUTDOWN")
def cmd_shutdown(server, ctx, args):
    """SHUTDOWN [NOSAVE|SAVE]: optionally checkpoint, then stop the server.
    Like Redis, a successful shutdown never delivers a reply — the
    connection just dies; the stop runs on a side thread so this handler's
    worker can finish its frame."""
    mode = bytes(args[0]).upper() if args else b""
    if mode == b"SAVE" and not server.checkpoint_path:
        raise RespError("ERR no checkpoint path configured")
    if mode == b"SAVE" or (mode != b"NOSAVE" and server.checkpoint_path):
        from redisson_tpu.core import checkpoint

        try:
            checkpoint.save(server.engine, server.checkpoint_path)
            server.__dict__["_lastsave"] = int(time.time())
        except Exception as e:  # noqa: BLE001 — like Redis: a failed final
            # save ABORTS the shutdown (data would be lost silently)
            raise RespError(f"ERR shutdown save failed, aborting: {e}")
    threading.Thread(target=server.stop, daemon=True, name="rtpu-shutdown").start()
    return "+OK"


@register("RESTORESTATE")
def cmd_restorestate(server, ctx, args):
    path = _s(args[0]) if args else server.checkpoint_path
    if path is None:
        raise RespError("ERR no checkpoint path configured")
    from redisson_tpu.core import checkpoint

    n = checkpoint.load(server.engine, path)
    return n


# -- script / function / admin verbs (RScript + RFunction wire surface) ------

def _script_svc(server):
    from redisson_tpu.services.script import ScriptService

    return server.engine.service("script", lambda: ScriptService(server.engine))


def _function_svc(server):
    from redisson_tpu.services.script import FunctionService

    return server.engine.service("function", lambda: FunctionService(server.engine))


def _proc_keys_args(args, at):
    """numkeys keys... args... tail shared by EVALSHA/FCALL."""
    n = _int(args[at])
    if n < 0:
        raise RespError("ERR Number of keys can't be negative")
    if len(args) < at + 1 + n:
        raise RespError("ERR Number of keys is greater than number of args")
    keys = [_s(k) for k in args[at + 1 : at + 1 + n]]
    rest = [bytes(a) for a in args[at + 1 + n :]]
    return keys, rest


@register("EVALSHA")
def cmd_evalsha(server, ctx, args):
    """EVALSHA sha numkeys key... arg... — invokes a script REGISTERED
    SERVER-SIDE (embedded script_load).  Scripts here are Python callables,
    so source never ships over the wire: remote callers address by digest
    only, and a miss replies NOSCRIPT exactly like the reference's
    EVAL-fallback discipline expects."""
    from redisson_tpu.services.script import NoScriptError

    keys, rest = _proc_keys_args(args, 1)
    try:
        return _script_svc(server).eval_sha(_s(args[0]), keys, rest)
    except NoScriptError:
        raise RespError("NOSCRIPT No matching script. Please use EVAL.")


@register("EVAL")
def cmd_eval(server, ctx, args):
    raise RespError(
        "ERR EVAL with shipped source is not supported on this server: "
        "scripts are Python callables registered server-side (script_load); "
        "invoke by digest with EVALSHA, or FCALL a loaded function library"
    )


@register("SCRIPT")
def cmd_script(server, ctx, args):
    sub = bytes(args[0]).upper()
    svc = _script_svc(server)
    if sub == b"EXISTS":
        return [1 if ok else 0 for ok in svc.script_exists(*[_s(s) for s in args[1:]])]
    if sub == b"FLUSH":
        svc.script_flush()
        return "+OK"
    if sub == b"LOAD":
        raise RespError(
            "ERR SCRIPT LOAD over the wire is not supported (scripts are "
            "Python callables; register them server-side)"
        )
    raise RespError(f"ERR Unknown SCRIPT subcommand '{_s(args[0])}'")


def _fcall(server, args, read_only: bool):
    keys, rest = _proc_keys_args(args, 1)
    svc = _function_svc(server)
    # resolve OUTSIDE the invocation: a KeyError raised by the function's
    # own body must surface as the function's error, not "not found"
    try:
        fn = svc._resolve(_s(args[0]))
    except KeyError:
        raise RespError(f"ERR Function not found: {_s(args[0])}")
    from redisson_tpu.services.script import ScriptMode

    mode = ScriptMode.READ_ONLY if read_only else ScriptMode.READ_WRITE
    return svc._script.eval(fn, keys, rest, mode)


@register("FCALL")
def cmd_fcall(server, ctx, args):
    return _fcall(server, args, read_only=False)


@register("FCALL_RO")
def cmd_fcall_ro(server, ctx, args):
    return _fcall(server, args, read_only=True)


@register("FUNCTION")
def cmd_function(server, ctx, args):
    sub = bytes(args[0]).upper()
    if sub == b"LIST":
        out = []
        for lib, fns in sorted(_function_svc(server).list().items()):
            out.append([
                b"library_name", lib.encode(),
                b"functions", [f.encode() for f in fns],
            ])
        return out
    if sub == b"DUMP" or sub == b"LOAD":
        raise RespError(
            "ERR FUNCTION libraries are Python callables registered "
            "server-side; wire DUMP/LOAD is not supported"
        )
    raise RespError(f"ERR Unknown FUNCTION subcommand '{_s(args[0])}'")


@register("WAIT")
def cmd_wait(server, ctx, args):
    """WAIT numreplicas timeout(ms): flush dirty records to replicas now and
    report how many replicas are attached (record-level async replication:
    a returned count >= numreplicas means the flush was SHIPPED to that
    many replicas — the syncSlaves/REPLFLUSH semantics)."""
    import time as _t

    if len(args) < 2:
        raise RespError("ERR wrong number of arguments for 'wait' command")
    want = _int(args[0])
    timeout_ms = _int(args[1])
    if timeout_ms < 0:
        raise RespError("ERR timeout is negative")
    # Redis WAIT timeout 0 = block until the replica count is reached
    # (same convention as _block_loop's timeout<=0)
    deadline = None if timeout_ms == 0 else _t.time() + timeout_ms / 1000.0
    while True:
        n = 0
        if server._replication is not None:
            server._replication.flush()
            n = len(server._replication.replicas())
        if (
            n >= want
            or (deadline is not None and _t.time() >= deadline)
            or getattr(server, "_closing", False)
            or getattr(_exec_tls, "in_exec", False)  # no parking inside EXEC
        ):
            return n
        _t.sleep(0.02)  # parked, not spinning: this holds a pool worker


@register("CONFIG")
def cmd_config(server, ctx, args):
    """CONFIG GET pattern | CONFIG SET key value — the RedisNode.setConfig
    admin surface over the server's live knob table."""
    sub = bytes(args[0]).upper()
    if sub == b"GET":
        pattern = _s(args[1]) if len(args) > 1 else "*"
        out = []
        for k, v in sorted(server.config_view().items()):
            if _glob_match(pattern, k):
                out += [k.encode(), str(v).encode()]
        return out
    if sub == b"SET":
        if not server.config_set(_s(args[1]), _s(args[2])):
            raise RespError(f"ERR Unknown or read-only CONFIG parameter '{_s(args[1])}'")
        return "+OK"
    raise RespError(f"ERR Unknown CONFIG subcommand '{_s(args[0])}'")


def _bmpop_prelude(args):
    """Shared BLMPOP/BZMPOP validation: timeout + numkeys BEFORE any
    delegation, so malformed input replies a syntax error, never ERR
    internal."""
    import math as _math

    if len(args) < 4:
        raise RespError("ERR wrong number of arguments")
    try:
        timeout = float(args[0])
    except (TypeError, ValueError):
        raise RespError("ERR timeout is not a float or out of range")
    if not _math.isfinite(timeout) or timeout < 0:
        # NaN would make every deadline comparison False: park forever
        raise RespError("ERR timeout is not a float or out of range")
    rest = args[1:]
    n = _int(rest[0])
    if n <= 0:
        raise RespError("ERR numkeys should be greater than 0")
    if len(rest) < 1 + n + 1:
        raise RespError("ERR Number of keys is greater than number of args")
    return timeout, rest, _s(rest[1])


@register("BLMPOP")
def cmd_blmpop(server, ctx, args):
    """BLMPOP timeout numkeys key... LEFT|RIGHT [COUNT n]."""
    timeout, rest, first_key = _bmpop_prelude(args)

    def poll_once():
        return cmd_lmpop(server, ctx, rest)

    return _block_loop(server, first_key, poll_once, timeout)


@register("BZMPOP")
def cmd_bzmpop(server, ctx, args):
    """BZMPOP timeout numkeys key... MIN|MAX [COUNT n]."""
    timeout, rest, first_key = _bmpop_prelude(args)

    def poll_once():
        return cmd_zmpop(server, ctx, rest)

    return _block_loop(server, first_key, poll_once, timeout)


@register("DUMP")
def cmd_dump(server, ctx, args):
    """DUMP key — the portable record blob (core/checkpoint.dump_record;
    wire names are stored keys, so no handle/NameMapper indirection)."""
    from redisson_tpu.core import checkpoint

    try:
        return checkpoint.dump_record(server.engine, _s(args[0]))
    except KeyError:
        return None  # missing key dumps nil


@register("RESTORE")
def cmd_restore(server, ctx, args):
    """RESTORE key ttl(ms) blob [REPLACE] — BUSYKEY unless REPLACE."""
    from redisson_tpu.core import checkpoint

    name = _s(args[0])
    ttl_ms = _int(args[1])
    if ttl_ms < 0:
        raise RespError("ERR Invalid TTL value, must be >= 0")
    opts = {bytes(a).upper() for a in args[3:]}
    if opts - {b"REPLACE", b"PERSIST"}:
        raise RespError("ERR syntax error")
    try:
        # Redis semantics: ttl 0 == no expiry.  RObject.migrate ships the
        # remaining TTL as this explicit operand; the blob-carried TTL only
        # applies to direct restore_record calls (checkpoint files).
        checkpoint.restore_record(
            server.engine, name, bytes(args[2]),
            ttl_ms / 1000.0 if ttl_ms > 0 else None,
            b"REPLACE" in opts, persist=b"PERSIST" in opts or ttl_ms == 0,
        )
    except ValueError as e:
        msg = str(e)
        raise RespError(msg if msg.startswith("BUSYKEY") else f"ERR {msg}")
    return "+OK"
