"""Sorted sets: ZADD family, lex ranges, combination reads, range stores (RedissonScoredSortedSet wire surface).

Split from server/registry.py (round 5, no behavior change): one module per
verb family, shared preludes in verbs/common.py so numkeys/syntax validation
cannot diverge between families again.
"""

from typing import Dict

from redisson_tpu.net.resp import RespError
from redisson_tpu.server.registry import register, _s, _int
from redisson_tpu.server.verbs.collections import _set
from redisson_tpu.server.verbs.common import (
    _bitset,
    _deque,
    _fnum,
    _glob_match,
    _scan_opts,
    _scan_page,
    _znumkeys,
    _zset,
)

# -- typed surface expansion (sorted sets) -----------------------------------


def _zbound(raw: bytes):
    """Parse a ZRANGEBYSCORE bound: -inf/+inf, (exclusive, or inclusive."""
    s = bytes(raw)
    inc = True
    if s.startswith(b"("):
        inc = False
        s = s[1:]
    if s in (b"-inf", b"+inf", b"inf"):
        return (float("-inf") if s == b"-inf" else float("inf")), inc
    return float(s), inc


@register("ZCOUNT")
def cmd_zcount(server, ctx, args):
    lo, lo_inc = _zbound(args[1])
    hi, hi_inc = _zbound(args[2])
    return _zset(server, _s(args[0])).count(lo, lo_inc, hi, hi_inc)


def _zrangebyscore(server, args, reverse: bool):
    z = _zset(server, _s(args[0]))
    if reverse:  # ZREVRANGEBYSCORE takes max first
        hi, hi_inc = _zbound(args[1])
        lo, lo_inc = _zbound(args[2])
    else:
        lo, lo_inc = _zbound(args[1])
        hi, hi_inc = _zbound(args[2])
    withscores = False
    offset, limit = 0, None
    i = 3
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"WITHSCORES":
            withscores = True
            i += 1
        elif opt == b"LIMIT":
            offset, limit = _int(args[i + 1]), _int(args[i + 2])
            i += 3
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    from redisson_tpu.client.objects.scoredsortedset import _in_score

    entries = [
        (m, sc)
        for m, sc in z.entry_range(0, -1)
        if _in_score(sc, lo, lo_inc, hi, hi_inc)
    ]
    if reverse:
        entries.reverse()
    if limit is not None and limit >= 0:
        entries = entries[offset : offset + limit]
    elif offset:
        entries = entries[offset:]
    out = []
    for m, sc in entries:
        out += [m, _fnum(sc)] if withscores else [m]
    return out


@register("ZRANGEBYSCORE")
def cmd_zrangebyscore(server, ctx, args):
    return _zrangebyscore(server, args, reverse=False)


@register("ZREVRANGEBYSCORE")
def cmd_zrevrangebyscore(server, ctx, args):
    return _zrangebyscore(server, args, reverse=True)


@register("ZREVRANGE")
def cmd_zrevrange(server, ctx, args):
    z = _zset(server, _s(args[0]))
    withscores = len(args) > 3 and bytes(args[3]).upper() == b"WITHSCORES"
    entries = z.entry_range(0, -1)
    entries.reverse()
    from redisson_tpu.client.objects.scoredsortedset import _norm_range

    lo, hi = _norm_range(_int(args[1]), _int(args[2]), len(entries))
    entries = entries[lo : hi + 1] if hi >= lo else []
    out = []
    for m, sc in entries:
        out += [m, _fnum(sc)] if withscores else [m]
    return out


@register("ZREVRANK")
def cmd_zrevrank(server, ctx, args):
    return _zset(server, _s(args[0])).rev_rank(bytes(args[1]))


def _zpop(server, args, first: bool):
    z = _zset(server, _s(args[0]))
    n = _int(args[1]) if len(args) > 1 else 1
    out = []
    for _ in range(n):
        entry = z.poll_first_entry() if first else z.poll_last_entry()
        if entry is None:
            break
        m, sc = entry
        out += [m, _fnum(sc)]
    return out


@register("ZPOPMIN")
def cmd_zpopmin(server, ctx, args):
    return _zpop(server, args, first=True)


@register("ZPOPMAX")
def cmd_zpopmax(server, ctx, args):
    return _zpop(server, args, first=False)


@register("ZMSCORE")
def cmd_zmscore(server, ctx, args):
    z = _zset(server, _s(args[0]))
    out = []
    for m in args[1:]:
        sc = z.get_score(bytes(m))
        out.append(None if sc is None else float(sc))
    return out


@register("ZRANDMEMBER")
def cmd_zrandmember(server, ctx, args):
    import random

    z = _zset(server, _s(args[0]))
    entries = z.entry_range(0, -1)
    if len(args) == 1:
        return random.choice(entries)[0] if entries else None
    n = _int(args[1])
    withscores = len(args) > 2 and bytes(args[2]).upper() == b"WITHSCORES"
    if n >= 0:
        picked = random.sample(entries, min(n, len(entries)))
    else:
        picked = [random.choice(entries) for _ in range(-n)] if entries else []
    out = []
    for m, sc in picked:
        out += [m, _fnum(sc)] if withscores else [m]
    return out


@register("ZREMRANGEBYSCORE")
def cmd_zremrangebyscore(server, ctx, args):
    lo, lo_inc = _zbound(args[1])
    hi, hi_inc = _zbound(args[2])
    return _zset(server, _s(args[0])).remove_range_by_score(lo, lo_inc, hi, hi_inc)


@register("ZREMRANGEBYRANK")
def cmd_zremrangebyrank(server, ctx, args):
    return _zset(server, _s(args[0])).remove_range_by_rank(_int(args[1]), _int(args[2]))


@register("ZSCAN")
def cmd_zscan(server, ctx, args):
    pattern, count, _ = _scan_opts(args, 2)
    entries = sorted(_zset(server, _s(args[0])).entry_range(0, -1))
    if pattern is not None:
        entries = [e for e in entries if _glob_match(pattern, e[0].decode(errors="replace"))]
    cur, page = _scan_page(entries, _int(args[1]), count)
    flat = []
    for m, sc in page:
        flat += [m, _fnum(sc)]
    return [cur, flat]


def _zstore(server, args, op: str):
    """ZUNIONSTORE/ZINTERSTORE dest numkeys key... [WEIGHTS w...]
    [AGGREGATE SUM|MIN|MAX] — computed in the handler so WEIGHTS compose
    (the handle-level union/intersection don't carry weights)."""
    dest = _s(args[0])
    n = _int(args[1])
    names = [_s(k) for k in args[2 : 2 + n]]
    weights = [1.0] * n
    agg = "SUM"
    i = 2 + n
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"WEIGHTS":
            weights = [float(args[i + 1 + j]) for j in range(n)]
            i += 1 + n
        elif opt == b"AGGREGATE":
            agg = _s(args[i + 1]).upper()
            if agg not in ("SUM", "MIN", "MAX"):
                raise RespError("ERR syntax error")
            i += 2
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    with server.engine.locked_many([dest, *names]):
        maps = []
        for nm, w in zip(names, weights):
            maps.append({m: sc * w for m, sc in _zset(server, nm).entry_range(0, -1)})
        if op == "union":
            acc: Dict[bytes, float] = {}
            for mp in maps:
                for m, sc in mp.items():
                    if m in acc:
                        acc[m] = sc + acc[m] if agg == "SUM" else (min if agg == "MIN" else max)(acc[m], sc)
                    else:
                        acc[m] = sc
        else:  # intersection
            keys = set(maps[0]) if maps else set()
            for mp in maps[1:]:
                keys &= set(mp)
            acc = {}
            for m in keys:
                vals = [mp[m] for mp in maps]
                acc[m] = sum(vals) if agg == "SUM" else (min(vals) if agg == "MIN" else max(vals))
        server.engine.store.delete(dest)
        z = _zset(server, dest)
        for m, sc in acc.items():
            z.add(sc, m)
        return len(acc)


@register("ZUNIONSTORE")
def cmd_zunionstore(server, ctx, args):
    return _zstore(server, args, "union")


@register("ZINTERSTORE")
def cmd_zinterstore(server, ctx, args):
    return _zstore(server, args, "intersection")


# -- typed surface expansion round 3: generic verbs, lex ranges, multi-pops,
# -- blocking family (RedisCommands.java rows toward full verb parity) -------

@register("COPY")
def cmd_copy(server, ctx, args):
    """COPY src dst [REPLACE] — record-level clone, any object kind
    (core/checkpoint.clone_record: device arrays deep-copy on device since
    records mutate through donated buffers)."""
    from redisson_tpu.core import checkpoint

    src, dst = _s(args[0]), _s(args[1])
    replace = any(bytes(a).upper() == b"REPLACE" for a in args[2:])
    return 1 if checkpoint.clone_record(server.engine, src, dst, replace) else 0


@register("RENAMENX")
def cmd_renamenx(server, ctx, args):
    src, dst = _s(args[0]), _s(args[1])
    with server.engine.locked_many([src, dst]):
        if not server.engine.store.exists(src):
            raise RespError("ERR no such key")
        if server.engine.store.exists(dst):
            return 0
        server.engine.store.rename(src, dst)
    return 1


@register("BITPOS")
def cmd_bitpos(server, ctx, args):
    """BITPOS key bit [start [end]] — byte-indexed range, Redis semantics:
    searching for 0 with NO explicit end treats the value as right-padded
    with zeros (position past the last byte); with an explicit end, -1."""
    bit = _int(args[1])
    if bit not in (0, 1):
        raise RespError("ERR The bit argument must be 1 or 0.")
    if len(args) > 4:
        raise RespError("ERR syntax error")
    data = _bitset(server, _s(args[0])).to_byte_array()
    nbytes = len(data)
    start = _int(args[2]) if len(args) > 2 else 0
    has_end = len(args) > 3
    end = _int(args[3]) if has_end else nbytes - 1
    if start < 0:
        start = max(0, nbytes + start)
    if end < 0:
        end = nbytes + end
    end = min(end, nbytes - 1)
    want = bool(bit)
    # bit order matches SETBIT/GETBIT's indexing (LSB-first within a byte,
    # the BitSet layout) so BITPOS(SETBIT(i)) == i on this surface
    for byte_i in range(start, end + 1):
        b = data[byte_i]
        for bit_i in range(8):
            if bool((b >> bit_i) & 1) == want:
                return byte_i * 8 + bit_i
    if not want and not has_end and start <= nbytes:
        return nbytes * 8  # zeros continue past the stored bytes
    return -1


@register("SORT")
def cmd_sort(server, ctx, args):
    """SORT key [LIMIT off cnt] [ASC|DESC] [ALPHA] [STORE dest] over list or
    set records (the RedissonList/SortedSet sort surface)."""
    name = _s(args[0])
    off, cnt, desc, alpha, store = 0, None, False, False, None
    i = 1
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"LIMIT":
            off, cnt = _int(args[i + 1]), _int(args[i + 2])
            i += 3
        elif opt in (b"ASC", b"DESC"):
            desc = opt == b"DESC"
            i += 1
        elif opt == b"ALPHA":
            alpha = True
            i += 1
        elif opt == b"STORE":
            store = _s(args[i + 1])
            i += 2
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    rec = server.engine.store.get(name)
    if rec is None:
        vals = []
    elif rec.kind == "set":
        vals = [bytes(v) for v in _set(server, name).read_all()]
    else:
        vals = [bytes(v) for v in _deque(server, name).read_all()]
    if alpha:
        vals.sort(reverse=desc)
    else:
        try:
            vals.sort(key=float, reverse=desc)
        except ValueError:
            raise RespError("ERR One or more scores can't be converted into double")
    if cnt is not None:
        vals = vals[off : off + cnt] if cnt >= 0 else vals[off:]
    if store is None:
        return vals
    with server.engine.locked(store):
        server.engine.store.delete(store)
        d = _deque(server, store)
        for v in vals:
            d.add_last(v)
    return len(vals)


# -- lex ranges over sorted sets ---------------------------------------------

def _lex_bound(raw):
    """Returns (value|None, inclusive).  None value = unbounded (-/+)."""
    s = bytes(raw)
    if s in (b"-", b"+"):
        return None, True
    if s.startswith(b"["):
        return s[1:], True
    if s.startswith(b"("):
        return s[1:], False
    raise RespError("ERR min or max not valid string range item")


def _lex_slice(server, name: str, lo_raw, hi_raw):
    lo, lo_inc = _lex_bound(lo_raw)
    hi, hi_inc = _lex_bound(hi_raw)
    lo_unbounded = bytes(lo_raw) == b"-"
    hi_unbounded = bytes(hi_raw) == b"+"
    if bytes(lo_raw) == b"+" or bytes(hi_raw) == b"-":
        return []  # inverted unbounded forms select nothing
    members = sorted(bytes(m) for m, _ in _zset(server, name).entry_range(0, -1))
    out = []
    for m in members:
        if not lo_unbounded:
            if m < lo or (m == lo and not lo_inc):
                continue
        if not hi_unbounded:
            if m > hi or (m == hi and not hi_inc):
                continue
        out.append(m)
    return out


@register("ZLEXCOUNT")
def cmd_zlexcount(server, ctx, args):
    return len(_lex_slice(server, _s(args[0]), args[1], args[2]))


@register("ZRANGEBYLEX")
def cmd_zrangebylex(server, ctx, args):
    out = _lex_slice(server, _s(args[0]), args[1], args[2])
    return _apply_limit(out, args, 3)


@register("ZREVRANGEBYLEX")
def cmd_zrevrangebylex(server, ctx, args):
    # note the reversed bound order: ZREVRANGEBYLEX key max min
    out = _lex_slice(server, _s(args[0]), args[2], args[1])
    out.reverse()
    return _apply_limit(out, args, 3)


@register("ZREMRANGEBYLEX")
def cmd_zremrangebylex(server, ctx, args):
    name = _s(args[0])
    with server.engine.locked(name):
        victims = _lex_slice(server, name, args[1], args[2])
        z = _zset(server, name)
        for m in victims:
            z.remove(m)
    return len(victims)


def _apply_limit(out, args, at):
    if len(args) > at:
        if bytes(args[at]).upper() != b"LIMIT" or len(args) < at + 3:
            raise RespError("ERR syntax error")
        off, cnt = _int(args[at + 1]), _int(args[at + 2])
        out = out[off : off + cnt] if cnt >= 0 else out[off:]
    return out


# -- zset combination reads + range store ------------------------------------


def _zcombine(server, names, op, weights=None, agg="SUM"):
    fold = sum if agg == "SUM" else (min if agg == "MIN" else max)
    weights = weights or [1.0] * len(names)
    maps = [
        {m: sc * w for m, sc in _zset(server, nm).entry_range(0, -1)}
        for nm, w in zip(names, weights)
    ]
    if not maps:
        return {}
    if op == "union":
        acc: dict = {}
        for mp in maps:
            for m, sc in mp.items():
                acc[m] = fold((acc[m], sc)) if m in acc else sc
        return acc
    if op == "inter":
        keys = set(maps[0])
        for mp in maps[1:]:
            keys &= set(mp)
        return {m: fold(mp[m] for mp in maps) for m in keys}
    # diff: first minus membership of the rest, scores from the first
    drop = set()
    for mp in maps[1:]:
        drop |= set(mp)
    return {m: sc for m, sc in maps[0].items() if m not in drop}


def _zcombo_read(server, ctx, args, op):
    n, names, i = _znumkeys(server, args)
    weights, agg, withscores = None, "SUM", False
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt == b"WITHSCORES":
            withscores = True
            i += 1
        elif opt == b"WEIGHTS" and op != "diff":  # ZDIFF takes no modifiers
            if len(args) < i + 1 + n:
                raise RespError("ERR syntax error")
            weights = [float(args[i + 1 + j]) for j in range(n)]
            i += 1 + n
        elif opt == b"AGGREGATE" and op != "diff":
            agg = _s(args[i + 1]).upper() if len(args) > i + 1 else ""
            if agg not in ("SUM", "MIN", "MAX"):
                raise RespError("ERR syntax error")
            i += 2
        else:
            # unknown trailing args must ERROR, never silently drop —
            # a typo'd WITHSCORES would otherwise return wrong-shaped data
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    with server.engine.locked_many(names):
        acc = _zcombine(server, names, op, weights, agg)
    out = []
    for m, sc in sorted(acc.items(), key=lambda kv: (kv[1], kv[0])):
        out += [m, _fnum(sc)] if withscores else [m]
    return out


@register("ZDIFF")
def cmd_zdiff(server, ctx, args):
    return _zcombo_read(server, ctx, args, "diff")


@register("ZINTER")
def cmd_zinter(server, ctx, args):
    return _zcombo_read(server, ctx, args, "inter")


@register("ZUNION")
def cmd_zunion(server, ctx, args):
    return _zcombo_read(server, ctx, args, "union")


@register("ZINTERCARD")
def cmd_zintercard(server, ctx, args):
    """ZINTERCARD numkeys key... [LIMIT n] — intersection cardinality
    without materializing the member list on the wire."""
    _n, names, i = _znumkeys(server, args, 0)
    limit = 0
    if i < len(args):
        if bytes(args[i]).upper() != b"LIMIT" or i + 1 >= len(args):
            raise RespError("ERR syntax error")
        limit = _int(args[i + 1])
        if limit < 0:
            raise RespError("ERR LIMIT can't be negative")
    with server.engine.locked_many(names):
        acc = _zcombine(server, names, "inter")
    card = len(acc)
    return min(card, limit) if limit else card


@register("ZDIFFSTORE")
def cmd_zdiffstore(server, ctx, args):
    dest = _s(args[0])
    _n, names, _i = _znumkeys(server, args, 1)
    with server.engine.locked_many([dest, *names]):
        acc = _zcombine(server, names, "diff")
        server.engine.store.delete(dest)
        z = _zset(server, dest)
        for m, sc in acc.items():
            z.add(sc, m)
    return len(acc)


@register("ZRANGESTORE")
def cmd_zrangestore(server, ctx, args):
    """ZRANGESTORE dst src min max [BYSCORE|BYLEX] [REV] [LIMIT off cnt]."""
    dst, src = _s(args[0]), _s(args[1])
    by, rev = b"INDEX", False
    limit_at = None
    i = 4
    while i < len(args):
        opt = bytes(args[i]).upper()
        if opt in (b"BYSCORE", b"BYLEX"):
            by = opt
            i += 1
        elif opt == b"REV":
            rev = True
            i += 1
        elif opt == b"LIMIT":
            limit_at = i
            i += 3
        else:
            raise RespError(f"ERR syntax error near '{_s(args[i])}'")
    if limit_at is not None and by == b"INDEX":
        raise RespError("ERR syntax error, LIMIT is only supported in combination with either BYSCORE or BYLEX")
    with server.engine.locked_many([dst, src]):
        lo_raw, hi_raw = (args[3], args[2]) if rev else (args[2], args[3])
        if by == b"BYLEX":
            members = _lex_slice(server, src, lo_raw, hi_raw)
            z = _zset(server, src)
            entries = [(m, z.get_score(m) or 0.0) for m in members]
        elif by == b"BYSCORE":
            lo, lo_inc = _zbound(lo_raw)
            hi, hi_inc = _zbound(hi_raw)
            entries = [
                (bytes(m), sc)
                for m, sc in _zset(server, src).entry_range(0, -1)
                if (sc > lo or (sc == lo and lo_inc)) and (sc < hi or (sc == hi and hi_inc))
            ]
        else:
            all_entries = _zset(server, src).entry_range(0, -1)
            from redisson_tpu.client.objects.scoredsortedset import _norm_range

            start, stop = _int(args[2]), _int(args[3])
            if rev:
                all_entries.reverse()
            lo_i, hi_i = _norm_range(start, stop, len(all_entries))
            entries = [
                (bytes(m), sc) for m, sc in
                (all_entries[lo_i : hi_i + 1] if hi_i >= lo_i else [])
            ]
        if rev and by != b"INDEX":
            entries.reverse()
        if limit_at is not None:
            off, cnt = _int(args[limit_at + 1]), _int(args[limit_at + 2])
            entries = entries[off : off + cnt] if cnt >= 0 else entries[off:]
        server.engine.store.delete(dst)
        z = _zset(server, dst)
        for m, sc in entries:
            z.add(sc, m)
    return len(entries)


