"""Wire-verb handler families; importing this package registers every verb.

Order mirrors the original registry.py layout.
"""
from redisson_tpu.server.verbs import connection  # noqa: F401,E402
from redisson_tpu.server.verbs import keyspace  # noqa: F401,E402
from redisson_tpu.server.verbs import sketch  # noqa: F401,E402
from redisson_tpu.server.verbs import admin  # noqa: F401,E402
from redisson_tpu.server.verbs import objcall_tx  # noqa: F401,E402
from redisson_tpu.server.verbs import collections  # noqa: F401,E402
from redisson_tpu.server.verbs import zset  # noqa: F401,E402
from redisson_tpu.server.verbs import streamgeo  # noqa: F401,E402
from redisson_tpu.server.verbs import modules  # noqa: F401,E402
