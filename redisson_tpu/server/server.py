"""TpuServer: asyncio RESP server fronting one Engine ("the sidecar").

Role parity: the reference has no server (Redis is the server); the TPU
build's data plane lives in THIS process next to the accelerator, so the
server is the piece that takes the Redis role for remote clients while the
Engine takes the command-execution role (SURVEY.md §7.1 L4').

Connection discipline mirrors the reference's pipeline
(client/handler/RedisChannelInitializer.java:74-108): framed RESP in, ordered
execution per connection (the CommandsQueue FIFO guarantee), replies written
in arrival order, pubsub push frames interleaved from a writer queue.
Engine calls execute on a bounded thread pool so the event loop never blocks
on device dispatch.

Overlap plane (core/ioplane, ISSUE 3): a frame whose replies carry device
results no longer blocks its read loop on the D2H readback — the frame's
grouped force runs as a readback future drained by the per-connection writer
task's completion queue (FIFO: reply order and RESP framing are untouched),
while the read loop stages and dispatches the NEXT frame.  Frames without
device results flush immediately.  `--no-overlap` restores the serial
stage->dispatch->fetch shape for A/B measurement.

QoS plane (server/scheduler.py, ISSUE 10): between frame parsing and
dispatch, every frame is classified into a deadline class (interactive vs
bulk), charged against its tenant's token bucket (over-budget = -BUSY shed
before dispatch), and admitted class-aware — interactive on a reserved
worker slice, bulk behind a bounded admission gate.  `--no-qos` /
`RTPU_NO_QOS=1` restores pure arrival-order dispatch, bit-identically.
"""
from __future__ import annotations

import asyncio
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from redisson_tpu.client import routing as _routing
from redisson_tpu.core import ioplane
from redisson_tpu.core.coalesce import plan_subwindows, runs_within_admission
from redisson_tpu.core.engine import Engine
from redisson_tpu.net import resp
from redisson_tpu.net.resp import ProtocolError, RespError
from redisson_tpu.observe import trace as _obs
from redisson_tpu.server import scheduler as _sched
from redisson_tpu.server.registry import LazyReply, REGISTRY, CommandContext


class _Encoded:
    """Pre-encoded wire frame (errors encoded at catch time)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


class _PendingFrame:
    """A frame whose readback is still in flight (overlap plane): the
    per-connection writer task awaits `fut` (the executor job forcing the
    frame's LazyReplies), then encodes and writes the replies — while the
    connection's read loop is already staging and dispatching the NEXT
    frame.  `proto` is the connection's negotiated protocol AT DISPATCH
    time: a later frame's HELLO must not re-encode earlier replies.
    `trace` is the frame's FrameTrace when tracing is armed (the writer
    task closes its `reply` span at write time), else None."""

    __slots__ = ("results", "fut", "proto", "trace")

    def __init__(self, results: list, fut, proto: int, trace=None):
        self.results = results
        self.fut = fut
        self.proto = proto
        self.trace = trace

    def encoded(self) -> bytes:
        return _encode_frame(self.results, self.proto)


class _TracedEncoded:
    """Pre-encoded frame bytes carrying their FrameTrace (tracing ARMED
    only — disarmed frames enqueue plain bytes, exactly as before): the
    writer task writes `data` and closes the trace's `reply` span, making
    the trace total the true client-observable latency."""

    __slots__ = ("data", "trace")

    def __init__(self, data: bytes, trace):
        self.data = data
        self.trace = trace


# fixed -TRYAGAIN texts (ISSUE 19): byte-identical whichever layer detects
# the fault and whether the chaos plane is armed or not
_DEVICE_FAULT_TRYAGAIN = "TRYAGAIN device fault during dispatch; retry"


def _quarantined_tryagain(dev_id: int) -> str:
    return f"TRYAGAIN device {dev_id} quarantined; retry after evacuation"


def _force_lazies(results: list, server, trace=None) -> None:
    """Materialize every LazyReply of a frame in place.  Device-form lazies
    are fetched with one concatenated transfer per dtype (the whole frame
    pays ~1 tunnel round trip); callable-form lazies force individually.
    `trace` (tracing armed only) is activated on this worker thread so the
    readback spans recorded inside the gather land on the right frame."""
    from redisson_tpu.server.registry import gather_lazy_device_results

    if trace is not None:
        _obs.set_current(trace)

    def fail(i, e):
        server.stats["errors"] += 1
        if isinstance(e, RespError):
            results[i] = _Encoded(resp.encode_error(str(e.args[0])))
        elif ioplane.is_retryable_device_fault(e):
            # watchdog timeout / kernel-launch failure surfacing at force
            # time: a clean retryable -TRYAGAIN, never a wedged writer or
            # an opaque internal error (ISSUE 19)
            results[i] = _Encoded(resp.encode_error(_DEVICE_FAULT_TRYAGAIN))
        else:
            results[i] = _Encoded(
                resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
            )

    try:
        dev_idx = [
            i for i, r in enumerate(results)
            if isinstance(r, LazyReply) and r.device is not None
        ]
        if dev_idx:
            try:
                host_vals = gather_lazy_device_results([results[i] for i in dev_idx])
            except ioplane.LaneWatchdogTimeout as e:
                # the grouped drain tripped the armed lane watchdog: the
                # frame's device-form lazies rode ONE hung transfer — fail
                # them all retryable instead of re-forcing through the
                # same wedged device one by one
                for i in dev_idx:
                    fail(i, e)
                dev_idx, host_vals = [], None
            except Exception:  # noqa: BLE001 — grouped path failed; force singly
                host_vals = None
            if host_vals is not None:
                for i, vals in zip(dev_idx, host_vals):
                    try:
                        results[i] = results[i].finish(vals)
                    except Exception as e:  # noqa: BLE001 — per-reply isolation
                        fail(i, e)
        for i, r in enumerate(results):
            if isinstance(r, LazyReply):
                try:
                    results[i] = r.force()
                except Exception as e:  # noqa: BLE001 — per-reply isolation
                    fail(i, e)
    finally:
        if trace is not None:
            _obs.clear_current()


# Commands whose handlers may PARK the worker thread (blocking verbs hold it
# for up to their timeout; OBJCALL runs arbitrary object methods incl.
# poll_blocking).  Dispatched on the wide slow pool so the per-connection
# fast pool never starves.
_SLOW_COMMANDS = frozenset(
    b.encode() for b in (
        "OBJCALL", "OBJCALLM", "OBJCALLMA", "OBJCALLV", "TXEXEC", "EXEC",
        "BLPOP", "BRPOP", "BLMOVE",
        "BRPOPLPUSH", "BZPOPMIN", "BZPOPMAX", "BLMPOP", "BZMPOP",
        "XREAD", "XREADGROUP", "WAIT",
    )
)


class TpuServer:
    def __init__(
        self,
        engine: Optional[Engine] = None,
        host: str = "127.0.0.1",
        port: int = 6390,
        password: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
        mode: str = "standalone",
        workers: int = 4,
        tls_cert_file: Optional[str] = None,
        tls_key_file: Optional[str] = None,
        tls_ca_file: Optional[str] = None,
        users: Optional[Dict[str, str]] = None,
        overlap: Optional[bool] = None,
        devices: Optional[Any] = None,
        qos: Optional[bool] = None,
        dispatch_ahead: Optional[int] = None,
        journal_dir: Optional[str] = None,
        advertise_host: Optional[str] = None,
    ):
        self.engine = engine if engine is not None else Engine()
        # device-sharded serving (ISSUE 8): `devices` maps the 16384-slot
        # table onto the local device mesh — an int takes the first N local
        # devices, "all" takes every one.  None (default) = the historical
        # single-device server.  An engine whose placement is already
        # enabled (embedded callers) is left as configured.
        if devices is not None and self.engine.placement is None:
            n = None if devices in ("all", "ALL") else int(devices)
            self.engine.enable_placement(n_devices=n)
        # overlapped device I/O plane (core/ioplane): frames with device-form
        # lazy replies hand their readback to the per-connection writer task
        # instead of blocking the read loop — upload/kernel of frame N+1
        # overlaps the D2H readback of frame N.  None = follow the process-
        # global switch; False = the serial A/B reference (--no-overlap).
        from redisson_tpu.core import ioplane as _ioplane

        self.overlap = _ioplane.overlap_enabled() if overlap is None else bool(overlap)
        # dispatch-ahead bound: at most this many frames may sit between
        # "dispatched" and "replies written" per connection (bounds device
        # memory held by un-drained readbacks).  Configurable (ISSUE 10
        # satellite): tpu-server --dispatch-ahead N / CONFIG SET
        # dispatch-ahead — applied to connections opened AFTER the change
        # (each connection sizes its semaphore at accept time); default 2.
        self.readback_ahead = (
            2 if dispatch_ahead is None else max(1, int(dispatch_ahead))
        )
        # deadline-aware window scheduling + per-tenant QoS (ISSUE 10,
        # server/scheduler.py): classify frames interactive/bulk, charge
        # per-tenant token buckets, shed over-budget frames with -BUSY
        # before dispatch.  None = follow the process-global switch
        # (RTPU_NO_QOS=1 disarms); shedding itself is additionally opt-in
        # via CONFIG SET qos-tenant-rate (default unlimited).
        self.scheduler = _sched.WindowScheduler(enabled=qos)
        if self.scheduler.bulk_slots <= 0:
            # reserve one dispatch slot for interactive traffic: bulk-class
            # frames across ALL connections share workers-1 admission slots
            self.scheduler.bulk_slots = max(1, workers - 1)
        self._bulk_gate: Optional[asyncio.Semaphore] = None
        self._bulk_gate_n = 0
        self.host = host
        self.port = port
        # the address this node IS in cluster views (ISSUE 16): a cross-host
        # node binds 0.0.0.0 but is named in views/journals/READY by its
        # routable address — without the split, owns_slot never matches and
        # the node MOVED-bounces its own slots forever.  None = bind host
        # (the single-machine default, where they coincide).
        self.advertise_host = advertise_host
        self.password = password
        # ACL users (username -> password): the reference's AUTH user pass
        # (BaseConnectionHandler.java:59-122).  "default" aliases `password`.
        self.users: Dict[str, str] = dict(users or {})
        # TLS: cert+key enable the listener's TLS; ca_file additionally
        # REQUIRES client certificates (mTLS) and pins the trust root for
        # this node's OUTGOING links (migration/replication) so a TLS
        # cluster's bus speaks TLS end to end.
        self.tls_cert_file = tls_cert_file
        self.tls_key_file = tls_key_file
        self.tls_ca_file = tls_ca_file
        self.checkpoint_path = checkpoint_path
        self.mode = mode
        self.node_id = uuid.uuid4().hex
        self.started_at = time.time()
        self.stats = {"connections": 0, "commands": 0, "errors": 0, "sheds": 0,
                      # read-scaling plane (ISSUE 17): replica-served keyed
                      # reads, reads refused as too stale (REPLSTATE
                      # MAXSTALE), reads bounced to the master (missing
                      # READONLY / fenced slot)
                      "replica_reads": 0, "replica_redirects_stale": 0,
                      "replica_fallbacks": 0}
        # observability (utils/metrics.py): per-command timers + counters,
        # rendered by the METRICS command; hooks = NettyHook-analog SPI
        from redisson_tpu.utils.metrics import MetricsHook, MetricsRegistry

        self.metrics = MetricsRegistry()
        self.hooks = [MetricsHook(self.metrics)]
        self.metrics.gauge("keys", lambda: len(self.engine.store))
        self.metrics.gauge("connections", lambda: self.stats["connections"])
        # tracing plane (ISSUE 12, observe/trace.py): the process tracer —
        # disarmed by default (zero-cost guards); CONFIG SET trace-enabled /
        # RTPU_TRACE=1 arms it.  Stage-duration histograms feed THIS
        # registry (stage.* timers) so prometheus_text exports breakdowns.
        self.tracer = _obs.TRACER
        self.tracer.registry = self.metrics
        self.metrics.gauge(
            "trace_ring_entries",
            lambda: self.tracer.census()["trace_ring_entries"],
        )
        self.metrics.gauge(
            "trace_inflight",
            lambda: self.tracer.census()["trace_inflight"],
        )
        # orphaned RESP3 pushes (ISSUE 12 satellite bugfix): the process-
        # global drop counter was census-only — a fleet scrape could never
        # see a desync-avoided push drop.  Now a first-class gauge.
        from redisson_tpu.net.client import dropped_push_count

        self.metrics.gauge("dropped_pushes", dropped_push_count)
        # QoS plane gauges (ISSUE 10): shed totals + per-class in-flight —
        # the census variants of the same numbers live in scheduler.census()
        self.metrics.gauge("qos_shed_ops", lambda: self.scheduler.shed_ops)
        self.metrics.gauge(
            "qos_shed_frames", lambda: self.scheduler.shed_frames
        )
        self.metrics.gauge(
            "qos_interactive_inflight_ops",
            lambda: self.scheduler.ledger.ops["interactive"],
        )
        self.metrics.gauge(
            "qos_bulk_inflight_ops",
            lambda: self.scheduler.ledger.ops["bulk"],
        )
        self.metrics.gauge(
            "qos_bulk_waiting", lambda: self.scheduler.ledger.waiting
        )
        # cluster_view: [(slot_from, slot_to, host, port, node_id)] when this
        # node is part of a cluster (set by the topology/launcher, L3')
        self.cluster_view: List[Tuple[int, int, str, int, str]] = []
        # highest accepted SETVIEW fencing token (coordinator-HA discipline:
        # a stale ex-leader's late view write carries a lower token and is
        # rejected; see registry.py CLUSTER SETVIEW TOKEN)
        self.view_epoch: int = 0
        # live resharding state (the MIGRATING/IMPORTING window of the
        # reference's slot-migration protocol, cluster/ClusterConnectionManager
        # .java:358-450 checkSlotsMigration + RedisExecutor ASK handling):
        #   migrating_slots: slot -> target "host:port" (this node drains it)
        #   importing_slots: slot -> source "host:port" (this node receives)
        self.migrating_slots: Dict[int, str] = {}
        self.importing_slots: Dict[int, str] = {}
        # crash-recovery fence (ISSUE 6): slots whose journaled migration
        # was in flight when THIS process last died (rearm_recovery at
        # boot).  Until resume_migrations settles the journal, every keyed
        # command in such a slot answers TRYAGAIN — serving the restored
        # (possibly stale) copies would fork the record lineage against the
        # copies the pre-crash drain already shipped to the target, and one
        # fork's acked writes would silently lose the version race when the
        # resumed drain reconciles them.  SETSLOT STABLE clears it.
        self.recovering_slots: Dict[int, str] = {}
        # per-slot migration fencing (ISSUE 4 journaled migrations): the
        # highest EPOCH this node accepted for each slot's SETSLOT/
        # MIGRATESLOTS traffic.  A resumed coordinator re-issues its
        # journaled epoch (== accepted: idempotent redo), while a STALE
        # coordinator resuming after a NEWER migration touched the slot
        # carries a lower epoch and is rejected (STALEEPOCH) — the fencing
        # that makes journal replay safe under coordinator races.
        self.slot_epochs: Dict[int, int] = {}
        # the ACTIVE journaled epoch per MIGRATING slot (set when SETSLOT
        # MIGRATING carries EPOCH, popped on STABLE): the drain stamps it
        # onto every outgoing IMPORTRECORDS so the target journals the
        # batch before acking.  Distinct from slot_epochs, which is the
        # fencing high-water mark and survives past STABLE — stamping from
        # it would mis-attribute a later unjournaled migration's batches
        # to a settled journal.
        self.migrating_epochs: Dict[int, int] = {}
        # import-side journal plane (ISSUE 13): the shared journal
        # directory (``--journal-dir`` / ClusterSupervisor) this node
        # writes its ImportJournals into, plus the OPEN journals by epoch —
        # settled on the migration's final SETSLOT STABLE, replayed by
        # migration.rearm_recovery after a crash
        self.journal_dir = journal_dir
        self._import_journals: Dict[int, Any] = {}
        self._import_journal_lock = threading.Lock()
        # read-scaling gauges (ISSUE 17): METRICS / METRICS CLUSTER rows +
        # ResourceCensus feed — replica-side attribution (the replica both
        # serves the read and refuses the stale/unarmed one)
        self.metrics.gauge(
            "replica_reads", lambda: self.stats["replica_reads"]
        )
        self.metrics.gauge(
            "replica_redirects_stale",
            lambda: self.stats["replica_redirects_stale"],
        )
        self.metrics.gauge(
            "replica_fallbacks", lambda: self.stats["replica_fallbacks"]
        )
        # -- cluster / replication role (server/replication.py) -------------
        self.role = "master"  # "master" | "replica"
        self.master_address: Optional[str] = None
        # bounded-staleness stamp (ISSUE 17): the highest sweep-cut offset
        # this REPLICA applied (REPLPUSH payload stamp or REPLPING), the
        # master wall-clock of that cut, and the LOCAL monotonic receipt
        # time — staleness_ms is measured against the local receipt so
        # cross-host clock skew can never fake freshness
        self.repl_applied_offset = 0
        self.repl_applied_ts = 0.0
        self.repl_applied_at: Optional[float] = None
        # set on REPLICAOF NO ONE promotion: the master this node replicated
        # before — the ROLE breadcrumb coordinators use to adopt
        # half-finished failovers (registry cmd_role / cmd_replicaof)
        self.promoted_from: Optional[str] = None
        self._replication = None  # lazy ReplicationSource (master side)
        self._repl_lock = threading.Lock()
        # REPLPUSHSEG staging: xfer_id -> [chunk slots, last-touch monotonic]
        # (verbs/admin.py cmd_replpushseg; census counts live entries)
        self._repl_xfers: Dict[str, list] = {}
        self._repl_xfers_lock = threading.Lock()
        # resumable REPLSNAPSHOT staging (ISSUE 16): xfer_id ->
        # [blob, chunk_bytes, last-touch monotonic] — one immutable
        # serialized cut a replica FETCHes by offset; reaped by staleness
        # (verbs/admin.py cmd_replsnapshot; census counts live entries)
        self._snap_stages: Dict[str, list] = {}
        self._snap_lock = threading.Lock()
        self._snap_seq = 0
        # chaos pause gate (SIGSTOP analog): cleared = every command handler
        # parks before dispatch, so the node stops answering (pings included)
        # WITHOUT closing connections — the hung-but-accepting failure mode
        # that only command-timeout detectors can catch
        self._pause_gate = threading.Event()
        self._pause_gate.set()
        self._client_ids = iter(range(1, 1 << 62))
        # server-assisted client tracking (tracking/table.py): per-connection
        # read-key memory + RESP3 invalidation pushes on write/expiry/
        # FLUSHALL/slot handoff.  Always constructed (cheap); the dispatch
        # hook costs one int load while no client has tracking on.
        from redisson_tpu.tracking.table import TrackingTable

        self.tracking = TrackingTable(self)
        self.metrics.gauge("tracking_keys", self.tracking.tracked_key_count)
        self.metrics.gauge(
            "tracking_overflow_evictions",
            lambda: self.tracking.stats["overflow_evictions"],
        )
        self.metrics.gauge(
            "tracking_pushes", lambda: self.tracking.stats["pushes"]
        )
        # expiry invalidation: a key the TTL reaper (or a lazy-expiry read)
        # drops must invalidate near caches exactly like a DEL would
        self.engine.store.on_expired = self.tracking.note_expired
        # embedding-bank residency gauges (ISSUE 11, the first HBM-ledger
        # brick): bank count + device bytes, 0 until FT.CREATE ... VECTOR
        # builds one (the search service is lazily constructed — don't
        # force it just to report zero)
        # ONE labeled gauge family for the whole embedding-bank census —
        # totals (ftvec_banks / ftvec_device_bytes / ftvec_index_bytes, the
        # ISSUE 11/14 rows) AND the per-device HBM-ledger labels
        # ftvec_*_bytes_dev<N> (ISSUE 15), which exist only while that
        # device holds bank bytes, so DROPINDEX zeroes every shard's row.
        # One family on purpose: the census walks every index/bank/shard,
        # and per-row scalar gauges would re-run that walk once per row
        # per scrape.
        self.metrics.multi_gauge("ftvec", self._ftvec_census)
        # per-device residency over ALL record kinds (ISSUE 19 satellite):
        # record_bytes_dev<N>[_<kind>] rows from one store scan per scrape —
        # same one-family discipline as ftvec, rows vanish with the bytes
        self.metrics.multi_gauge("devbytes", self._device_bytes_census)
        # tiered-HBM residency plane (ISSUE 20): per-device per-tier byte
        # ledgers (residency_bytes_dev<N>_{hot,warm,cold}) plus the
        # promotion/demotion/fault-in counters — rows exist only while the
        # manager is armed and the tier holds bytes, so DEL drains them
        self.metrics.multi_gauge("residency", self._residency_census)
        # OBJCALL handle cache (ordered for LRU eviction; see registry)
        from collections import OrderedDict

        self._objcall_handles: "OrderedDict" = OrderedDict()
        self._objcall_handles_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="rtpu-srv")
        # reserved interactive dispatch capacity (ISSUE 10): frames the
        # scheduler classifies interactive run HERE, so a bulk flood holding
        # every shared worker can never queue ahead of them (the thread-pool
        # face of "interactive ops are admitted into the window first").
        # FULL --workers width on purpose: this is isolation, not a
        # reservation — with QoS armed by default, small-frame-heavy
        # deployments (and sharded interactive frames' per-device fan-out)
        # must keep their historical dispatch concurrency (threads spawn
        # lazily, so an all-bulk workload never pays for these)
        self._workers = workers
        self._qos_pool = ThreadPoolExecutor(
            max_workers=max(2, workers), thread_name_prefix="rtpu-qos"
        )
        # OBJCALL may run arbitrarily-blocking object methods (blocking
        # queues, latches); isolate them on a wide pool so parked callers
        # can't starve the data-plane workers (the reference marks such
        # commands isBlockingCommand and gives them dedicated connections)
        self._slow_pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="rtpu-slow")
        self._closing = False
        # EXEC transactions serialize (see cmd_exec: handlers may take record
        # locks beyond the precomputed key set)
        self._exec_mutex = threading.Lock()
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._writers: set = set()
        self._local_client = None

    # -- registry support ----------------------------------------------------

    def config_view(self) -> Dict[str, Any]:
        """CONFIG GET surface: the node's live knob table (read side)."""
        ev = self.engine._eviction
        cfg = self.engine.config
        view = {
            "port": self.port,
            "mode": self.mode,
            "role": self.role,
            "node-id": self.node_id,
            "checkpoint-path": self.checkpoint_path or "",
            "tls": bool(self.tls_cert_file),
            # before the scheduler lazily starts, report what it WILL use
            "eviction-min-delay": ev.min_delay if ev else cfg.min_cleanup_delay,
            "eviction-max-delay": ev.max_delay if ev else cfg.max_cleanup_delay,
            "tracking-table-max-keys": self.tracking.max_keys,
            "placement-devices": (
                self.engine.placement.n_devices
                if self.engine.placement is not None else 0
            ),
            "dispatch-ahead": self.readback_ahead,
            # device fault domain (ISSUE 19): lane watchdog + quarantine
            "lane-watchdog-ms": ioplane.lane_watchdog_ms(),
            "lane-quarantine-after": ioplane.quarantine_after(),
            # tracing plane (ISSUE 12): arming + ring/slowlog knobs
            "trace-enabled": int(_obs.tracing_enabled()),
            "trace-ring-capacity": self.tracer.ring_capacity,
            "slowlog-log-slower-than": self.tracer.slowlog_slower_than_us,
            "slowlog-max-len": self.tracer.slowlog_max_len,
        }
        # vector-plane tuning (ISSUE 15 satellite): the IVF gather geometry
        # and the per-bank HBM budget must re-sweep on a chip WITHOUT a
        # code edit — live process-global knobs in services/vector.py
        from redisson_tpu.services import vector as _V

        view["ivf-cell-imbalance"] = _V.IVF_CELL_IMBALANCE
        view["ivf-cell-cap-max"] = _V.IVF_CELL_CAP_MAX
        view["ftvec-device-budget"] = _V.DEVICE_BYTES_BUDGET
        # tiered-HBM residency plane (ISSUE 20): the per-DEVICE byte budget
        # (the generalization of the per-bank ftvec knob above) + arming
        from redisson_tpu.core import residency as _res

        view["device-budget-bytes"] = _res.DEVICE_BUDGET_BYTES
        view["residency-enabled"] = int(
            self.engine.residency is not None and _res.tier_enabled()
        )
        view.update(self.scheduler.config_view())
        return view

    def config_set(self, key: str, value: str) -> bool:
        """CONFIG SET: the runtime-tunable subset (RedisNode.setConfig
        analog).  Structural knobs (port, TLS, mode) are read-only."""
        if key == "eviction-min-delay":
            self.engine.eviction.min_delay = float(value)
            return True
        if key == "eviction-max-delay":
            self.engine.eviction.max_delay = float(value)
            return True
        if key == "checkpoint-path":
            self.checkpoint_path = value or None
            return True
        if key == "tracking-table-max-keys":
            n = int(value)
            if n <= 0:
                return False
            self.tracking.max_keys = n
            return True
        if key == "dispatch-ahead":
            n = int(value)
            if n <= 0:
                return False
            # connections opened from now on size their per-connection
            # dispatch-ahead semaphore with this (see _handle)
            self.readback_ahead = n
            return True
        if key == "lane-watchdog-ms":
            # bounded readback wait (ISSUE 19): 0 disarms — the historical
            # unbounded-wait shape, bit-identical replies
            n = int(value)
            if n < 0:
                return False
            ioplane.set_lane_watchdog_ms(n)
            return True
        if key == "lane-quarantine-after":
            # consecutive device faults/timeouts that flip a lane to
            # QUARANTINED (CLUSTER DEVICES shows the state)
            n = int(value)
            if n <= 0:
                return False
            ioplane.set_quarantine_after(n)
            return True
        if key == "trace-enabled":
            # arm/disarm the per-frame tracing plane live (the chaos-hook
            # discipline: disarmed sites cost one load + is-None; armed
            # replies stay bit-identical — tests/test_observe.py pins both)
            _obs.set_tracing(
                value.lower() not in ("0", "false", "no", "off")
            )
            return True
        if key == "trace-ring-capacity":
            n = int(value)
            if n <= 0:
                return False
            self.tracer.set_ring_capacity(n)
            return True
        if key == "slowlog-log-slower-than":
            # Redis parity: µs threshold; negative disables slowlog
            # recording, 0 logs every frame
            self.tracer.slowlog_slower_than_us = int(value)
            return True
        if key == "slowlog-max-len":
            n = int(value)
            if n <= 0:
                return False
            self.tracer.set_slowlog_max_len(n)
            return True
        if key == "ivf-cell-imbalance":
            # cell_cap bound multiplier; applies at the next cell rebuild /
            # retrain (the chip-run gather-bandwidth sweep, ISSUE 15)
            v = float(value)
            if v < 1.0:
                return False
            from redisson_tpu.services import vector as _V

            _V.set_ivf_cell_imbalance(v)
            return True
        if key == "ivf-cell-cap-max":
            # hard gather-width ceiling (0 = unbounded)
            n = int(value)
            if n < 0:
                return False
            from redisson_tpu.services import vector as _V

            _V.set_ivf_cell_cap_max(n)
            return True
        if key == "ftvec-device-budget":
            # per-bank-per-device HBM budget in bytes (0 = unlimited)
            n = int(value)
            if n < 0:
                return False
            from redisson_tpu.services import vector as _V

            _V.set_device_bytes_budget(n)
            return True
        if key == "device-budget-bytes":
            # per-DEVICE HBM budget the residency sweeper demotes against
            # (0 = unlimited; demotion still available via explicit verbs)
            n = int(value)
            if n < 0:
                return False
            from redisson_tpu.core import residency as _res

            _res.set_device_budget_bytes(n)
            return True
        if key == "residency-enabled":
            # arm/disarm the tiered-HBM residency plane live (ISSUE 20).
            # Disarming promotes every demoted record back to HOT first so
            # replies stay bit-identical with the plane off.
            on = value.lower() not in ("0", "false", "no", "off")
            from redisson_tpu.core import residency as _res

            if on:
                self.enable_residency(sweep_interval=1.0)
            else:
                _res.set_tier(False)
                self.engine.disable_residency()
            return True
        if key.startswith("qos-"):
            if key == "qos-bulk-slots" and int(value) <= 0:
                # 0 means "re-derive from workers" exactly like construction
                # time — it must never silently disable the flood protection
                value = str(max(1, self._workers - 1))
            ok = self.scheduler.config_set(key, value)
            if ok and key == "qos-interactive-deadline-ms":
                # arm/disarm ioplane's deadline-triggered window close: live
                # lane pipelines update NOW, pipelines built later inherit
                # the process-global default
                s = self.scheduler.interactive_deadline_ms / 1000.0
                ioplane.set_window_deadline(s if s > 0 else None)
                if self.engine.lanes is not None:
                    for lane in self.engine.lanes.lanes():
                        lane.pipeline.deadline_s = s if s > 0 else None
            if ok and key == "qos-bulk-subwindow-items":
                # push the sub-window split target into the process-global
                # knob the lane dispatch paths read (ISSUE 18)
                ioplane.set_bulk_subwindow_items(
                    self.scheduler.bulk_subwindow_items
                )
            return ok
        return False

    def next_client_id(self) -> int:
        return next(self._client_ids)

    def local_client(self):
        """Embedded client over this server's engine (OBJCALL target)."""
        if self._local_client is None:
            from redisson_tpu.client.redisson import RedissonTpu

            self._local_client = RedissonTpu(self.engine)
        return self._local_client

    def cluster_slots(self) -> List[Any]:
        """CLUSTER SLOTS reply shape: [from, to, [host, port, id]]."""
        if not self.cluster_view:
            return [[0, 16383,
                     [self.public_host.encode(), self.port,
                      self.node_id.encode()]]]
        return [
            [lo, hi, [h.encode(), p, nid.encode()]]
            for (lo, hi, h, p, nid) in self.cluster_view
        ]

    # -- cluster routing / replication role ----------------------------------

    @property
    def public_host(self) -> str:
        """The host this node is KNOWN BY (views, journals, READY line):
        the advertised address when bind and routable addresses differ
        (cross-host nodes binding 0.0.0.0), else the bind host."""
        return self.advertise_host or self.host

    def address(self) -> str:
        return f"{self.public_host}:{self.port}"

    def owns_slot(self, slot: int) -> bool:
        if not self.cluster_view:
            return True
        for lo, hi, h, p, _nid in self.cluster_view:
            if lo <= slot <= hi:
                if (h, p) == (self.public_host, self.port):
                    return True
                # a replica serves READS for its master's range (the READONLY
                # connection mode of Redis cluster replicas); writes are
                # rejected separately by the role check in check_routing
                return self.role == "replica" and self.master_address == f"{h}:{p}"
        return False  # unassigned slot: treat as not owned

    def moved_target(self, slot: int) -> Optional[Tuple[str, int]]:
        for lo, hi, h, p, _nid in self.cluster_view:
            if lo <= slot <= hi:
                return h, p
        return None

    def check_routing(self, cmd: str, args: List[bytes], asking: bool = False,
                      readonly: bool = False) -> None:
        """MOVED/ASK + READONLY enforcement (the server half of the
        reference's redirect protocol, cluster/ClusterConnectionManager +
        command/RedisExecutor redirect handling).

        Migration window semantics (Redis slot-migration model):
          * slot MIGRATING here: keys still present serve locally; absent
            keys redirect ASK to the draining target (they either moved
            already or must be created there);
          * slot IMPORTING here: normally MOVED back to the source (the view
            still names it), but a command preceded by ASKING is served.

        Replica read admission (ISSUE 17, Redis parity): a CLUSTER replica
        serves keyed reads only to connections that armed READONLY —
        everyone else is MOVED to the master (writes keep the historical
        -READONLY refusal below).  Standalone replicated pairs (no cluster
        view) keep serving reads to every connection, as before.
        """
        from redisson_tpu.net import commands as C
        from redisson_tpu.net.resp import RespError
        from redisson_tpu.utils.crc16 import calc_slot

        if self.cluster_view:
            migrating_absent = migrating_present = 0
            ask_target = None
            replica_read = False
            for key in C.command_keys(cmd, args):
                slot = calc_slot(key)
                if slot in self.recovering_slots:
                    # interrupted-migration fence: neither the restored
                    # local copy nor an ASK hop is safe until the journal
                    # resume settles the slot (see recovering_slots above)
                    # — and a replica never serves a fenced slot either
                    raise RespError(
                        f"TRYAGAIN slot {slot} recovering from an "
                        "interrupted migration"
                    )
                if self.owns_slot(slot):
                    if self.role == "replica" and not C.is_write(cmd, args):
                        if not readonly:
                            # the Redis-parity refusal: keyed reads without
                            # READONLY bounce to the master (the client's
                            # fallback path counts the redirect)
                            self.stats["replica_fallbacks"] += 1
                            ma = self.master_address
                            if ma:
                                raise RespError(f"MOVED {slot} {ma}")
                        else:
                            replica_read = True
                    target = self.migrating_slots.get(slot)
                    if target is not None:
                        name = key.decode() if isinstance(key, bytes) else key
                        if self.engine.store.peek(name):
                            migrating_present += 1
                        else:
                            migrating_absent += 1
                            ask_target, ask_slot = target, slot
                    continue
                if asking and slot in self.importing_slots:
                    continue  # one-shot admission during the handoff window
                target = self.moved_target(slot)
                if target is not None:
                    raise RespError(f"MOVED {slot} {target[0]}:{target[1]}")
                raise RespError(f"CLUSTERDOWN Hash slot {slot} not served")
            if migrating_absent:
                if migrating_present:
                    # mixed present/absent across a migration window: neither
                    # node holds every key right now — the client must retry
                    # until the drain finishes (Redis returns TRYAGAIN for
                    # exactly this multi-key case)
                    raise RespError(
                        "TRYAGAIN Multiple keys request during rehashing of slot"
                    )
                raise RespError(f"ASK {ask_slot} {ask_target}")
            if replica_read:
                self.stats["replica_reads"] += 1
        if self.role == "replica" and C.is_write(cmd, args):
            raise RespError("READONLY You can't write against a read only replica.")

    # -- live slot migration (server side) -----------------------------------

    def _migration_absent_guard(self, name: str) -> None:
        """DeviceStore absent-name hook: any touch of an ABSENT record in a
        MIGRATING slot redirects to the target.  This closes the races the
        pre-dispatch ASK check cannot: a record the drain deletes between
        check_routing and the handler would otherwise be silently recreated
        here (lost acked write) or read as nil (read-your-writes violation)."""
        from redisson_tpu.utils.crc16 import calc_slot

        slot = calc_slot(name.encode())
        target = self.migrating_slots.get(slot)
        if target is not None:
            raise RespError(f"ASK {slot} {target}")

    def fence_slot_epoch(self, slot: int, epoch: Optional[int]) -> None:
        """Accept-or-reject a migration-control command's fencing epoch for
        one slot.  Epoch-less commands (legacy callers, manual admin) pass
        unfenced; an epoch below the highest accepted one is a stale
        coordinator's late write and is refused loudly."""
        if epoch is None:
            return
        cur = self.slot_epochs.get(slot, 0)
        if epoch < cur:
            raise RespError(
                f"STALEEPOCH slot {slot} fenced at epoch {cur}; got {epoch}"
            )
        self.slot_epochs[slot] = epoch

    def set_slot_migrating(self, slot: int, target: str,
                           epoch: Optional[int] = None) -> None:
        self.migrating_slots[slot] = target
        if epoch is not None:
            # journaled drain: outgoing IMPORTRECORDS carry this epoch so
            # the target journals each batch before acking (ISSUE 13)
            self.migrating_epochs[slot] = epoch
        self.engine.store.absent_guard = self._migration_absent_guard

    def set_slot_importing(self, slot: int, source: str) -> None:
        self.importing_slots[slot] = source

    def set_slot_recovering(self, slot: int, target: str,
                            epoch: Optional[int] = None) -> None:
        self.recovering_slots[slot] = target
        # fence-first invalidation (the case Redis gets wrong-by-config): a
        # RECOVERING slot's restored copies may be stale against what the
        # pre-crash drain already shipped — every near cache drops the
        # slot's keys BEFORE the slot serves anything again, stamped with
        # THIS handoff's fencing epoch (the caller's, NOT the recorded
        # slot_epochs high-water mark: an epoch-less handoff of a slot a
        # PREVIOUS journaled migration fenced would otherwise be deduped
        # against that stale record and emit nothing) so the resume
        # re-issue is idempotent
        # slot_names is a full store scan — only pay it when a tracking
        # client could actually hear the invalidation (rearm_recovery calls
        # this per in-flight slot BEFORE serving; with tracking idle the
        # boot path must stay O(1))
        self.tracking.invalidate_slot(
            slot, epoch,
            self.slot_names(slot) if self.tracking.active else None,
        )

    def set_slot_stable(self, slot: int, epoch: Optional[int] = None) -> None:
        migrated = slot in self.migrating_slots or slot in self.recovering_slots
        self.migrating_slots.pop(slot, None)
        self.importing_slots.pop(slot, None)
        self.recovering_slots.pop(slot, None)  # resume settled the journal
        self.migrating_epochs.pop(slot, None)
        if not self.migrating_slots:
            self.engine.store.absent_guard = None
        self._settle_import_journals(epoch)
        if migrated:
            # handoff finalized on the SOURCE: whatever the per-key drain
            # stream didn't already invalidate (keys read-but-absent, keys
            # registered after their ship) flushes here, stamped with THIS
            # command's epoch — None (unfenced legacy migration) always
            # emits, a journaled re-issue at its own epoch dedupes
            self.tracking.invalidate_slot(slot, epoch)

    # -- import-side journal (ISSUE 13: the target-kill durability gap) -------

    def journal_import_batch(self, epoch: int, source: Optional[str],
                             blob: bytes) -> None:
        """Make one accepted IMPORTRECORDS batch durable (fsync'd into this
        node's ImportJournal) BEFORE it is applied or acked — the source
        deletes a record only once its batch survives a SIGKILL here.  A
        batch arriving for an epoch whose journal is already terminal is a
        stale re-ship of a settled migration: applied (idempotent by
        version) but not re-journaled — terminal journals stay terminal."""
        from redisson_tpu.server.migration_journal import ImportJournal

        if self.journal_dir is None:
            return
        with self._import_journal_lock:
            j = self._import_journals.get(epoch)
            if j is None:
                j = ImportJournal.open_for(
                    self.journal_dir, self.address(), epoch, source=source
                )
                if j.is_terminal():
                    return
                self._import_journals[epoch] = j
            j.append_batch(blob)

    def adopt_import_journal(self, journal) -> None:
        """Boot-time re-adoption (migration.rearm_recovery): a replayed
        in-flight import journal stays open on the restarted node so the
        resumed migration's final SETSLOT STABLE settles it."""
        with self._import_journal_lock:
            self._import_journals[journal.epoch] = journal

    def import_journal_rows(self) -> List[Tuple[int, str, int, str]]:
        """(epoch, phase, batches journaled, source) per OPEN import
        journal — the CLUSTER WINDOWS rows that let an operator see an
        in-flight import from the receiving end."""
        with self._import_journal_lock:
            return [
                (epoch, j.phase or "", j.batch_count(), j.source or "")
                for epoch, j in sorted(self._import_journals.items())
            ]

    def _settle_import_journals(self, epoch: Optional[int]) -> None:
        """Terminalize the import journal for `epoch` once its migration's
        LAST window slot goes STABLE (no remaining MIGRATING/IMPORTING/
        RECOVERING slot fenced at that epoch) — after which gc may prune it
        and a restart no longer replays it."""
        if epoch is None or not self._import_journals:
            return

        def _settleable() -> bool:
            j = self._import_journals.get(epoch)
            if j is None:
                return False
            open_slots = (
                set(self.importing_slots) | set(self.migrating_slots)
                | set(self.recovering_slots)
            )
            # still a window in flight for this migration? not settleable
            return not any(
                self.slot_epochs.get(s) == epoch for s in open_slots
            )

        with self._import_journal_lock:
            if not _settleable():
                return
        # durability point OUTSIDE the lock: a concurrent drain's
        # journal-and-ack (journal_import_batch) must not stall behind a
        # full-store snapshot and time its source's link out
        if not self._checkpoint_import_state():
            return  # not durable yet: keep the journal for boot replay
        with self._import_journal_lock:
            if not _settleable():  # a re-opened window raced the save
                return
            self._import_journals.pop(epoch).append("STABLE", settled=True)

    def _checkpoint_import_state(self) -> bool:
        """Make the imported records as durable as this node's normal
        story BEFORE an import journal retires: the journal holds the only
        durable copy of batches whose source copies are already deleted,
        so it may only terminalize once a checkpoint covers them — else a
        SIGKILL after STABLE but before the next snapshot would restore a
        pre-import checkpoint with nothing left to replay.  A node with no
        checkpoint configured has no durability floor to wait for.
        Returns False (journal kept in flight, replayed at next boot) when
        the save fails."""
        if self.checkpoint_path is None:
            return True
        from redisson_tpu.core import checkpoint

        try:
            checkpoint.save(self.engine, self.checkpoint_path)
            self.__dict__["_lastsave"] = int(time.time())
            return True
        except Exception:  # noqa: BLE001 — keep the journal instead
            return False

    def slot_names(self, slot: int) -> List[str]:
        from redisson_tpu.utils.crc16 import calc_slot

        return [
            n for n in self.engine.store.keys() if calc_slot(n.encode()) == slot
        ]

    # records shipped per IMPORTRECORDS frame during drains: a journaled
    # target fsyncs ONCE per frame, so batch coalescing divides the
    # journal-before-ack cost by the batch width (ISSUE 14 satellite; the
    # r06 container measured ~2.7ms/record = -27% import throughput at
    # batch 1)
    DRAIN_BATCH_RECORDS = 32

    def migrate_slot_batch(self, slots, limit: int = 0,
                           batch: Optional[int] = None) -> int:
        """Drain MIGRATING slot(s) to their targets; limit<=0 drains fully.

        Records ship in BATCHES of `batch` (default DRAIN_BATCH_RECORDS)
        per IMPORTRECORDS frame, grouped by (target, epoch).  The whole
        batch's record locks are held (sorted order — deadlock-free) across
        serialize -> IMPORTRECORDS -> local delete, the same atomicity the
        per-record path had: every mutation path (object handles AND the
        store-level DEL/EXPIRE commands) takes these locks, so no client
        write, delete, or expire can interleave between the snapshot
        leaving and the local copies dying — the zero-lost-acked-writes
        contract holds for deletes too (a DEL either lands before the
        snapshot, keeping the record out of the batch, or blocks until the
        name is locally absent and then ASK-redirects to the target).
        Redis gets the same guarantee from MIGRATE's single-threaded
        blocking; we pay it per-batch instead of per-server.  A journaled
        target fsyncs its ImportJournal ONCE per frame (journal-before-ack
        and the pre-ack replica cover are per-frame contracts — both hold
        unchanged), so the batch width directly divides the durability
        overhead the ISSUE 13 plane added.
        """
        from redisson_tpu.net.client import NodeClient
        from redisson_tpu.server import replication
        from redisson_tpu.utils.crc16 import calc_slot

        if isinstance(slots, int):
            slots = [slots]
        targets: Dict[int, str] = {}
        for s in slots:
            t = self.migrating_slots.get(s)
            if t is None:
                raise RespError(f"ERR slot {s} is not MIGRATING")
            targets[s] = t
        wanted = set(targets)
        names = [
            (n, calc_slot(n.encode()))
            for n in self.engine.store.keys()
            if calc_slot(n.encode()) in wanted
        ]
        if limit and limit > 0:
            names = names[:limit]
        if not names:
            return 0
        if batch is None or batch <= 0:
            batch = self.DRAIN_BATCH_RECORDS
        # group by (target, epoch) preserving scan order: one frame may
        # carry records of MANY slots, but never records bound for
        # different targets or fenced at different epochs
        groups: Dict[Tuple[str, Optional[int]], List[str]] = {}
        for name, slot in names:
            key = (targets[slot], self.migrating_epochs.get(slot))
            groups.setdefault(key, []).append(name)
        moved = 0
        links: Dict[str, NodeClient] = {}
        try:
            for (target, ep), gnames in groups.items():
                link = links.get(target)
                if link is None:
                    link = links[target] = self.link_client(
                        target, ping_interval=0, retry_attempts=1
                    )
                for i in range(0, len(gnames), batch):
                    moved += self._drain_batch_locked(
                        link, ep, gnames[i : i + batch]
                    )
        finally:
            for link in links.values():
                link.close()
        return moved

    def _drain_batch_locked(self, link, ep: Optional[int],
                            names: List[str]) -> int:
        """Ship one drain batch under ALL its record locks (sorted
        acquisition; serialize_records re-enters each per-record RLock)."""
        from redisson_tpu.server import replication

        with self.engine.locked_many(names):
            present = [n for n in names if self.engine.store.peek(n)]
            if not present:
                return 0  # expired/deleted meanwhile
            blob, shipped = replication.serialize_records(
                self.engine, present, include_live=False
            )
            if not shipped:
                return 0
            if ep is not None:
                # journaled migration: the target fsyncs the whole frame
                # into its ImportJournal BEFORE this ack — the local
                # deletes below are then safe against a target SIGKILL
                # (ISSUE 13 target-kill gap), at ONE fsync per batch
                link.execute(
                    "IMPORTRECORDS", "EPOCH", ep, "SOURCE",
                    self.address(), blob, timeout=30.0,
                )
            else:
                link.execute("IMPORTRECORDS", blob, timeout=30.0)
            shipped_names = [n for n, _nonce, _ver in shipped]
            for name in shipped_names:
                self.engine.store.delete_unguarded(name)
            # drain-stream invalidation: the records just left this node —
            # a near cache serving them would miss every write the target
            # accepts from now on (push enqueue only, so holding the locks
            # here is fine); active-guarded like every other site so an
            # idle-tracking migration never touches the dispatch-shared
            # table lock
            if self.tracking.active:
                self.tracking.note_write(shipped_names, None)
            return len(shipped_names)

    # -- chaos hooks (fault plane, server layer) ------------------------------

    def pause(self) -> None:
        """Stop answering commands without dropping connections (the
        SIGSTOP/GC-pause analog).  Paused workers park on the gate; clients
        observe reply timeouts, feeding FailedCommandsTimeoutDetector."""
        self._pause_gate.clear()

    def resume(self) -> None:
        self._pause_gate.set()

    @property
    def paused(self) -> bool:
        return not self._pause_gate.is_set()

    def _dispatch_gated(self, ctx, cmd):
        if not self._pause_gate.is_set():
            # bounded so a forgotten resume() degrades to a long stall, not
            # a permanently wedged worker pool
            self._pause_gate.wait(timeout=60.0)
        return REGISTRY.dispatch(self, ctx, cmd)

    def _fused_add_error_invalidate(self, track, run_names) -> None:
        """A failed fused BF.MADD64 run may have PARTIALLY applied (that is
        why add runs never re-dispatch) — tracked near caches holding
        negative `contains` entries for these filters must still be
        invalidated or they serve stale membership forever.  writer_ctx is
        None deliberately: the writer's client-side wrapper aborted on the
        error reply, so even a NOLOOP writer needs the push."""
        if track is not None and run_names:
            try:
                track.note_write(run_names, None)
            except Exception:  # noqa: BLE001 — never mask the primary error
                pass

    def _dispatch_bloom_run(self, ctx, cmds):
        """Coalesced execution of a same-verb BF blob run inside one frame
        (the adaptive coalescing plane): ONE stacked-bank kernel dispatch for
        the whole run instead of one per command, per-command LazyReplies
        riding the frame's single d2h gather.  Ineligible runs fall back to
        sequential per-command dispatch with identical semantics; an
        unexpected failure of the fused path falls back only for CONTAINS
        runs (read-only) — add runs reply per-command errors instead, so a
        possibly-applied mutation is never re-dispatched (at-most-once)."""
        from redisson_tpu.server.verbs.sketch import coalesce_bloom_run

        if not self._pause_gate.is_set():
            self._pause_gate.wait(timeout=60.0)
        cur = _obs.current_trace() if _obs._tracer is not None else None
        k0 = time.monotonic() if cur is not None else 0.0
        is_add = bytes(cmds[0][0]).upper() == b"BF.MADD64"
        # tracking hooks for the fused path (the fallback below re-dispatches
        # through REGISTRY.dispatch, which carries its own hooks): probe runs
        # register their filter names PRE-dispatch, add runs invalidate after
        # the fused kernel applied
        track = self.tracking if self.tracking.active else None
        run_names = None
        if track is not None:
            seen = set()
            run_names = [
                n for n in (bytes(c[1]).decode() for c in cmds)
                if not (n in seen or seen.add(n))
            ]
            if not is_add:
                track.note_read(ctx, run_names)
        try:
            fused = coalesce_bloom_run(self, ctx, cmds)
        except RuntimeError as e:
            if "shutdown" in str(e):
                # same contract as the per-command path: a stopping worker
                # pool drops the connection, never replies per-command errors
                raise ConnectionResetError(str(e)) from e
            if is_add:
                self._fused_add_error_invalidate(track, run_names)
                self.stats["errors"] += len(cmds)
                # a device fault mid-run replies retryably (-TRYAGAIN);
                # the possibly-applied run is NEVER re-dispatched here —
                # at-most-once is the client's to spend (ISSUE 19)
                enc = resp.encode_error(
                    _DEVICE_FAULT_TRYAGAIN
                    if ioplane.is_retryable_device_fault(e)
                    else f"ERR internal: {type(e).__name__}: {e}"
                )
                return [_Encoded(enc) for _ in cmds]
            fused = None
        except Exception as e:  # noqa: BLE001 — per-run isolation
            if is_add:
                self._fused_add_error_invalidate(track, run_names)
                self.stats["errors"] += len(cmds)
                enc = resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
                return [_Encoded(enc) for _ in cmds]
            fused = None
        if fused is not None:
            if cur is not None:
                # coalescer fan-in: ONE kernel span for the fused run, its
                # member commands recorded as child spans sharing the
                # kernel's interval (bounded so a 1000-command blob run
                # cannot bloat the trace)
                k1 = time.monotonic()
                cur.add_span(
                    "kernel", k0, k1,
                    verb=bytes(cmds[0][0]).upper().decode(),
                    members=len(cmds),
                )
                for c in cmds[:32]:
                    cur.add_span(
                        "kernel.member", k0, k1,
                        key=bytes(c[1]).decode(errors="replace"),
                    )
            if track is not None and is_add:
                track.note_write(run_names, ctx)
            return fused
        out = []
        for cmd in cmds:
            try:
                out.append(REGISTRY.dispatch(self, ctx, cmd))
            except RespError as e:
                self.stats["errors"] += 1
                out.append(_Encoded(resp.encode_error(str(e.args[0]))))
            except RuntimeError as e:
                if "shutdown" in str(e):
                    raise ConnectionResetError(str(e)) from e
                self.stats["errors"] += 1
                out.append(_Encoded(
                    resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
                ))
            except Exception as e:  # noqa: BLE001 — sandbox per-command
                self.stats["errors"] += 1
                out.append(_Encoded(
                    resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
                ))
        return out

    # -- device-sharded frame dispatch (ISSUE 8) ------------------------------

    def _ftvec_census(self) -> dict:
        """Embedding-bank residency rows ({ftvec_banks, ftvec_device_bytes})
        from the lazily-created search service; zeros while none exists."""
        svc = self.engine._services.get("search")
        zeros = {"ftvec_banks": 0.0, "ftvec_device_bytes": 0.0,
                 "ftvec_index_bytes": 0.0}
        if svc is None:
            return zeros
        try:
            # observe-only: a scrape must never fault a demoted bank back
            # onto the device (ISSUE 20) — a WARM bank reports 0 HBM bytes,
            # which is exactly what the ledger means
            from redisson_tpu.core import residency as _res

            with _res.no_promote():
                return svc.device_census()
        except Exception:  # noqa: BLE001 — a broken gauge must not kill scrape
            return zeros

    def _device_bytes_census(self) -> dict:
        """Per-device HBM residency over EVERY record kind (ISSUE 19
        satellite — the generalization of the ftvec_*_bytes_dev ledger):
        one store scan summing each record's committed device arrays by
        (device, kind).  Rows — ``record_bytes_dev<N>`` totals plus
        ``record_bytes_dev<N>_<kind>`` breakdowns — exist only while that
        device holds bytes, so DEL / FT.DROPINDEX drains them to absence
        == zero (the soak's flat-census assertion)."""
        from redisson_tpu.core.ioplane import _device_id_of

        by_dev: dict = {}
        by_kind: dict = {}
        try:
            records = self.engine.store.census_records()
        except Exception:  # noqa: BLE001 — a broken gauge must not kill scrape
            return {}
        for kind, rec in records:
            arrays = getattr(rec, "arrays", None)
            if not arrays:
                continue
            for arr in list(arrays.values()):
                d = _device_id_of(arr)
                if d is None:
                    continue
                n = float(getattr(arr, "nbytes", 0) or 0)
                if n <= 0.0:
                    continue
                by_dev[d] = by_dev.get(d, 0.0) + n
                by_kind[(d, kind)] = by_kind.get((d, kind), 0.0) + n
        out: dict = {}
        for d, v in sorted(by_dev.items()):
            out[f"record_bytes_dev{d}"] = v
        for (d, kind), v in sorted(by_kind.items()):
            out[f"record_bytes_dev{d}_{kind}"] = v
        return out

    def _residency_census(self) -> dict:
        """Per-tier residency rows (ISSUE 20): empty while the plane is
        disarmed so the gauge family contributes nothing to a scrape."""
        mgr = self.engine.residency
        if mgr is None:
            return {}
        try:
            return mgr.census()
        except Exception:  # noqa: BLE001 — a broken gauge must not kill scrape
            return {}

    def _residency_fence_check(self, name: str) -> bool:
        """True when ``name``'s slot is mid-migration on this node — the
        demoter must never touch a record the fenced journaled mover is
        about to snapshot (ISSUE 20 'fenced/migrating slots never demote')."""
        if not (self.migrating_slots or self.importing_slots
                or self.recovering_slots):
            return False
        from redisson_tpu.utils.crc16 import calc_slot

        slot = calc_slot(name.encode())
        return (slot in self.migrating_slots
                or slot in self.importing_slots
                or slot in self.recovering_slots)

    def enable_residency(self, **kw) -> None:
        """Arm the tiered-residency plane with the server's fences wired in
        (CONFIG SET residency-enabled yes / --residency boot path).  Under
        RTPU_NO_TIER=1 this is a refused no-op END TO END: set_tier(True)
        would be rejected, and a manager whose sweeper demotes while the
        getter guard stays disarmed would strand WARM records with no
        fault-in path."""
        from redisson_tpu.core import residency as _res

        if _res._NO_TIER:
            return
        self.engine.enable_residency(**kw)
        self.engine.residency.fence_check = self._residency_fence_check
        _res.set_tier(True)

    @staticmethod
    def _estimate_device_items(cmds) -> int:
        """Rough op count a command list dispatches to one device — the
        occupancy unit the per-device lane accounts (and, under the bench
        CPU-replica knob, the modeled per-chip compute time).  The sizing
        rule itself lives in server/scheduler.py (ISSUE 10) so lane
        accounting and tenant budgets cannot diverge."""
        return _sched.estimate_device_items(cmds)

    def _occupancy_gate(self, cmds, qos_class: Optional[str] = None):
        """Lane-occupancy context for one sequential-path dispatch (a single
        command or one same-verb coalesced run): the owning device's lane
        when every key maps to ONE device, else None (no gate).  This is how
        single-command frames — pipelined blobs bigger than one recv chunk
        arrive one command per parse batch — still account their device
        occupancy against the owning lane: dispatches from CONCURRENT
        connections bound for different devices overlap, same-device ones
        serialize, exactly like N per-chip streams."""
        lane = self._lane_for(cmds)
        if lane is None:
            return None
        if lane.quarantined:
            # a QUARANTINED lane rejects new keyed work retryably while its
            # slots evacuate / await a probe — never a dispatch into a
            # faulted device stream (ISSUE 19)
            raise RespError(_quarantined_tryagain(lane.dev_id))
        return lane.occupy(
            self._estimate_device_items(cmds), qos_class=qos_class,
            nbytes=_sched._frame_nbytes(cmds) if qos_class is not None else 0,
        )

    def _lane_for(self, cmds):
        """The one device lane every key of `cmds` maps to, else None
        (laneless or mixed-device: no occupancy gate)."""
        eng = self.engine
        if eng.placement is None or eng.lanes is None:
            return None
        dev = None
        for cmd in cmds:
            d = eng.placement.device_index_for_command(cmd)
            if d is None or (dev is not None and d != dev):
                return None
            dev = d
        if dev is None:
            return None
        return eng.lanes.lane(eng.placement.devices[dev])

    def _subwindow_target(self, qos_class: Optional[str]) -> int:
        """Effective bulk sub-window item target for one dispatch: >0 only
        with preemption armed, splitting configured, and a non-interactive
        dispatch (interactive frames ride the fast path whole)."""
        if qos_class == "interactive" or not ioplane.preempt_enabled():
            return 0
        return ioplane.bulk_subwindow_items()

    def _dispatch_laned(self, ctx, cmd, qos_class: Optional[str] = None,
                        trace=None):
        """Sequential-path single-command dispatch with lane accounting.
        `trace` (tracing armed only) is activated on this worker thread so
        lane/readback spans land on the frame; laneless dispatches record
        their own `dispatch` span (the lane gate records it otherwise)."""
        if trace is not None:
            _obs.set_current(trace)
        try:
            gate = self._occupancy_gate((cmd,), qos_class)
            if gate is None:
                if trace is not None:
                    t0 = time.monotonic()
                    try:
                        return self._dispatch_gated(ctx, cmd)
                    finally:
                        trace.add_span("dispatch", t0, time.monotonic())
                return self._dispatch_gated(ctx, cmd)
            with gate:
                return self._dispatch_gated(ctx, cmd)
        finally:
            if trace is not None:
                _obs.clear_current()

    def _dispatch_bloom_run_laned(self, ctx, cmds,
                                  qos_class: Optional[str] = None,
                                  trace=None):
        """Sequential-path coalesced run with lane accounting (a run whose
        filters span devices gets no gate — the coalescer itself falls back
        to per-record dispatch on a mixed-device group).

        Preemptible sub-windows (ISSUE 18): an oversized bulk run splits at
        command boundaries into chunks of at most qos-bulk-subwindow-items
        estimated device items, each chunk a SELF-CONTAINED fused dispatch
        — its own lane occupancy, its own record locks — with
        ``lane.preempt_point()`` between chunks so a waiting interactive
        frame jumps the inter-sub-window boundary instead of the drained
        window.  At-most-once survives splitting because a chunk is a
        complete fused add run: a failed chunk replies per-command errors
        and is never re-dispatched, while earlier chunks already applied
        and replied (the ``runs_within_admission`` sub-run shape).  Chunk
        replies extend in frame order, so per-connection FIFO and reply
        bytes are identical to the unsplit dispatch."""
        if trace is not None:
            _obs.set_current(trace)
        try:
            lane = self._lane_for(cmds)
            if lane is not None and lane.quarantined:
                # per-command retryable rejection (ISSUE 19): the run was
                # never dispatched, so at-most-once is trivially preserved
                self.stats["errors"] += len(cmds)
                enc = _Encoded(
                    resp.encode_error(_quarantined_tryagain(lane.dev_id))
                )
                return [enc for _ in cmds]
            if lane is None:
                if trace is not None:
                    t0 = time.monotonic()
                    try:
                        return self._dispatch_bloom_run(ctx, cmds)
                    finally:
                        trace.add_span("dispatch", t0, time.monotonic())
                return self._dispatch_bloom_run(ctx, cmds)
            target = self._subwindow_target(qos_class)
            chunks = None
            if target > 0:
                per = [_sched.estimate_command_items(c) for c in cmds]
                plan = plan_subwindows(per, target)
                if len(plan) > 1:
                    chunks = plan
            nb = _sched._frame_nbytes(cmds) if qos_class is not None else 0
            if chunks is None:
                with lane.occupy(self._estimate_device_items(cmds),
                                 qos_class=qos_class, nbytes=nb):
                    return self._dispatch_bloom_run(ctx, cmds)
            out = []
            for k, (s, e) in enumerate(chunks):
                if k:
                    lane.preempt_point()
                sub = cmds[s:e]
                with lane.occupy(
                    self._estimate_device_items(sub), qos_class=qos_class,
                    nbytes=(
                        _sched._frame_nbytes(sub)
                        if qos_class is not None else 0
                    ),
                ):
                    out.extend(self._dispatch_bloom_run(ctx, sub))
            return out
        finally:
            if trace is not None:
                _obs.clear_current()

    def _pool_for(self, adm):
        """Worker pool for one frame's dispatch: interactive-class frames
        (scheduler armed) run on the reserved interactive pool so a bulk
        flood occupying every shared worker can never queue ahead of them;
        everything else keeps the historical shared pool."""
        if adm is not None and adm.interactive:
            return self._qos_pool
        return self._pool

    def _dispatch_one_sync(self, ctx, cmd, trace=None):
        """One command, dispatched with the per-command error translation of
        the connection loop (RespError -> -ERR reply, shutdown -> drop the
        connection, anything else sandboxed per command).  `trace` (tracing
        armed only, serial-segment path) activates the frame's trace on
        this worker thread and records the handler window as `dispatch`."""
        if not isinstance(cmd, list) or not all(
            isinstance(a, (bytes, bytearray)) for a in cmd
        ):
            return _Encoded(resp.encode_error("ERR bad request frame"))
        if trace is not None:
            _obs.set_current(trace)
            t0 = time.monotonic()
            try:
                return self._dispatch_one_sync(ctx, cmd)
            finally:
                trace.add_span("dispatch", t0, time.monotonic())
                _obs.clear_current()
        try:
            return self._dispatch_gated(ctx, cmd)
        except RespError as e:
            self.stats["errors"] += 1
            return _Encoded(resp.encode_error(str(e.args[0])))
        except ConnectionResetError:
            raise
        except RuntimeError as e:
            if "shutdown" in str(e):
                raise ConnectionResetError(str(e)) from e
            self.stats["errors"] += 1
            if ioplane.is_retryable_device_fault(e):
                # device-layer fault (kernel launch, watchdog timeout):
                # clean retryable -TRYAGAIN, connection survives (ISSUE 19)
                return _Encoded(resp.encode_error(_DEVICE_FAULT_TRYAGAIN))
            return _Encoded(
                resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
            )
        except Exception as e:  # noqa: BLE001 — sandbox handler bugs per-command
            self.stats["errors"] += 1
            return _Encoded(
                resp.encode_error(f"ERR internal: {type(e).__name__}: {e}")
            )

    def _dispatch_device_bucket(self, ctx, dev_index: int, items,
                                qos_class: Optional[str] = None,
                                trace=None):
        """One device's ordered slice of a pipelined frame (placement
        plan_frame 'sharded' segment): runs on a worker thread WHILE the
        other devices' buckets run on theirs — the per-chip dispatch lanes
        of device-sharded serving.  Same-verb BF blob runs inside the
        bucket still coalesce into one stacked-bank kernel (now guaranteed
        single-device).  Returns [(frame_index, result), ...]."""
        if trace is not None:
            _obs.set_current(trace)
            try:
                return self._dispatch_device_bucket(
                    ctx, dev_index, items, qos_class
                )
            finally:
                _obs.clear_current()
        if not self._pause_gate.is_set():
            self._pause_gate.wait(timeout=60.0)
        eng = self.engine
        lane = (
            eng.lanes.lane(eng.placement.devices[dev_index])
            if eng.lanes is not None else None
        )
        if lane is not None and lane.quarantined:
            # the whole bucket rejects retryably in frame position —
            # the other devices' buckets still serve (ISSUE 19)
            self.stats["errors"] += len(items)
            enc = _Encoded(
                resp.encode_error(_quarantined_tryagain(lane.dev_id))
            )
            return [(i, enc) for i, _c in items]
        cmds = [c for _i, c in items]
        out = []
        run_at: Dict[int, int] = (
            dict(_routing.coalescible_frame_runs(cmds)) if len(cmds) > 1 else {}
        )
        from contextlib import nullcontext

        def dispatch_span(lo: int, hi: int) -> None:
            ci = lo
            while ci < hi:
                run_end = run_at.get(ci)
                if run_end is not None:
                    replies = self._dispatch_bloom_run(ctx, cmds[ci:run_end])
                    for off, r in enumerate(replies):
                        out.append((items[ci + off][0], r))
                    ci = run_end
                    continue
                out.append((items[ci][0], self._dispatch_one_sync(ctx, cmds[ci])))
                ci += 1

        # preemptible sub-windows (ISSUE 18): an oversized bucket splits its
        # ONE bucket-wide occupancy into per-segment gates with a lane
        # preemption point between segments.  Segments cut at dispatch-unit
        # boundaries — one coalesced run or one single command — so a fused
        # add run is never split mid-apply (at-most-once).
        target = self._subwindow_target(qos_class) if lane is not None else 0
        segs = None
        if target > 0:
            units: List[Tuple[int, int]] = []
            ci = 0
            while ci < len(cmds):
                run_end = run_at.get(ci)
                units.append((ci, run_end) if run_end is not None
                             else (ci, ci + 1))
                ci = units[-1][1]
            unit_items = [
                self._estimate_device_items(cmds[s:e]) for s, e in units
            ]
            plan = plan_subwindows(unit_items, target)
            if len(plan) > 1:
                segs = [(units[lo][0], units[hi - 1][1]) for lo, hi in plan]
        if segs is not None:
            for k, (s, e) in enumerate(segs):
                if k:
                    lane.preempt_point()
                seg_cmds = cmds[s:e]
                with lane.occupy(
                    self._estimate_device_items(seg_cmds),
                    qos_class=qos_class,
                    nbytes=(
                        _sched._frame_nbytes(seg_cmds)
                        if qos_class is not None else 0
                    ),
                ):
                    dispatch_span(s, e)
            return out

        gate = (
            lane.occupy(
                self._estimate_device_items(cmds), qos_class=qos_class,
                nbytes=(
                    _sched._frame_nbytes(cmds) if qos_class is not None else 0
                ),
            )
            if lane is not None else nullcontext()
        )
        with gate:
            dispatch_span(0, len(cmds))
        return out

    async def _run_frame_sharded(self, ctx, commands, plan, loop, adm=None,
                                 trace=None):
        """Execute one pipelined frame under a placement plan: 'sharded'
        segments fan their per-device buckets out on the worker pool
        CONCURRENTLY (each bucket FIFO on its device lane — per-key order
        is preserved because a key maps to exactly one device), 'serial'
        segments run in frame order as barriers.  Reply order is by frame
        index regardless of completion order."""
        qos_class = adm.qos_class if adm is not None else None
        results: list = [None] * len(commands)
        for seg_kind, seg in plan:
            if seg_kind == "serial":
                for i in seg:
                    cmd = commands[i]
                    self.stats["commands"] += 1
                    pool = (
                        self._slow_pool
                        if (
                            isinstance(cmd, list) and cmd
                            and isinstance(cmd[0], (bytes, bytearray))
                            and bytes(cmd[0]).upper() in _SLOW_COMMANDS
                        )
                        else self._pool_for(adm)
                    )
                    results[i] = await loop.run_in_executor(
                        pool, self._dispatch_one_sync, ctx, cmd, trace
                    )
                continue
            jobs = []
            for dev_index, idxs in seg.items():
                self.stats["commands"] += len(idxs)
                jobs.append(loop.run_in_executor(
                    self._pool_for(adm), self._dispatch_device_bucket, ctx,
                    dev_index, [(i, commands[i]) for i in idxs], qos_class,
                    trace,
                ))
            outs = await asyncio.gather(*jobs, return_exceptions=True)
            err = next((o for o in outs if isinstance(o, BaseException)), None)
            if err is not None:
                raise err
            for out in outs:
                for i, r in out:
                    results[i] = r
        return results

    def replication_source(self):
        """Lazy master-side record shipper (server/replication.py)."""
        from redisson_tpu.server.replication import ReplicationSource

        with self._repl_lock:
            if self._replication is None:
                self._replication = ReplicationSource(self)
            return self._replication

    def info_text(self) -> str:
        up = int(time.time() - self.started_at)
        return (
            "# Server\r\n"
            f"redis_version:7.2.0-rtpu\r\nrun_id:{self.node_id}\r\n"
            f"tcp_port:{self.port}\r\nuptime_in_seconds:{up}\r\nmode:{self.mode}\r\n"
            "# Clients\r\n"
            f"connected_clients:{self.stats['connections']}\r\n"
            "# Stats\r\n"
            f"total_commands_processed:{self.stats['commands']}\r\n"
            f"errors:{self.stats['errors']}\r\n"
            "# Keyspace\r\n"
            f"db0:keys={len(self.engine.store)},expires=0\r\n"
        )

    def commandstats_text(self) -> str:
        """INFO commandstats section (Redis parity): per-verb
        calls/usec/usec_per_call, sourced from the MetricsRegistry's
        ``command.<verb>`` timers (the MetricsHook records every dispatched
        command there already — no second accounting plane)."""
        lines = ["# Commandstats"]
        with self.metrics._lock:
            timers = sorted(self.metrics._timers.items())
        for name, t in timers:
            if not name.startswith("command."):
                continue
            verb = name[len("command."):]
            usec = int(t.total_s * 1e6)
            per = usec / t.count if t.count else 0.0
            lines.append(
                f"cmdstat_{verb}:calls={t.count},usec={usec},"
                f"usec_per_call={per:.2f}"
            )
        return "\r\n".join(lines) + "\r\n"

    # -- QoS admission (ISSUE 10: deadline classes + per-tenant budgets) ------

    def _bulk_gate_for(self, slots: int) -> Optional[asyncio.Semaphore]:
        """The server-wide bulk admission gate: at most `slots` bulk-class
        frames may be in dispatch at once across ALL connections, so a bulk
        flood can never occupy every worker ahead of interactive traffic.
        Rebuilt when CONFIG SET qos-bulk-slots changes the count (holders of
        the old gate release into the old gate — each frame releases exactly
        the object it acquired)."""
        if slots <= 0:
            return None
        gate = self._bulk_gate
        if gate is None or self._bulk_gate_n != slots:
            gate = self._bulk_gate = asyncio.Semaphore(slots)
            self._bulk_gate_n = slots
        return gate

    async def _serve_frame(self, ctx, commands, loop, write_q,
                           readback_slots, alive, trace=None) -> bool:
        """Admit + dispatch ONE parsed frame (the read loop's per-frame
        body).  Returns False when the connection must stop reading (writer
        task dead).  With the scheduler armed the frame is classified
        (interactive/bulk) and charged against its tenant's token bucket
        BEFORE anything dispatches: over-budget commands shed with -BUSY
        (never any queue residency), bulk frames pass the bounded bulk
        admission gate, and the frame's dispatch is accounted on the
        per-class in-flight ledger for its whole residency.  `trace`
        (tracing armed only) records admit + bulk-gate wait as the frame's
        `qos` span, annotated tenant/class/items/shed."""
        sched = self.scheduler
        adm = None
        bulk_gate = None
        acquired = begun = False
        tq0 = time.monotonic() if trace is not None else 0.0
        if (
            sched.armed
            and commands
            and ctx.authenticated
            and ctx.multi_queue is None
        ):
            adm = sched.admit(ctx, commands)
            if adm.shed_count:
                self.stats["sheds"] += adm.shed_count
        fully_shed = (
            adm is not None
            and adm.shed_mask is not None
            and all(adm.shed_mask)
        )
        try:
            if adm is not None:
                # a FULLY-refused frame never dispatches (its replies are
                # pure encodes), so it must not occupy a bulk admission
                # slot — holding one through the shed path would give the
                # over-budget tenant's refusals queue residency that delays
                # in-budget bulk tenants
                if not adm.interactive and not fully_shed:
                    bulk_gate = self._bulk_gate_for(sched.bulk_slots)
                    if bulk_gate is not None:
                        sched.ledger.wait_enter()
                        try:
                            await bulk_gate.acquire()
                            acquired = True
                        finally:
                            sched.ledger.wait_exit()
                sched.begin(adm)
                begun = True
                if trace is not None:
                    # classification + tenant charge + bulk-gate wait: the
                    # span that attributes "my frame sat behind admission"
                    trace.qos_class = adm.qos_class
                    trace.tenant = adm.tenant
                    trace.add_span(
                        "qos", tq0, time.monotonic(),
                        tenant=adm.tenant, cls=adm.qos_class,
                        items=adm.items, shed=adm.shed_count,
                    )
            ok = await self._dispatch_frame(
                ctx, commands, loop, write_q, readback_slots, alive, adm,
                trace,
            )
        finally:
            if begun:
                sched.end(adm)
            if acquired:
                bulk_gate.release()
        if ok and fully_shed and sched.shed_penalty_ms > 0:
            # fully-refused frame: park THIS connection's read loop for the
            # shed penalty (replies already flushed, every gate/ledger hold
            # already released) — a client that spins on -BUSY cannot turn
            # the cheap shed path into a parse-plane DoS; nobody else's
            # traffic is delayed
            await asyncio.sleep(sched.shed_penalty_ms / 1000.0)
        return ok

    async def _dispatch_frame(self, ctx, commands, loop, write_q,
                              readback_slots, alive, adm=None,
                              trace=None) -> bool:
        # Two-phase frame execution: dispatch every command of the
        # pipelined frame first (handlers may return LazyReply —
        # device work enqueued, NOT forced), then force all lazy
        # replies together and write the replies in order.  One
        # device->host sync per frame instead of per command; per-
        # connection ordering is untouched (dispatch stays
        # sequential, and the device stream is in-order).
        # Same-verb BF blob RUNS additionally collapse into one
        # fused kernel dispatch each (_dispatch_bloom_run — the
        # coalescing plane; runs never cross a verb change, so
        # frame order is preserved exactly).
        # Device-sharded frame plan (ISSUE 8): with the slot table
        # placed over >1 device, the frame's single-device keyed
        # data commands split into per-device queues dispatched
        # CONCURRENTLY (one worker per device lane) instead of
        # serializing through one lane; everything else barriers in
        # frame order.  plan is None when there is nothing to shard
        # — the sequential loop below is byte-identical to before.
        qos_class = adm.qos_class if adm is not None else None
        shed_mask = adm.shed_mask if adm is not None else None
        shed_enc = (
            resp.encode_error(_sched.busy_error(adm.tenant))
            if shed_mask is not None else None
        )
        plan = None
        if (
            self.engine.placement is not None
            and ctx.multi_queue is None
            and ctx.authenticated
            and not ctx.asking
            and shed_mask is None  # a partially-shed frame stays sequential
            and len(commands) > 1
        ):
            try:
                # with the CPU-replica occupancy model armed (bench
                # config5d A/B), even a 1-device frame runs the lane
                # dispatch path so both legs execute identical code
                plan = self.engine.placement.plan_frame(
                    commands,
                    single_device_ok=(
                        ioplane.replica_occupancy() is not None
                    ),
                )
            except Exception:  # noqa: BLE001 — planning must never
                plan = None    # break a frame; fall back to serial
        if plan is not None:
            results = await self._run_frame_sharded(
                ctx, commands, plan, loop, adm, trace
            )
            if any(isinstance(r, LazyReply) for r in results):
                if self.overlap:
                    await readback_slots.acquire()
                    if not alive["writer"]:
                        return False
                    if trace is not None:
                        trace.mark_dispatched()
                    fut = loop.run_in_executor(
                        self._pool_for(adm), _force_lazies, results, self,
                        trace,
                    )
                    write_q.put_nowait(
                        _PendingFrame(results, fut, ctx.proto, trace)
                    )
                    return True
                await loop.run_in_executor(
                    self._pool_for(adm), _force_lazies, results, self, trace
                )
            if results:
                if trace is not None:
                    trace.mark_dispatched()
                    write_q.put_nowait(_TracedEncoded(
                        _encode_frame(results, ctx.proto), trace
                    ))
                else:
                    write_q.put_nowait(_encode_frame(results, ctx.proto))
            return True
        run_at: Dict[int, int] = {}
        if len(commands) > 1:
            runs = [
                (s, e)
                for s, e in _routing.coalescible_frame_runs(commands)
                if all(
                    isinstance(a, (bytes, bytearray))
                    for c in commands[s:e]
                    for a in c
                )
            ]
            # QoS shed boundary (ISSUE 10): a run never spans a shed
            # command — the fused window covers ADMITTED ops only, so a
            # partially-applied coalesced add run can never be created by
            # (or re-dispatched after) a shed decision
            run_at = dict(runs_within_admission(runs, shed_mask))
        results = []
        ci = -1
        for cmd in commands:
            ci += 1
            if len(results) > ci:
                continue  # covered by an already-dispatched run
            if shed_mask is not None and shed_mask[ci]:
                # load-shed: -BUSY in frame position, NO dispatch, no
                # queue residency (the reply FIFO is untouched — the
                # error encodes exactly where the command's reply goes)
                results.append(_Encoded(shed_enc))
                continue
            run_end = run_at.get(ci)
            if run_end is not None:
                run_cmds = commands[ci:run_end]
                self.stats["commands"] += len(run_cmds)
                results.extend(
                    await loop.run_in_executor(
                        self._pool_for(adm), self._dispatch_bloom_run_laned,
                        ctx, run_cmds, qos_class, trace,
                    )
                )
                continue
            if not isinstance(cmd, list) or not all(
                isinstance(a, (bytes, bytearray)) for a in cmd
            ):
                results.append(_Encoded(resp.encode_error("ERR bad request frame")))
                continue
            self.stats["commands"] += 1
            # OBJCALL (user methods may park) and blocking verbs go
            # to the wide slow pool: a parked handler must never
            # starve the small fast pool every connection shares
            pool = (
                self._slow_pool
                if bytes(cmd[0]).upper() in _SLOW_COMMANDS
                else self._pool_for(adm)
            )
            try:
                results.append(
                    await loop.run_in_executor(
                        pool, self._dispatch_laned, ctx, cmd, qos_class,
                        trace,
                    )
                )
            except RespError as e:
                self.stats["errors"] += 1
                results.append(_Encoded(resp.encode_error(str(e.args[0]))))
            except ConnectionResetError:
                raise
            except RuntimeError as e:
                if "shutdown" in str(e):  # worker pool stopped: drop conn
                    raise ConnectionResetError(str(e)) from e
                # any other RuntimeError (uninitialized object, state
                # errors) is a per-command failure — reply -ERR, keep
                # the connection (dropping it would kill every other
                # pipelined command on this socket)
                self.stats["errors"] += 1
                results.append(_Encoded(resp.encode_error(
                    _DEVICE_FAULT_TRYAGAIN
                    if ioplane.is_retryable_device_fault(e)
                    else f"ERR internal: {type(e).__name__}: {e}"
                )))
            except Exception as e:  # noqa: BLE001 — sandbox handler bugs per-command
                self.stats["errors"] += 1
                results.append(
                    _Encoded(resp.encode_error(f"ERR internal: {type(e).__name__}: {e}"))
                )
        if any(isinstance(r, LazyReply) for r in results):
            if self.overlap:
                # overlap plane: hand the readback to the writer task
                # as a completion-queue entry and go straight back to
                # reading — frame N+1's upload/dispatch overlaps this
                # frame's D2H.  FIFO queue order preserves the reply
                # order; proto is snapshotted at dispatch time.
                await readback_slots.acquire()
                if not alive["writer"]:
                    return False  # connection is going down; stop dispatching
                if trace is not None:
                    trace.mark_dispatched()
                fut = loop.run_in_executor(
                    self._pool_for(adm), _force_lazies, results, self, trace
                )
                write_q.put_nowait(_PendingFrame(results, fut, ctx.proto,
                                                 trace))
                return True
            await loop.run_in_executor(
                self._pool_for(adm), _force_lazies, results, self, trace
            )
        if results:
            # one queue item per frame — the whole frame's replies
            # encode in one pass and write in one syscall batch
            if trace is not None:
                trace.mark_dispatched()
                write_q.put_nowait(_TracedEncoded(
                    _encode_frame(results, ctx.proto), trace
                ))
            else:
                write_q.put_nowait(_encode_frame(results, ctx.proto))
        return True

    # -- asyncio plumbing ----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.stats["connections"] += 1
        self._writers.add(writer)
        ctx = CommandContext(self)
        self.tracking.register_conn(ctx)
        parser = resp.RespParser()
        loop = asyncio.get_running_loop()
        write_q: asyncio.Queue = asyncio.Queue()

        def push(msg) -> None:
            # pubsub listeners fire on engine threads; hop to the loop
            # (encoded with THIS connection's negotiated protocol)
            loop.call_soon_threadsafe(
                write_q.put_nowait, resp.encode_reply(msg, ctx.proto)
            )

        ctx.push = push

        # dispatch-ahead bound (overlap plane): the read loop may run at most
        # `readback_ahead` frames ahead of the slowest un-written readback
        # (snapshotted at accept time so a mid-connection CONFIG SET
        # dispatch-ahead cannot skew this connection's acquire/release pairing)
        readback_ahead = max(1, self.readback_ahead)
        readback_slots = asyncio.Semaphore(readback_ahead)
        # shared liveness flag (writer task -> read loop/_serve_frame)
        alive = {"writer": True}

        async def writer_task():
            # The completion queue drain: items are pre-encoded bytes (pubsub
            # pushes, readback-free frames — these flush immediately) or
            # _PendingFrame readback futures (awaited HERE, off the read
            # loop, so the next frame's upload and dispatch overlap this
            # frame's D2H readback).  The queue is FIFO and this task writes
            # strictly in pop order, so per-connection reply ordering and
            # RESP framing are preserved exactly.
            #
            # Aggregated writes: everything drained from one queue pass —
            # coalesced frames AND resolved readback frames — is joined and
            # written as a SINGLE transport.write (one syscall per drained
            # batch instead of per frame).  An unresolved readback only ever
            # delays bytes queued BEHIND it, never ones already collected.
            #
            # Tracing (armed only): traced items carry their FrameTrace;
            # once the batch's bytes are written+drained each trace closes
            # its `reply` span HERE — the trace total is therefore the true
            # client-observable latency.  A trace whose bytes never reach
            # the wire (pool death, connection error) is abandoned so the
            # inflight census row still drains.
            held = None  # a _PendingFrame popped while coalescing bytes
            try:
                while True:
                    item = held if held is not None else await write_q.get()
                    held = None
                    if item is None:
                        return
                    parts: list = []
                    done_tr = None  # traces of this batch (armed only)
                    final = False
                    while True:
                        if isinstance(item, _PendingFrame):
                            if parts and not item.fut.done():
                                # flush what's ready; await this one next pass
                                held = item
                                break
                            try:
                                await item.fut  # the overlapped readback
                            except Exception:  # noqa: BLE001 — pool died mid-force
                                # tear the connection DOWN, like the serial
                                # path's in-loop exception would: a silent
                                # return leaves the read loop dispatching into
                                # a dead queue and the client blocked on recv
                                # with no EOF
                                if item.trace is not None:
                                    _obs.TRACER.abandon(item.trace)
                                if done_tr is not None:
                                    for t in done_tr:
                                        _obs.TRACER.abandon(t)
                                try:
                                    writer.close()
                                except Exception:  # noqa: BLE001
                                    pass
                                return
                            finally:
                                readback_slots.release()
                            parts.append(item.encoded())
                            if item.trace is not None:
                                if done_tr is None:
                                    done_tr = []
                                done_tr.append(item.trace)
                        elif isinstance(item, _TracedEncoded):
                            parts.append(item.data)
                            if done_tr is None:
                                done_tr = []
                            done_tr.append(item.trace)
                        else:
                            parts.append(item)
                        if write_q.empty():
                            break
                        nxt = write_q.get_nowait()
                        if nxt is None:
                            final = True
                            break
                        item = nxt
                    if parts:
                        writer.write(parts[0] if len(parts) == 1 else b"".join(parts))
                        try:
                            await writer.drain()
                        except ConnectionError:
                            if done_tr is not None:
                                for t in done_tr:
                                    _obs.TRACER.abandon(t)
                            return
                        if done_tr is not None:
                            for t in done_tr:
                                _obs.TRACER.finish_reply(t)
                    if final:
                        return
            finally:
                alive["writer"] = False
                # un-stick a read loop parked on the dispatch-ahead bound
                for _ in range(readback_ahead):
                    readback_slots.release()

        wt = asyncio.create_task(writer_task())
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    break
                # tracing (observe/trace.py): frames are stamped AT PARSE
                # TIME — trace id + monotonic t0 — and the stamp rides the
                # frame through every chokepoint.  Disarmed cost: one
                # module-global load + `is not None` per read.  None (not
                # 0.0) is the disarmed sentinel: arming between this read
                # and the begin_frame guard must not anchor a trace at
                # monotonic zero (a garbage uptime-long total).
                t_parse0 = (
                    time.monotonic() if _obs._tracer is not None else None
                )
                try:
                    commands = parser.feed(data)
                except ProtocolError as e:
                    write_q.put_nowait(resp.encode_error(f"ERR protocol error: {e}"))
                    break
                trace = None
                if _obs._tracer is not None and commands:
                    trace = _obs._tracer.begin_frame(
                        ctx, commands, t0=t_parse0
                    )
                    if self.role == "replica":
                        # per-stage replica annotation (ISSUE 17): every
                        # span of a replica-served frame carries replica=1
                        trace.base_attrs = {"replica": 1}
                try:
                    ok = await self._serve_frame(
                        ctx, commands, loop, write_q, readback_slots, alive,
                        trace,
                    )
                except BaseException:
                    # frame died before its replies were queued: close the
                    # trace's books so the inflight census row drains
                    if trace is not None and not trace.finished:
                        _obs.TRACER.abandon(trace)
                    raise
                if not ok:
                    if trace is not None and not trace.finished:
                        _obs.TRACER.abandon(trace)
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError, BrokenPipeError):
            pass
        finally:
            # tracking disconnect-cleanup FIRST: the table must not leak this
            # conn's keys, and dependents redirecting here must break loudly
            self.tracking.unregister_conn(ctx)
            for ch, lid in list(ctx.subscriptions.items()):
                self.engine.pubsub.unsubscribe(ch, lid)
            for pat, lid in list(ctx.psubscriptions.items()):
                self.engine.pubsub.punsubscribe(pat, lid)
            write_q.put_nowait(None)
            await wt
            # traced frames still queued behind the writer's death never
            # reached the wire: abandon them so trace_inflight drains
            while not write_q.empty():
                leftover = write_q.get_nowait()
                t = getattr(leftover, "trace", None)
                if t is not None and not t.finished:
                    _obs.TRACER.abandon(t)
            self._writers.discard(writer)
            self.stats["connections"] -= 1
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    @property
    def tls_enabled(self) -> bool:
        return self.tls_cert_file is not None

    def _server_ssl_context(self):
        if not self.tls_enabled:
            return None
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.tls_cert_file, self.tls_key_file)
        if self.tls_ca_file:
            ctx.load_verify_locations(self.tls_ca_file)
            ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
        return ctx

    def link_client(self, address: str, **kw):
        """NodeClient for this node's OUTGOING links (slot drains, replica
        sync): inherits the node's password and, when TLS is on, a client
        context trusting the cluster CA (hostname checks off — cluster
        peers are addressed by IP)."""
        from redisson_tpu.net.client import NodeClient, client_ssl_context

        kw.setdefault("password", self.password)
        if self.tls_enabled:
            kw.setdefault(
                "ssl_context",
                client_ssl_context(
                    # self-signed deployments (no separate CA) trust the
                    # shared node cert itself — same fallback as
                    # ServerThread.client(); without it REPLSNAPSHOT and
                    # IMPORTRECORDS links die on SSLCertVerificationError
                    ca_file=self.tls_ca_file or self.tls_cert_file,
                    cert_file=self.tls_cert_file,
                    key_file=self.tls_key_file,
                    verify_hostname=False,
                ),
            )
        return NodeClient(address, **kw)

    async def start_async(self):
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, reuse_address=True,
            ssl=self._server_ssl_context(),
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def serve_forever(self):
        await self.start_async()
        async with self._server:
            await self._server.serve_forever()

    async def serve_until_signal(self, ready_fd: Optional[int] = None,
                                 journal_dir: Optional[str] = None):
        """CLI serve loop: run until SIGTERM **or** SIGINT — both are the
        graceful path (supervisors send SIGTERM; only the SIGINT/Ctrl-C
        route used to reach the AutoCheckpointer flush-on-stop, which left
        SIGTERM'd deployments losing their last interval of writes).

        ``ready_fd``: once the listener is bound (port 0 resolved), write
        one line — ``READY <host> <port> <pid>`` — to this inherited file
        descriptor and close it.  The ClusterSupervisor awaits that line
        instead of sleep-polling the port (cluster/supervisor.py)."""
        import os
        import signal as _signal

        loop = asyncio.get_running_loop()
        stopped = asyncio.Event()
        installed = []
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stopped.set)
                installed.append(sig)
            except (NotImplementedError, RuntimeError):  # non-main thread /
                pass                                     # exotic loop
        await self.start_async()
        if journal_dir is not None:
            # the node's import journals live here too (ISSUE 13): the
            # IMPORTRECORDS handler needs the dir armed before serving
            self.journal_dir = journal_dir
        if self.journal_dir is not None:
            # BEFORE the ready line goes out (supervised clients gate on
            # it): re-arm migration windows this node was a party to when
            # it last died — restored copies of mid-migration slots must
            # answer TRYAGAIN, not serve a forked lineage — and replay the
            # import journals whose batches this node acked but may have
            # lost with its memory (migration.rearm_recovery)
            from redisson_tpu.server.migration import rearm_recovery

            rearm_recovery(self, self.journal_dir)
        if ready_fd is not None:
            line = f"READY {self.public_host} {self.port} {os.getpid()}\n".encode()
            try:
                os.write(ready_fd, line)
            finally:
                try:
                    os.close(ready_fd)
                except OSError:
                    pass
        try:
            async with self._server:
                await stopped.wait()
        finally:
            for sig in installed:
                loop.remove_signal_handler(sig)
            self.stop()

    def stop(self):
        # parked blocking verbs (_block_loop, WAIT) poll this to unpark:
        # a forever-blocked worker would otherwise survive pool shutdown
        # (wait=False) and hang interpreter exit via the futures atexit join
        self._closing = True
        self._pause_gate.set()  # release chaos-paused workers
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            def shutdown():
                server.close()
                # drop established connections too: clients must see a dead
                # node, not a half-alive one (failover tests depend on this)
                for w in list(self._writers):
                    try:
                        w.close()
                    except Exception:  # noqa: BLE001
                        pass

            try:
                loop.call_soon_threadsafe(shutdown)
            except RuntimeError:
                pass  # loop already closed (repeated stop): nothing to do
        if self._replication is not None:
            self._replication.close()
        self._pool.shutdown(wait=False)
        self._qos_pool.shutdown(wait=False)
        self._slow_pool.shutdown(wait=False)


def _encode_result(result, proto: int = 3) -> bytes:
    if isinstance(result, str) and result.startswith("+"):
        return resp.encode_simple(result[1:])
    if isinstance(result, list) and result and all(isinstance(r, resp.Push) for r in result):
        # subscribe-style confirmations: stream of push frames
        return b"".join(resp.encode_reply(r, proto) for r in result)
    return resp.encode_reply(result, proto)


def _encode_frame(results: list, proto: int) -> bytes:
    """Encode a whole frame's replies as ONE byte string.  Runs of plain
    values ride a single resp.encode_replies emit (one native arena write
    for the run — the aggregated-write path); pre-encoded errors and the
    two special result forms (`+simple` strings, push-frame lists) keep
    their _encode_result semantics, in place, in order."""
    parts: list = []
    run: list = []
    flush = parts.append
    for r in results:
        if isinstance(r, _Encoded):
            if run:
                flush(resp.encode_replies(run, proto))
                run = []
            flush(r.data)
        elif isinstance(r, str) and r.startswith("+"):
            if run:
                flush(resp.encode_replies(run, proto))
                run = []
            flush(resp.encode_simple(r[1:]))
        elif isinstance(r, list) and r and isinstance(r[0], resp.Push):
            if run:
                flush(resp.encode_replies(run, proto))
                run = []
            flush(_encode_result(r, proto))
        else:
            run.append(r)
    if run:
        flush(resp.encode_replies(run, proto))
    if len(parts) == 1:
        return parts[0]
    return b"".join(parts)


class ServerThread:
    """In-process server on a daemon thread — the embedded-test harness
    (RedisRunner analog for hermetic tests, SURVEY.md §4 lesson)."""

    def __init__(self, engine: Optional[Engine] = None, port: int = 0, **kw):
        self.server = TpuServer(engine=engine, port=port, **kw)
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    def __enter__(self) -> "ServerThread":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def start(self) -> "ServerThread":
        def run():
            async def main():
                await self.server.start_async()
                self._started.set()
                async with self.server._server:
                    try:
                        await self.server._server.serve_forever()
                    except asyncio.CancelledError:
                        pass

            asyncio.run(main())

        self._thread = threading.Thread(target=run, daemon=True, name="rtpu-server")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("server failed to start")
        return self

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def address(self) -> str:
        scheme = "tpus" if self.server.tls_enabled else "tpu"
        return f"{scheme}://{self.server.host}:{self.server.port}"

    def stop(self):
        self.server.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def client(self):
        """One-shot admin connection (context manager) to this node — speaks
        TLS when the node does (trusting the node's own CA/cert chain)."""
        from contextlib import closing

        from redisson_tpu.net.client import Connection, client_ssl_context

        ssl_ctx = None
        if self.server.tls_enabled:
            ssl_ctx = client_ssl_context(
                ca_file=self.server.tls_ca_file or self.server.tls_cert_file,
                cert_file=self.server.tls_cert_file if self.server.tls_ca_file else None,
                key_file=self.server.tls_key_file if self.server.tls_ca_file else None,
                verify_hostname=False,
            )
        return closing(
            Connection(
                self.server.host,
                self.server.port,
                timeout=120.0,
                password=self.server.password,
                ssl_context=ssl_ctx,
            )
        )


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description="redisson-tpu server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6390)
    ap.add_argument(
        "--advertise-host", default=None,
        help="the routable address this node is named by in cluster views, "
             "migration journals, and its READY line when it differs from "
             "the bind --host (cross-host nodes bind 0.0.0.0; without this "
             "a node would MOVED-bounce its own slots)",
    )
    ap.add_argument("--password", default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--restore", action="store_true", help="load checkpoint on boot")
    ap.add_argument(
        "--checkpoint-interval", type=float, default=0.0,
        help="seconds between automatic snapshots (0 = manual SAVE only)",
    )
    ap.add_argument("--platform", default=None, help="force jax platform (cpu/tpu)")
    ap.add_argument(
        "--prewarm", action="store_true",
        help="precompile hot kernels for restored records at boot "
             "(core/warmpool — keeps the first request's latency clean)",
    )
    ap.add_argument(
        "--no-overlap", action="store_true",
        help="disable the overlapped device I/O plane (core/ioplane): "
             "flushes run strictly stage->dispatch->fetch and every frame's "
             "readback blocks its connection's read loop — the serial "
             "reference path for A/B measurement",
    )
    ap.add_argument(
        "--no-tier", action="store_true",
        help="disable the tiered-HBM residency plane (core/residency): "
             "every record stays HOT on its owner device, no demotion or "
             "fault-in ever runs — the reference path for A/B measurement "
             "(RTPU_NO_TIER=1 equivalent; replies are bit-identical)",
    )
    ap.add_argument(
        "--residency", action="store_true",
        help="arm the tiered-HBM residency plane at boot (cold records "
             "demote to host RAM / spill under the per-device "
             "device-budget-bytes budget and fault back in on first touch; "
             "also CONFIG SET residency-enabled yes)",
    )
    ap.add_argument(
        "--workers", type=int, default=4,
        help="data-plane worker threads (the per-connection dispatch pool)",
    )
    ap.add_argument(
        "--no-qos", action="store_true",
        help="disable the deadline-aware window scheduler / per-tenant QoS "
             "plane (server/scheduler.py): frames dispatch in pure arrival "
             "order with no classification, budgets, or load shedding — the "
             "reference path for A/B measurement (RTPU_NO_QOS=1 equivalent)",
    )
    ap.add_argument(
        "--no-preempt", action="store_true",
        help="disable the bulk-window preemption plane (ISSUE 18): no "
             "sub-window splitting, no per-class device streams — every "
             "dispatch serializes through the single per-lane gate exactly "
             "as PR 9 shipped, the reference path for A/B measurement "
             "(RTPU_NO_PREEMPT=1 equivalent)",
    )
    ap.add_argument(
        "--dispatch-ahead", type=int, default=None,
        help="per-connection dispatch-ahead bound: how many frames may sit "
             "between 'dispatched' and 'replies written' on one connection "
             "(bounds device memory held by un-drained readbacks; also "
             "CONFIG SET dispatch-ahead).  Default: 2.",
    )
    ap.add_argument(
        "--devices", default=None,
        help="device-sharded serving (ISSUE 8): map the 16384-slot table "
             "onto this many local devices ('all' = every jax.local_device); "
             "each object's banks live on the device owning its slot and "
             "frames dispatch down per-device lanes.  Default: one device "
             "(no placement).",
    )
    ap.add_argument(
        "--ready-fd", type=int, default=None,
        help="inherited fd to write one 'READY <host> <port> <pid>' line to "
             "once the listener is bound (the ClusterSupervisor's readiness "
             "protocol; with --port 0 this reports the kernel-chosen port)",
    )
    ap.add_argument(
        "--journal-dir", default=None,
        help="migration-journal directory to consult at boot: in-flight "
             "migrations naming this node re-arm their windows and fence "
             "their slots RECOVERING until resume_migrations settles them "
             "(the crashed-node restart discipline, migration.rearm_recovery)",
    )
    ap.add_argument(
        "--tls-cert", default=None,
        help="PEM certificate: enables TLS on the listener (with --tls-key) "
             "and on this node's OUTGOING cluster links (migration/"
             "replication) — the cross-host bus discipline: plaintext "
             "clients are refused at the handshake",
    )
    ap.add_argument("--tls-key", default=None,
                    help="PEM private key for --tls-cert")
    ap.add_argument(
        "--tls-ca", default=None,
        help="PEM CA bundle: additionally REQUIRE client certificates "
             "(mutual TLS) and pin the trust root for outgoing links",
    )
    ap.add_argument(
        "--retry-profile", default=None, choices=("lan", "wan"),
        help="link retry cadence for cluster-internal connections "
             "(net/retry.py LINK_PROFILES): 'lan' (default) keeps the "
             "historical tight schedules; 'wan' stretches backoff and "
             "deadlines for links that cross real networks.  Equivalent to "
             "RTPU_RETRY_PROFILE; the flag also exports the env var so "
             "coordinator code spawned from this process inherits it.",
    )
    args = ap.parse_args(argv)
    if bool(args.tls_cert) != bool(args.tls_key):
        ap.error("--tls-cert and --tls-key must be given together")
    if args.checkpoint_interval > 0 and not args.checkpoint:
        ap.error("--checkpoint-interval requires --checkpoint <path>")
    if args.platform:
        import os

        os.environ.setdefault("JAX_PLATFORMS", args.platform)
    from redisson_tpu.core import ioplane as _iop

    if args.no_overlap:
        # flip the process-global switch too: the embedded Batch/pack paths
        # of THIS process must match the server's serial reply path
        _iop.set_overlap(False)
    if args.no_qos:
        _sched.set_qos(False)
    if args.no_preempt:
        _iop.set_preempt(False)
    if args.no_tier:
        from redisson_tpu.core import residency as _res_tier

        _res_tier.set_tier(False)
    if args.retry_profile:
        import os as _os

        from redisson_tpu.net import retry as _retry

        _os.environ["RTPU_RETRY_PROFILE"] = args.retry_profile
        _retry.set_retry_profile(args.retry_profile)
    engine = Engine()
    srv = TpuServer(
        engine,
        host=args.host,
        port=args.port,
        advertise_host=args.advertise_host,
        password=args.password,
        checkpoint_path=args.checkpoint,
        overlap=not args.no_overlap,
        workers=args.workers,
        devices=args.devices,
        qos=False if args.no_qos else None,
        dispatch_ahead=args.dispatch_ahead,
        tls_cert_file=args.tls_cert,
        tls_key_file=args.tls_key,
        tls_ca_file=args.tls_ca,
    )
    if args.restore and args.checkpoint:
        from redisson_tpu.core import checkpoint

        # a fresh boot has nothing to restore yet — the supervisor restart
        # path passes --restore unconditionally once a checkpoint dir exists
        import os as _os

        if _os.path.exists(args.checkpoint):
            checkpoint.load(engine, args.checkpoint)
    if args.residency and not args.no_tier:
        srv.enable_residency(sweep_interval=1.0)
    if args.prewarm:
        engine.prewarm()
    checkpointer = None
    if args.checkpoint and args.checkpoint_interval > 0:
        from redisson_tpu.core.checkpoint import AutoCheckpointer

        checkpointer = AutoCheckpointer(
            engine, args.checkpoint, args.checkpoint_interval
        ).start()
    try:
        # SIGTERM and SIGINT both land on the graceful path (the supervisor
        # stops nodes with SIGTERM; see serve_until_signal)
        asyncio.run(srv.serve_until_signal(
            ready_fd=args.ready_fd, journal_dir=args.journal_dir,
        ))
    finally:
        if checkpointer is not None:
            # flush-on-stop: writes since the last interval tick reach disk
            # even on Ctrl-C / SIGTERM-driven exit
            checkpointer.stop()
    return 0


if __name__ == "__main__":
    main()
