"""Slot-migration orchestrator: live rebalancing with zero lost acked writes.

Parity target: the reference's resharding flow — the topology poll diffs
slot ownership (``cluster/ClusterConnectionManager.java:358-450``
``checkSlotsMigration``) while ``command/RedisExecutor.java`` follows the
MOVED/ASK redirects redis-cli's resharding produces.  Redis itself drives a
reshard as: SETSLOT IMPORTING on the target, SETSLOT MIGRATING on the
source, MIGRATE each key, SETSLOT NODE everywhere.  This orchestrator is
that driver for the TPU grid, with records (whole device-backed objects) as
the migration unit and the replication serializer as the transfer format.

Protocol walk (per slot):
  1. target: CLUSTER SETSLOT <s> IMPORTING <source>   (admit ASKING traffic)
  2. source: CLUSTER SETSLOT <s> MIGRATING <target>   (absent keys -> ASK;
     record creation in the slot is barred by the store's creation guard)
  3. source: CLUSTER MIGRATESLOTS <s...> until 0      (each record moves
     atomically under its record lock: serialize -> IMPORTRECORDS -> delete)
  4. everyone: CLUSTER SETVIEW <new view>; source+target: SETSLOT STABLE
     (clears the window; clients converge via MOVED + refresh)

During the window writes are never dropped: a record still on the source
serves there (and ships if it mutates before its move); a record already
moved ASK-redirects; creations ASK-redirect.  The chaos test
(tests/test_migration.py) rebalances mid-load and audits every acked write.

Crash safety (ISSUE 4 tentpole): pass ``journal_dir=`` and the run becomes
a **journaled state machine** — every phase is recorded write-ahead in a
:class:`~redisson_tpu.server.migration_journal.MigrationJournal` (PLANNED →
WINDOW_OPEN → DRAINING(sweep progress) → VIEW_COMMITTED →
STABLE/ROLLED_BACK), each ``SETSLOT``/``MIGRATESLOTS`` carries the
migration's fencing ``EPOCH`` (stale coordinators get ``STALEEPOCH``), and
:func:`resume_migrations` replays the journal directory after a
coordinator crash: migrations that died before opening the window roll
back (reverse-draining any ASK-created strays), migrations that died later
complete forward — idempotently, because every re-issued verb is safe
under the recorded epoch and views.  ``crash_after=`` is the deterministic
kill hook the chaos tier uses to murder the coordinator at every phase
boundary.

Admin links ride :class:`~redisson_tpu.net.retry.RetryPolicy` (bounded
exponential backoff + jitter + deadline) instead of the old single-shot
``retry_attempts=1`` connections, so control traffic feeds the same
failure detectors as data traffic and a transient refuse-connect no longer
aborts a whole reshard.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from redisson_tpu.net.client import NodeClient
from redisson_tpu.net.retry import RetryPolicy, link_policy
from redisson_tpu.server.migration_journal import ImportJournal, MigrationJournal
from redisson_tpu.utils.crc16 import MAX_SLOT


class CoordinatorKilled(BaseException):
    """The deterministic coordinator-kill hook (``crash_after=``): raised
    at a phase boundary to simulate the process dying there.  Derives from
    BaseException so no best-effort ``except Exception`` in the protocol
    path can swallow the 'death' — exactly like a real SIGKILL, nothing
    (including rollback) runs after it."""


def _admin_retry_policy() -> RetryPolicy:
    """Migration control traffic's retry schedule: a fresh policy per link
    (each carries its own jitter RNG) with a deadline that bounds any one
    control verb's total retry budget.  Numbers come from the active link
    profile (RTPU_RETRY_PROFILE): the ``lan`` profile IS the historical
    hard-coded schedule; ``wan`` stretches attempts/backoff/deadline for
    cross-host links without touching deadline-clamp semantics."""
    return link_policy("admin")


def _admin(addr: str, password: Optional[str], ssl_context=None) -> NodeClient:
    return NodeClient(
        addr, password=password, ping_interval=0,
        retry_policy=_admin_retry_policy(), ssl_context=ssl_context,
    )


def migrate_slots(
    source: str,
    target: str,
    slots: Sequence[int],
    all_nodes: Optional[Sequence[str]] = None,
    password: Optional[str] = None,
    ssl_context=None,
    journal_dir: Optional[str] = None,
    crash_after: Optional[str] = None,
) -> int:
    """Move `slots` from `source` to `target` while both serve traffic.

    `all_nodes` = every node (masters + replicas) that should learn the new
    view; defaults to the masters named in the source's current view plus
    the target.  Returns the number of records moved.

    With ``journal_dir`` the run is journaled + fenced (see module
    docstring); ``crash_after=<PHASE>`` (or ``"DRAINING:<sweep>"``) raises
    :class:`CoordinatorKilled` right after that phase's journal entry —
    the chaos tier's deterministic kill switch.
    """
    journal = (
        MigrationJournal.create(journal_dir, source, target)
        if journal_dir is not None else None
    )
    run = _MigrationRun(
        source, target, slots, all_nodes=all_nodes, password=password,
        ssl_context=ssl_context, journal=journal, crash_after=crash_after,
    )
    return run.execute()


def resume_migrations(
    journal_dir: str,
    password: Optional[str] = None,
    ssl_context=None,
    gc_keep: Optional[int] = 64,
    readdress: Optional[Dict[str, str]] = None,
) -> List[Dict[str, Any]]:
    """Settle every in-flight migration the journal directory records —
    the coordinator-restart path.  Idempotent: re-running it (even after
    ANOTHER crash mid-resume) converges, because every replayed verb
    carries the migration's recorded epoch and views.

    Policy per last-recorded phase:

      * ``PLANNED`` — the window may be partially open but no drain sweep
        was recorded: ROLL BACK (close the window, reverse-drain strays an
        ASK redirect created on the target, restore the recorded old view).
      * ``WINDOW_OPEN`` / ``DRAINING`` / ``VIEW_COMMITTED`` — COMPLETE
        forward: re-open the window (idempotent re-issue), drain to zero,
        re-commit the recorded new view, stabilize + propagate.

    Returns one summary dict per journal touched; a migration whose nodes
    are unreachable is reported ``"failed"`` and left non-terminal for the
    next resume pass rather than aborting the others.

    After settling, terminal journals older than the newest ``gc_keep`` are
    pruned (``MigrationJournal.gc`` — the GC policy long-lived coordinators
    need so the journal directory stops growing one file per migration
    forever; terminal IMPORT journals ride the same sweep, in-flight ones
    never do); pass ``gc_keep=None`` to keep everything.

    ``readdress`` maps a DEAD node's address to its promoted successor's
    (``ClusterSupervisor.promote_replica``): every replayed verb, dial, and
    recorded view row naming the old address is rewritten to the new one —
    the replica that REPLPUSH-covered the in-flight import batches becomes
    the migration's target and the pair still converges to STABLE.
    """
    out: List[Dict[str, Any]] = []
    myid_cache: Dict[str, Optional[str]] = {}
    for ij in ImportJournal.in_flight(journal_dir):
        # a torn OPENED line (crash mid-first-append) leaves an import
        # journal with zero intact entries: no batch ever became durable,
        # but no node will claim it (its target is unreadable) — settle it
        # here or it reads in-flight forever and gc pins its coordinator
        # journal for eternity
        if not ij.entries:
            ij.append("ROLLED_BACK", resumed=True,
                      reason="torn import journal; no durable batches")
    for journal in MigrationJournal.in_flight(journal_dir):
        planned = journal.entry("PLANNED")
        if planned is None:  # only a torn PLANNED line: nothing ever ran
            journal.append("ROLLED_BACK", resumed=True, reason="empty journal")
            out.append({"id": journal.migration_id, "action": "rolled_back"})
            continue
        if planned.get("kind") == "device_rebalance":
            # intra-process device moves (ISSUE 8) share the journal
            # directory's epoch allocator but resume through
            # resume_device_rebalances — treating one as a slot migration
            # would dial "dev:N" as a node address
            continue
        if readdress:
            planned = _readdress_planned(
                planned, readdress, myid_cache, password, ssl_context
            )
        run = _MigrationRun(
            planned["source"], planned["target"], planned["slots"],
            all_nodes=planned.get("all_nodes"), password=password,
            ssl_context=ssl_context, journal=journal,
        )
        try:
            if journal.phase == "PLANNED":
                run.resume_rollback(planned)
                out.append({
                    "id": journal.migration_id, "action": "rolled_back",
                    "epoch": journal.epoch,
                })
            else:
                moved = run.resume_complete(planned)
                out.append({
                    "id": journal.migration_id, "action": "completed",
                    "moved": moved, "epoch": journal.epoch,
                })
        except Exception as e:  # noqa: BLE001 — settle the REST of the journals
            out.append({
                "id": journal.migration_id, "action": "failed", "error": repr(e),
            })
    if gc_keep is not None:
        MigrationJournal.gc(journal_dir, keep=gc_keep)
    return out


def _readdress_planned(
    planned: Dict[str, Any],
    readdress: Dict[str, str],
    myid_cache: Dict[str, Optional[str]],
    password: Optional[str],
    ssl_context,
) -> Dict[str, Any]:
    """Rewrite a PLANNED entry's addresses through a failover mapping
    ({dead "host:port": promoted "host:port"}): source/target dials plus
    every recorded view row, whose node id becomes the successor's (fetched
    once per address, best-effort — an unreachable successor keeps the
    recorded id and the resume reports "failed" for the next pass)."""
    def _myid(addr: str) -> Optional[str]:
        if addr not in myid_cache:
            c = None
            try:
                c = _admin(addr, password, ssl_context)
                myid_cache[addr] = _s(c.execute("CLUSTER", "MYID"))
            except Exception:  # noqa: BLE001 — successor unreachable too
                myid_cache[addr] = None
            finally:
                if c is not None:
                    c.close()
        return myid_cache[addr]

    out = dict(planned)
    out["source"] = readdress.get(planned["source"], planned["source"])
    out["target"] = readdress.get(planned["target"], planned["target"])
    if planned.get("all_nodes"):
        out["all_nodes"] = [
            readdress.get(a, a) for a in planned["all_nodes"]
        ]
    if out["target"] != planned["target"]:
        out["target_id"] = _myid(out["target"]) or planned.get("target_id")
    for key in ("old_view", "new_view"):
        rows = planned.get(key)
        if not rows:
            continue
        rewritten = []
        for lo, hi, h, p, nid in (tuple(r) for r in rows):
            addr = f"{h}:{p}"
            if addr in readdress:
                nh, _, np_ = readdress[addr].rpartition(":")
                rewritten.append(
                    (lo, hi, nh, int(np_), _myid(readdress[addr]) or nid)
                )
            else:
                rewritten.append((lo, hi, h, p, nid))
        out[key] = rewritten
    return out


def rearm_recovery(server, journal_dir: str) -> int:
    """Boot-time journal re-arm for a RESTARTED server process (ISSUE 6).

    A node SIGKILLed mid-migration loses its in-memory window state; its
    restored checkpoint may resurrect records the pre-crash drain already
    shipped to the target.  If the fresh process served those slots
    normally, two processes would accept writes for the same records (the
    restored stale lineage here, the shipped lineage there) and whichever
    fork loses the resumed drain's version reconciliation would silently
    drop acked writes.  So, BEFORE the listener answers its first command,
    the restart path replays the journal directory:

      * this node is the SOURCE of an in-flight migration — re-fence the
        epoch, re-arm the MIGRATING window (``resume_migrations``' drain
        needs it) and mark every slot RECOVERING: all keyed traffic gets
        ``TRYAGAIN`` until the resumed migration reaches STABLE (writes
        held off entirely: brief unavailability instead of a silent fork);
      * this node is the TARGET — re-fence the epoch and re-arm the
        IMPORTING window so in-flight ASK traffic is admitted again.

    The IMPORTING arm (ISSUE 13) additionally replays this node's import
    journals: every batch this node journaled-then-acked is re-applied on
    top of the restored checkpoint (idempotent — ``apply_records``
    reconciles by version), because the source deleted those records on the
    strength of the ack and the SIGKILL took the applied copies with the
    process.  Replay policy per the matching COORDINATOR journal:

      * in flight — replay, keep the import journal open (the resumed
        migration's final SETSLOT STABLE settles it);
      * STABLE — replay (the records are this node's to keep; only their
        durable copy may predate the crash) and terminalize;
      * ROLLED_BACK and this node was the migration's TARGET — do NOT
        replay (the rollback reverse-drained the records home;
        resurrecting them would fork ownership), terminalize;
      * ROLLED_BACK and this node was the SOURCE — the journal holds the
        REVERSE drain's batches, which belong here: replay, terminalize;
      * missing (externally pruned — gc keeps coordinator journals whose
        epoch has an in-flight import journal, so this is abnormal) —
        favor durability: replay and terminalize.

    Returns the number of slot windows re-armed plus import journals
    replayed.  Wired to the CLI as ``tpu-server --journal-dir`` (the
    ClusterSupervisor passes its coordinator journal dir to every node it
    spawns).
    """
    from redisson_tpu.server import replication

    n = 0
    addr = server.address()
    coordinator: Dict[int, MigrationJournal] = {}
    for journal in MigrationJournal.scan(journal_dir):
        planned = journal.entry("PLANNED")
        if planned is not None and planned.get("kind") != "device_rebalance":
            coordinator[journal.epoch] = journal
    for ij in ImportJournal.in_flight(journal_dir):
        if ij.target != addr:
            continue
        cj = coordinator.get(ij.epoch)
        cj_planned = cj.entry("PLANNED") if cj is not None else None
        if cj is not None and cj.phase == "ROLLED_BACK" \
                and (cj_planned or {}).get("source") != addr:
            ij.append("ROLLED_BACK", resumed=True,
                      reason="migration rolled back; records went home")
            continue
        for blob in ij.batch_blobs():
            replication.apply_records(server.engine, blob)
        n += 1
        if cj is None or cj.is_terminal():
            # the replayed records live only in memory until a checkpoint
            # covers them — terminalizing before that would hand a second
            # crash nothing to replay.  On save failure the journal stays
            # in flight on disk for the next boot.
            if server._checkpoint_import_state():
                ij.append("STABLE", resumed=True,
                          reason="migration already settled")
        else:
            server.adopt_import_journal(ij)
    for journal in MigrationJournal.in_flight(journal_dir):
        planned = journal.entry("PLANNED")
        if planned is None or planned.get("kind") == "device_rebalance":
            continue
        slots = [int(s) for s in planned["slots"]]
        epoch = journal.epoch
        if planned["source"] == addr:
            for s in slots:
                server.fence_slot_epoch(s, epoch)
                server.set_slot_migrating(s, planned["target"], epoch)
                server.set_slot_recovering(s, planned["target"], epoch)
                n += 1
        elif planned["target"] == addr:
            for s in slots:
                server.fence_slot_epoch(s, epoch)
                server.set_slot_importing(s, planned["source"])
                n += 1
    return n


class _MigrationRun:
    """One migration as an explicit state machine: phase methods shared by
    the fresh path (``execute``) and the journal-replay paths
    (``resume_complete`` / ``resume_rollback``)."""

    def __init__(
        self,
        source: str,
        target: str,
        slots: Sequence[int],
        all_nodes: Optional[Sequence[str]] = None,
        password: Optional[str] = None,
        ssl_context=None,
        journal: Optional[MigrationJournal] = None,
        crash_after: Optional[str] = None,
    ):
        self.source, self.target = source, target
        self.slots = [int(s) for s in slots]
        self.all_nodes = all_nodes
        self.password, self.ssl_context = password, ssl_context
        self.journal = journal
        self.crash_after = crash_after
        self.epoch: Optional[int] = journal.epoch if journal is not None else None
        self.src: Optional[NodeClient] = None
        self.tgt: Optional[NodeClient] = None

    # -- journal / crash plumbing --------------------------------------------

    def _record(self, phase: str, **data) -> None:
        if self.journal is not None:
            self.journal.append(phase, **data)

    def _crash_point(self, label: str) -> None:
        if self.crash_after is not None and self.crash_after == label:
            raise CoordinatorKilled(f"[chaos] coordinator killed after {label}")

    def _ep(self) -> Tuple:
        """Trailing fencing operands for SETSLOT (epoch-less when not
        journaled — legacy manual migrations stay unfenced)."""
        return ("EPOCH", self.epoch) if self.epoch is not None else ()

    def _ep_lead(self) -> Tuple:
        """Leading fencing operands for MIGRATESLOTS."""
        return ("EPOCH", self.epoch) if self.epoch is not None else ()

    def _connect(self) -> None:
        self.src = _admin(self.source, self.password, self.ssl_context)
        self.tgt = _admin(self.target, self.password, self.ssl_context)

    def _target_reachable(self) -> bool:
        """One cheap fresh-connection PING (no retry schedule): decides
        whether a failed journaled migration may roll back now or must stay
        in flight for a forward resume."""
        c = None
        try:
            c = NodeClient(
                self.target, password=self.password, ping_interval=0,
                retry_attempts=1, ssl_context=self.ssl_context,
            )
            c.execute("PING", timeout=2.0)
            return True
        except Exception:  # noqa: BLE001 — any failure reads as dead
            return False
        finally:
            if c is not None:
                c.close()

    def _close(self) -> None:
        for c in (self.src, self.tgt):
            if c is not None:
                c.close()

    # -- phases ---------------------------------------------------------------

    def _phase_open_window(self) -> None:
        # importing BEFORE migrating: an ASK redirect must never land on a
        # target that would bounce it back MOVED
        for s in self.slots:
            self.tgt.execute(
                "CLUSTER", "SETSLOT", s, "IMPORTING", self.source, *self._ep()
            )
        for s in self.slots:
            self.src.execute(
                "CLUSTER", "SETSLOT", s, "MIGRATING", self.target, *self._ep()
            )

    def _phase_drain(self, moved: int = 0) -> int:
        # one bulk call scans the store once for ALL slots; loop until a
        # sweep moves nothing (absent-guarded creations can't add names
        # behind the scan, so this converges in ~2 sweeps).  Each sweep is
        # journaled — a resumed coordinator knows how far the drain got.
        sweep_no = 0
        while True:
            n = int(
                self.src.execute(
                    "CLUSTER", "MIGRATESLOTS", *self._ep_lead(), *self.slots,
                    timeout=300.0,
                )
            )
            moved += n
            sweep_no += 1
            self._record("DRAINING", moved=moved, sweep=sweep_no, batch=n)
            self._crash_point(f"DRAINING:{sweep_no}")
            if n == 0:
                return moved

    def _phase_commit_view(self, new_view) -> List:
        flat: List = []
        for lo, hi, h, p, nid in new_view:
            flat += [lo, hi, h, p, nid]
        # Source and target MUST learn the new view before the window
        # closes — a target that still believes the old view would
        # MOVED-bounce the slot back at the source forever.
        self.tgt.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
        self.src.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
        return flat

    def _phase_stabilize(self, flat: List, known_view) -> None:
        for s in self.slots:
            self.src.execute("CLUSTER", "SETSLOT", s, "STABLE", *self._ep())
            self.tgt.execute("CLUSTER", "SETSLOT", s, "STABLE", *self._ep())
        # remaining nodes are best-effort: they converge via MOVED + refresh
        nodes = set(self.all_nodes or [])
        nodes.update(f"{h}:{p}" for _lo, _hi, h, p, _nid in known_view)
        nodes.discard(self.source)
        nodes.discard(self.target)
        for addr in nodes:
            c = None
            try:
                c = _admin(addr, self.password, self.ssl_context)
                c.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
            except Exception:  # noqa: BLE001 — down node learns on recovery/MOVED
                pass
            finally:
                if c is not None:
                    c.close()

    # -- fresh run -------------------------------------------------------------

    def execute(self) -> int:
        moved = 0
        window_open = False
        old_view: List[Tuple[int, int, str, int, str]] = []
        self._connect()
        try:
            view = old_view = _fetch_view(self.src)
            target_id = _s(self.tgt.execute("CLUSTER", "MYID"))
            new_view = _reassign(view, self.slots, self.target, target_id)
            # WRITE-AHEAD: the PLANNED entry carries everything a resumed
            # coordinator needs — recorded BEFORE any remote mutation
            self._record(
                "PLANNED", source=self.source, target=self.target,
                slots=self.slots, epoch=self.epoch, old_view=old_view,
                new_view=new_view, target_id=target_id,
                all_nodes=list(self.all_nodes) if self.all_nodes else None,
            )
            self._crash_point("PLANNED")
            # set BEFORE opening: a failure mid-way through either SETSLOT
            # loop leaves a HALF-open window (e.g. target IMPORTING, source
            # untouched) that the rollback must still unwind
            window_open = True
            self._phase_open_window()
            self._record("WINDOW_OPEN")
            self._crash_point("WINDOW_OPEN")
            moved = self._phase_drain()
            self._crash_point("DRAINING")
            flat = self._phase_commit_view(new_view)
            self._record("VIEW_COMMITTED")
            self._crash_point("VIEW_COMMITTED")
            self._phase_stabilize(flat, view)
            self._record("STABLE", moved=moved)
            return moved
        except CoordinatorKilled:
            raise  # a 'dead' coordinator runs nothing — resume owns recovery
        except BaseException as primary:
            if window_open and self.journal is not None \
                    and not self._target_reachable():
                # The target died mid-migration (ISSUE 13): it may hold
                # journaled import batches whose source copies the drain
                # already deleted, and a rollback that cannot reach it
                # would close the window and restore the old view — the
                # source would then recreate those keys at version 0 and
                # the resumed drain's reconciliation would drop their
                # journaled (newer) lineage.  Leave the journal IN FLIGHT
                # and the window armed instead: drained keys keep
                # ASK-redirecting (brief unavailability, not a fork) until
                # resume_migrations completes the pair forward once the
                # target — or its promoted replica (readdress=) — is back.
                raise
            if window_open:
                try:
                    _rollback(
                        self.src, self.tgt, self.source, self.target,
                        self.slots, old_view, epoch=self.epoch,
                    )
                except BaseException as rb_err:  # noqa: BLE001
                    # the rollback's OWN failure must not mask the original
                    # error: surface the primary, chain the rollback failure
                    raise primary from rb_err
                self._record("ROLLED_BACK", error=repr(primary))
            raise
        finally:
            self._close()

    # -- journal-replay paths --------------------------------------------------

    def resume_complete(self, planned: Dict[str, Any]) -> int:
        """Drive a journaled migration that died at/after WINDOW_OPEN to
        STABLE.  Every step re-issues under the recorded epoch, so redoing
        work the dead coordinator already did is a no-op (SETSLOT and
        SETVIEW are level-triggered; an empty drain sweeps zero records)."""
        self._connect()
        try:
            self._phase_open_window()  # idempotent re-open
            moved = self._phase_drain(moved=int(self.journal.latest("moved", 0)))
            new_view = [tuple(row) for row in planned["new_view"]]
            flat = self._phase_commit_view(new_view)
            self._record("VIEW_COMMITTED", resumed=True)
            old_view = [tuple(row) for row in planned["old_view"]]
            self._phase_stabilize(flat, old_view)
            self._record("STABLE", moved=moved, resumed=True)
            return moved
        finally:
            self._close()

    def resume_rollback(self, planned: Dict[str, Any]) -> None:
        """Unwind a journaled migration that died at PLANNED: the window
        may be half-open and an ASK redirect may have created records on
        the target, but no drain sweep was recorded — rolling back is
        strictly cheaper than completing."""
        self._connect()
        try:
            old_view = [tuple(row) for row in planned["old_view"]]
            _rollback(
                self.src, self.tgt, self.source, self.target, self.slots,
                old_view, epoch=self.epoch,
            )
            self._record("ROLLED_BACK", resumed=True)
        finally:
            self._close()


def _rollback(src, tgt, source: str, target: str, slots, old_view,
              epoch: Optional[int] = None) -> None:
    """Best-effort unwind of a failed migration: pull already-moved records
    back to the source, restore the pre-migration view on BOTH ends, close
    the window.  If the target is unreachable, the window is still closed —
    records already shipped stay safe on the target and a RE-RUN of
    migrate_slots(source, target, slots) converges once it returns
    (IMPORTRECORDS applies by version, the drain resumes where it stopped).
    A journaled rollback carries the migration's fencing epoch so a stale
    coordinator's late rollback cannot disturb a newer migration."""
    ep: Tuple = ("EPOCH", epoch) if epoch is not None else ()
    # close the forward window on the source FIRST: its absent guard must
    # not ASK-bounce the reverse imports about to arrive
    for s in slots:
        try:
            src.execute("CLUSTER", "SETSLOT", s, "STABLE", *ep)
        except Exception:  # noqa: BLE001 — source gone; nothing to unwind into
            pass
    try:
        # reverse-drain: target -> source for anything that already moved
        for s in slots:
            try:
                src.execute("CLUSTER", "SETSLOT", s, "IMPORTING", target, *ep)
                tgt.execute("CLUSTER", "SETSLOT", s, "MIGRATING", source, *ep)
            except Exception:  # noqa: BLE001 — target gone; records stay there
                pass
        try:
            while int(tgt.execute(
                "CLUSTER", "MIGRATESLOTS", *ep, *slots, timeout=300.0
            )) > 0:
                pass
        except Exception:  # noqa: BLE001 — target gone; records stay there
            pass
    finally:
        for s in slots:
            for c in (src, tgt):
                try:
                    c.execute("CLUSTER", "SETSLOT", s, "STABLE", *ep)
                except Exception:  # noqa: BLE001 — unreachable node
                    pass
        # restore the pre-migration view: a target that already installed
        # the NEW view would otherwise claim slots it just gave back
        if old_view:
            flat: List = []
            for lo, hi, h, p, nid in old_view:
                flat += [lo, hi, h, p, nid]
            for c in (src, tgt):
                try:
                    c.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
                except Exception:  # noqa: BLE001 — unreachable node
                    pass


# -- journaled DEVICE rebalance (ISSUE 8: slot -> device handoffs) ------------
#
# A device move is a slot handoff INSIDE one process (no wire drain, no view
# commit), but it shares the failure mode journaled slot migrations exist
# for: a coordinator killed mid-rebalance leaves half the move set on the
# old device with no record of intent, and a STALE coordinator resuming
# later must not clobber a newer move.  So device moves ride the same
# machinery — one MigrationJournal per rebalance (kind="device_rebalance" in
# PLANNED so the two resume paths never cross), the journal directory's
# monotonic epoch allocator, per-slot fencing on the SlotPlacement
# (PlacementStaleEpoch == the STALEEPOCH reply), and kill-at-every-phase
# resume: PLANNED -> DRAINING (per-batch progress) -> STABLE.

_DEVICE_PHASES = ("PLANNED", "DRAINING", "STABLE")


def rebalance_devices(
    engine,
    targets: Dict[int, int],
    journal_dir: Optional[str] = None,
    crash_after: Optional[str] = None,
    batch: int = 256,
) -> int:
    """Move the slots in ``targets`` ({slot: device_index}) onto their new
    owner devices, fenced and (optionally) journaled.  Returns the number
    of records whose banks moved.  ``crash_after`` raises
    :class:`CoordinatorKilled` right after that phase's journal entry
    (``"PLANNED"``, ``"DRAINING:<sweep>"``, ``"STABLE"``) — the chaos
    tier's deterministic kill switch, same contract as ``migrate_slots``.

    Every slot is fenced at the journal's epoch BEFORE any bank moves, so
    a resumed re-issue is idempotent and a stale coordinator (lower epoch
    than a newer rebalance that touched the slot) dies loudly with
    PlacementStaleEpoch instead of silently un-moving it."""
    placement = engine.placement
    if placement is None:
        raise RuntimeError("placement is not enabled on this engine")
    journal = None
    epoch = None
    if journal_dir is not None:
        devs = sorted(set(targets.values()))
        journal = MigrationJournal.create(
            journal_dir, "dev:rebalance", f"dev:{devs}"
        )
        epoch = journal.epoch
        journal.append(
            "PLANNED", kind="device_rebalance", epoch=epoch,
            targets={str(s): int(d) for s, d in targets.items()},
        )
    run = _DeviceRebalanceRun(engine, targets, journal, epoch, crash_after,
                              batch=batch)
    return run.execute()


def resume_device_rebalances(engine, journal_dir: str) -> List[Dict[str, Any]]:
    """Settle every in-flight device rebalance the journal directory
    records — the restart path.  A device move has no rollback shape (the
    banks live in this process either way), so every in-flight rebalance
    completes FORWARD: re-fence at the recorded epoch, re-move (moving an
    already-moved slot is a no-op), STABLE.  Idempotent under repeated
    crashes mid-resume; a slot a NEWER rebalance already fenced higher is
    skipped (stale epoch), counted in the summary."""
    out: List[Dict[str, Any]] = []
    for journal in MigrationJournal.in_flight(journal_dir):
        planned = journal.entry("PLANNED")
        if planned is None or planned.get("kind") != "device_rebalance":
            continue
        targets = {int(s): int(d) for s, d in planned["targets"].items()}
        run = _DeviceRebalanceRun(
            engine, targets, journal, journal.epoch, None
        )
        try:
            moved, stale = run.resume()
            out.append({
                "id": journal.migration_id, "action": "completed",
                "moved": moved, "stale_slots": stale, "epoch": journal.epoch,
            })
        except Exception as e:  # noqa: BLE001 — settle the rest
            out.append({
                "id": journal.migration_id, "action": "failed",
                "error": repr(e),
            })
    return out


def evacuation_plan(placement, dev_index: int) -> Dict[int, int]:
    """Target owners for every slot of ``dev_index``: round-robin over the
    surviving devices (every other device whose lane is not itself
    quarantined).  The quarantine-and-evacuate half of the device fault
    domain (ISSUE 19) — the plan feeds :func:`rebalance_devices` unchanged,
    so an evacuation IS a journaled, kill-at-every-phase-resumable device
    rebalance with zero new migration machinery."""
    from redisson_tpu.core.ioplane import quarantined_device_ids

    if not 0 <= dev_index < placement.n_devices:
        raise ValueError(f"device index {dev_index} outside placement")
    bad = quarantined_device_ids()
    survivors = [
        i for i, d in enumerate(placement.devices)
        if i != dev_index and getattr(d, "id", i) not in bad
    ]
    if not survivors:
        raise ValueError(
            f"no surviving devices to evacuate device {dev_index} onto"
        )
    owner = placement.owner_snapshot()
    slots = (owner == dev_index).nonzero()[0]
    return {
        int(s): survivors[j % len(survivors)]
        for j, s in enumerate(slots)
    }


def shed_plan(placement, dev_index: int, count: int) -> "Dict[int, int]":
    """Partial evacuation (ISSUE 20): target owners for up to ``count`` of
    ``dev_index``'s slots, round-robin over the surviving devices — the
    HBM-pressure actuator the residency rebalancer drives.  Same contract
    as :func:`evacuation_plan` (feeds :func:`rebalance_devices` unchanged,
    fenced + journaled + resumable), just bounded so one shed step moves a
    bite of the device, not the whole device."""
    full = evacuation_plan(placement, dev_index)
    if count <= 0 or count >= len(full):
        return full
    keep = sorted(full)[:count]
    return {s: full[s] for s in keep}


def evacuate_device(engine, dev_index: int,
                    journal_dir: Optional[str] = None,
                    crash_after: Optional[str] = None):
    """Quarantine-and-evacuate driver (ISSUE 19): compute the surviving-
    device plan for ``dev_index`` and run it through the journaled device
    rebalance.  Returns ``(records_moved, targets, epoch)``; epoch is None
    when unjournaled or when the device owned no slots (nothing ran).
    Keyed traffic on the moving slots rides the existing TRYAGAIN fence;
    a crashed coordinator resumes via :func:`resume_device_rebalances`."""
    placement = engine.placement
    if placement is None:
        raise RuntimeError("placement is not enabled on this engine")
    targets = evacuation_plan(placement, dev_index)
    if not targets:
        return 0, targets, None
    moved = rebalance_devices(
        engine, targets, journal_dir=journal_dir, crash_after=crash_after
    )
    epoch = None
    if journal_dir is not None:
        # every target slot was fenced at the journal's epoch before any
        # bank moved — read it back off the placement for the reply
        epoch = placement.epoch_of(next(iter(targets)))
    return moved, targets, epoch


class _DeviceRebalanceRun:
    """One device rebalance as a journaled state machine (the
    ``_MigrationRun`` shape without a wire)."""

    def __init__(self, engine, targets: Dict[int, int], journal, epoch,
                 crash_after: Optional[str], batch: int = 256):
        self.engine = engine
        self.targets = dict(targets)
        self.journal = journal
        self.epoch = epoch
        self.crash_after = crash_after
        self.batch = max(1, batch)

    def _record(self, phase: str, **data) -> None:
        if self.journal is not None:
            self.journal.append(phase, **data)

    def _crash_point(self, label: str) -> None:
        if self.crash_after is not None and self.crash_after == label:
            raise CoordinatorKilled(f"[chaos] coordinator killed after {label}")

    def _move(self, moved: int = 0, skip_stale: bool = False):
        """Batched fenced moves (one bulk store scan per batch —
        engine.move_slots_records), one DRAINING journal entry per batch so
        a resumed coordinator knows how far it got.  Returns
        (records_moved, stale_slot_count)."""
        slots = sorted(self.targets)
        stale = 0
        sweep = 0
        for start in range(0, len(slots), self.batch):
            batch = {
                slot: self.targets[slot]
                for slot in slots[start:start + self.batch]
            }
            n, s = self.engine.move_slots_records(
                batch, self.epoch, skip_stale=skip_stale
            )
            moved += n
            stale += s
            sweep += 1
            self._record("DRAINING", moved=moved, sweep=sweep)
            self._crash_point(f"DRAINING:{sweep}")
        return moved, stale

    def execute(self) -> int:
        self._crash_point("PLANNED")
        moved, _stale = self._move()
        self._record("STABLE", moved=moved)
        self._crash_point("STABLE")
        return moved

    def resume(self):
        moved0 = int(self.journal.latest("moved", 0)) if self.journal else 0
        moved, stale = self._move(moved=moved0, skip_stale=True)
        self._record("STABLE", moved=moved, resumed=True)
        return moved, stale


def _s(v) -> str:
    return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)


def _fetch_view(node: NodeClient) -> List[Tuple[int, int, str, int, str]]:
    view = []
    for row in node.execute("CLUSTER", "SLOTS"):
        lo, hi, (host, port, nid) = int(row[0]), int(row[1]), row[2]
        view.append((lo, hi, _s(host), int(port), _s(nid)))
    return view


def _reassign(
    view: List[Tuple[int, int, str, int, str]],
    slots: Sequence[int],
    target: str,
    target_id: str,
) -> List[Tuple[int, int, str, int, str]]:
    """Point `slots` at `target` and re-compress into contiguous ranges."""
    owner: Dict[int, Tuple[str, int, str]] = {}
    for lo, hi, h, p, nid in view:
        for s in range(lo, hi + 1):
            owner[s] = (h, p, nid)
    th, tp = target.rsplit(":", 1)
    for s in slots:
        owner[s] = (th, int(tp), target_id)
    out: List[Tuple[int, int, str, int, str]] = []
    run_start: Optional[int] = None
    prev: Optional[Tuple[str, int, str]] = None
    for s in range(MAX_SLOT):  # slots are 0..MAX_SLOT-1 (16384 of them)
        cur = owner.get(s)
        if cur != prev:
            if prev is not None and run_start is not None:
                out.append((run_start, s - 1, *prev))
            run_start, prev = (s, cur) if cur is not None else (None, None)
    if prev is not None and run_start is not None:
        out.append((run_start, MAX_SLOT - 1, *prev))
    return out
