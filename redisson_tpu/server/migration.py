"""Slot-migration orchestrator: live rebalancing with zero lost acked writes.

Parity target: the reference's resharding flow — the topology poll diffs
slot ownership (``cluster/ClusterConnectionManager.java:358-450``
``checkSlotsMigration``) while ``command/RedisExecutor.java`` follows the
MOVED/ASK redirects redis-cli's resharding produces.  Redis itself drives a
reshard as: SETSLOT IMPORTING on the target, SETSLOT MIGRATING on the
source, MIGRATE each key, SETSLOT NODE everywhere.  This orchestrator is
that driver for the TPU grid, with records (whole device-backed objects) as
the migration unit and the replication serializer as the transfer format.

Protocol walk (per slot):
  1. target: CLUSTER SETSLOT <s> IMPORTING <source>   (admit ASKING traffic)
  2. source: CLUSTER SETSLOT <s> MIGRATING <target>   (absent keys -> ASK;
     record creation in the slot is barred by the store's creation guard)
  3. source: CLUSTER MIGRATESLOT <s> [batch] until 0  (each record moves
     atomically under its record lock: serialize -> IMPORTRECORDS -> delete)
  4. everyone: CLUSTER SETVIEW <new view>; source+target: SETSLOT NODE
     (clears the window; clients converge via MOVED + refresh)

During the window writes are never dropped: a record still on the source
serves there (and ships if it mutates before its move); a record already
moved ASK-redirects; creations ASK-redirect.  The chaos test
(tests/test_migration.py) rebalances mid-load and audits every acked write.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from redisson_tpu.net.client import NodeClient
from redisson_tpu.utils.crc16 import MAX_SLOT


def _admin(addr: str, password: Optional[str], ssl_context=None) -> NodeClient:
    return NodeClient(
        addr, password=password, ping_interval=0, retry_attempts=1,
        ssl_context=ssl_context,
    )


def migrate_slots(
    source: str,
    target: str,
    slots: Sequence[int],
    all_nodes: Optional[Sequence[str]] = None,
    password: Optional[str] = None,
    ssl_context=None,
) -> int:
    """Move `slots` from `source` to `target` while both serve traffic.

    `all_nodes` = every node (masters + replicas) that should learn the new
    view; defaults to the masters named in the source's current view plus
    the target.  Returns the number of records moved.
    """
    src = _admin(source, password, ssl_context)
    tgt = _admin(target, password, ssl_context)
    moved = 0
    window_open = False
    old_view: List[Tuple[int, int, str, int, str]] = []
    try:
        view = old_view = _fetch_view(src)
        target_id = _s(tgt.execute("CLUSTER", "MYID"))
        # 1+2: open the window (importing BEFORE migrating: an ASK redirect
        # must never land on a target that would bounce it back MOVED)
        for s in slots:
            tgt.execute("CLUSTER", "SETSLOT", s, "IMPORTING", source)
        window_open = True
        for s in slots:
            src.execute("CLUSTER", "SETSLOT", s, "MIGRATING", target)
        # 3: drain — one bulk call scans the store once for ALL slots; loop
        # until a sweep moves nothing (absent-guarded creations can't add
        # names behind the scan, so this converges in ~2 sweeps)
        while True:
            n = int(
                src.execute("CLUSTER", "MIGRATESLOTS", *slots, timeout=300.0)
            )
            moved += n
            if n == 0:
                break
        # 4: finalize.  Source and target MUST learn the new view before the
        # window closes — a target that still believes the old view would
        # MOVED-bounce the slot back at the source forever.  Failure here
        # aborts (and rolls back) rather than strands the slot.
        new_view = _reassign(view, slots, target, target_id)
        flat: List = []
        for lo, hi, h, p, nid in new_view:
            flat += [lo, hi, h, p, nid]
        tgt.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
        src.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
        for s in slots:
            src.execute("CLUSTER", "SETSLOT", s, "STABLE")
            tgt.execute("CLUSTER", "SETSLOT", s, "STABLE")
        # remaining nodes are best-effort: they converge via MOVED + refresh
        nodes = set(all_nodes or [])
        nodes.update(f"{h}:{p}" for _lo, _hi, h, p, _nid in view)
        nodes.discard(source)
        nodes.discard(target)
        for addr in nodes:
            c = None
            try:
                c = _admin(addr, password, ssl_context)
                c.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
            except Exception:  # noqa: BLE001 — down node learns on recovery/MOVED
                pass
            finally:
                if c is not None:
                    c.close()
        return moved
    except BaseException:
        if window_open:
            _rollback(src, tgt, source, target, slots, old_view)
        raise
    finally:
        src.close()
        tgt.close()


def _rollback(src, tgt, source: str, target: str, slots, old_view) -> None:
    """Best-effort unwind of a failed migration: pull already-moved records
    back to the source, restore the pre-migration view on BOTH ends, close
    the window.  If the target is unreachable, the window is still closed —
    records already shipped stay safe on the target and a RE-RUN of
    migrate_slots(source, target, slots) converges once it returns
    (IMPORTRECORDS applies by version, the drain resumes where it stopped)."""
    # close the forward window on the source FIRST: its absent guard must
    # not ASK-bounce the reverse imports about to arrive
    for s in slots:
        try:
            src.execute("CLUSTER", "SETSLOT", s, "STABLE")
        except Exception:  # noqa: BLE001 — source gone; nothing to unwind into
            pass
    try:
        # reverse-drain: target -> source for anything that already moved
        for s in slots:
            try:
                src.execute("CLUSTER", "SETSLOT", s, "IMPORTING", target)
                tgt.execute("CLUSTER", "SETSLOT", s, "MIGRATING", source)
            except Exception:  # noqa: BLE001 — target gone; records stay there
                pass
        try:
            while int(tgt.execute("CLUSTER", "MIGRATESLOTS", *slots, timeout=300.0)) > 0:
                pass
        except Exception:  # noqa: BLE001 — target gone; records stay there
            pass
    finally:
        for s in slots:
            for c in (src, tgt):
                try:
                    c.execute("CLUSTER", "SETSLOT", s, "STABLE")
                except Exception:  # noqa: BLE001 — unreachable node
                    pass
        # restore the pre-migration view: a target that already installed
        # the NEW view would otherwise claim slots it just gave back
        if old_view:
            flat: List = []
            for lo, hi, h, p, nid in old_view:
                flat += [lo, hi, h, p, nid]
            for c in (src, tgt):
                try:
                    c.execute("CLUSTER", "SETVIEW", *flat, timeout=10.0)
                except Exception:  # noqa: BLE001 — unreachable node
                    pass


def _s(v) -> str:
    return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)


def _fetch_view(node: NodeClient) -> List[Tuple[int, int, str, int, str]]:
    view = []
    for row in node.execute("CLUSTER", "SLOTS"):
        lo, hi, (host, port, nid) = int(row[0]), int(row[1]), row[2]
        view.append((lo, hi, _s(host), int(port), _s(nid)))
    return view


def _reassign(
    view: List[Tuple[int, int, str, int, str]],
    slots: Sequence[int],
    target: str,
    target_id: str,
) -> List[Tuple[int, int, str, int, str]]:
    """Point `slots` at `target` and re-compress into contiguous ranges."""
    owner: Dict[int, Tuple[str, int, str]] = {}
    for lo, hi, h, p, nid in view:
        for s in range(lo, hi + 1):
            owner[s] = (h, p, nid)
    th, tp = target.rsplit(":", 1)
    for s in slots:
        owner[s] = (th, int(tp), target_id)
    out: List[Tuple[int, int, str, int, str]] = []
    run_start: Optional[int] = None
    prev: Optional[Tuple[str, int, str]] = None
    for s in range(MAX_SLOT):  # slots are 0..MAX_SLOT-1 (16384 of them)
        cur = owner.get(s)
        if cur != prev:
            if prev is not None and run_start is not None:
                out.append((run_start, s - 1, *prev))
            run_start, prev = (s, cur) if cur is not None else (None, None)
    if prev is not None and run_start is not None:
        out.append((run_start, MAX_SLOT - 1, *prev))
    return out
