"""FailoverCoordinator: the sentinel-role monitor for tpu clusters.

Parity targets (SURVEY.md §5.3):
  * detection — ``client/PingConnectionHandler.java:60-104`` periodic ping +
    pluggable ``client/FailedNodeDetector.java`` thresholds (reused verbatim
    from net/detectors.py);
  * recovery — the sentinel manager's master switch
    (``connection/SentinelConnectionManager.java:210,281-430``) and the
    cluster manager's ``checkMasterNodesChange`` -> ``changeMaster`` path
    (``cluster/ClusterConnectionManager.java``): on confirmed master death,
    promote a replica (REPLICAOF NO ONE), rewrite the slot view on every
    surviving node (CLUSTER SETVIEW), re-point sibling replicas.

Unlike Redis Sentinel there is no quorum vote — one coordinator owns the
decision (run it supervised; a standby can watch the same topology since
promotion is idempotent: SETVIEW is last-writer-wins and replicas of the old
master re-register against the promoted one).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from redisson_tpu.net.client import NodeClient
from redisson_tpu.net.detectors import FailedConnectionDetector


class MonitoredMaster:
    def __init__(self, address: str, slot_range: Tuple[int, int], node_id: str):
        self.address = address
        self.slot_range = slot_range
        self.node_id = node_id
        # 3 failed pings in a short window = dead (the coordinator pings
        # every check_interval, so the window bounds detection latency)
        self.detector = FailedConnectionDetector(threshold=3, window_s=30.0)
        self.client = NodeClient(address, ping_interval=0, retry_attempts=0)
        self.replicas: List[str] = []


class FailoverCoordinator:
    """Watches the masters of one cluster view; promotes replicas on death."""

    def __init__(
        self,
        view: List[Tuple[int, int, str, int, str]],
        check_interval: float = 0.5,
        on_failover: Optional[Callable[[str, str], None]] = None,
    ):
        self._masters: Dict[str, MonitoredMaster] = {}
        for lo, hi, host, port, nid in view:
            addr = f"{host}:{port}"
            self._masters[addr] = MonitoredMaster(addr, (lo, hi), nid)
        self.check_interval = check_interval
        self.on_failover = on_failover
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.failovers: List[Tuple[str, str]] = []  # (dead master, promoted)
        # dead masters with no promotable replica: their slot range is down
        # (CLUSTERDOWN) but NOT abandoned — the loop keeps pinging them and
        # retrying promotion, so a restarted master or a late replica
        # restores the range instead of leaving it orphaned forever
        self._pending: Dict[str, MonitoredMaster] = {}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "FailoverCoordinator":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rtpu-failover"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for m in list(self._masters.values()) + list(self._pending.values()):
            m.client.close()

    # -- the check loop (scheduleClusterChangeCheck analog) -------------------

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval):
            for m in list(self._masters.values()):
                self._check(m)
            for m in list(self._pending.values()):
                self._check_pending(m)

    def _check_pending(self, m: MonitoredMaster) -> None:
        try:
            back = m.client.execute("PING", timeout=2.0) in (b"PONG", "PONG")
        except Exception:  # noqa: BLE001 — still down
            back = False
        if back:
            # the master itself returned: resume monitoring, and re-push the
            # view — an intervening failover's SETVIEW was built while this
            # range was pending and may have reached nodes that missed it
            self._pending.pop(m.address, None)
            m.detector.on_ping_successful()
            self._masters[m.address] = m
            self._push_view()
            return
        # still dead: a replica may have come (back) up — retry promotion
        self._failover(m)

    def _view_flat(self) -> List:
        """Current slot view INCLUDING pending (down but unreplaced) ranges —
        dropping a pending range from SETVIEW would orphan its slots on every
        node even after the master returns."""
        flat: List = []
        for m in list(self._masters.values()) + list(self._pending.values()):
            h, p = m.address.rsplit(":", 1)
            flat += [m.slot_range[0], m.slot_range[1], h, int(p), m.node_id]
        return flat

    def _push_view(self) -> None:
        flat = self._view_flat()
        for m in list(self._masters.values()):
            try:
                m.client.execute("CLUSTER", "SETVIEW", *flat, timeout=5.0)
            except Exception:  # noqa: BLE001 — node will catch up on next push
                pass

    def _check(self, m: MonitoredMaster) -> None:
        try:
            reply = m.client.execute("PING", timeout=2.0)
            ok = reply in (b"PONG", "PONG")
        except Exception:  # noqa: BLE001 — unreachable counts as a failed ping
            ok = False
        if ok:
            m.detector.on_ping_successful()
            try:
                reps = m.client.execute("REPLICAS", timeout=2.0)
                m.replicas = [r.decode() if isinstance(r, bytes) else r for r in reps]
            except Exception:  # noqa: BLE001
                pass
            return
        m.detector.on_ping_failed()
        if m.detector.is_node_failed():
            self._failover(m)

    # -- promotion ------------------------------------------------------------

    def _failover(self, dead: MonitoredMaster) -> None:
        self._masters.pop(dead.address, None)
        promoted: Optional[str] = None
        for candidate in dead.replicas:
            c = None
            try:
                c = NodeClient(candidate, ping_interval=0, retry_attempts=0)
                c.execute("REPLICAOF", "NO", "ONE", timeout=10.0)
                promoted = candidate
                break
            except Exception:  # noqa: BLE001 — try the next replica
                continue
            finally:
                if c is not None:
                    c.close()
        if promoted is None:
            # no live replica: slot range is down (CLUSTERDOWN) but stays on
            # the pending list so a node restart can recover it (above)
            self._pending[dead.address] = dead
            return
        self._pending.pop(dead.address, None)
        dead.client.close()
        host, port = promoted.rsplit(":", 1)
        nm = MonitoredMaster(promoted, dead.slot_range, dead.node_id)
        nm.replicas = [r for r in dead.replicas if r != promoted]
        self._masters[promoted] = nm
        # rewrite the view everywhere (SETVIEW is last-writer-wins); pending
        # ranges stay in the view so their slots aren't orphaned
        self._push_view()
        # surviving replicas of the dead master re-attach to the promoted one
        for r in nm.replicas:
            rc = None
            try:
                rc = NodeClient(r, ping_interval=0, retry_attempts=0)
                rc.execute("CLUSTER", "SETVIEW", *flat, timeout=5.0)
                rc.execute("REPLICAOF", host, int(port), timeout=120.0)
            except Exception:  # noqa: BLE001
                continue
            finally:
                if rc is not None:
                    rc.close()
        self.failovers.append((dead.address, promoted))
        if self.on_failover is not None:
            try:
                self.on_failover(dead.address, promoted)
            except Exception:  # noqa: BLE001 — user callback must not kill the loop
                pass
