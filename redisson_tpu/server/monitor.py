"""FailoverCoordinator: the sentinel-role monitor for tpu clusters.

Parity targets (SURVEY.md §5.3):
  * detection — ``client/PingConnectionHandler.java:60-104`` periodic ping +
    pluggable ``client/FailedNodeDetector.java`` thresholds (reused verbatim
    from net/detectors.py);
  * recovery — the sentinel manager's master switch
    (``connection/SentinelConnectionManager.java:210,281-430``) and the
    cluster manager's ``checkMasterNodesChange`` -> ``changeMaster`` path
    (``cluster/ClusterConnectionManager.java``): on confirmed master death,
    promote a replica (REPLICAOF NO ONE), rewrite the slot view on every
    surviving node (CLUSTER SETVIEW), re-point sibling replicas.

Unlike Redis Sentinel there is no quorum vote — one coordinator owns the
decision (run it supervised; a standby can watch the same topology since
promotion is idempotent: SETVIEW is last-writer-wins and replicas of the old
master re-register against the promoted one).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from redisson_tpu.net.client import NodeClient
from redisson_tpu.net.detectors import FailedConnectionDetector


class MonitoredMaster:
    def __init__(self, address: str, slot_range: Tuple[int, int], node_id: str):
        self.address = address
        self.slot_range = slot_range
        self.node_id = node_id
        # 3 failed pings in a short window = dead (the coordinator pings
        # every check_interval, so the window bounds detection latency)
        self.detector = FailedConnectionDetector(threshold=3, window_s=30.0)
        self.client = NodeClient(address, ping_interval=0, retry_attempts=0)
        self.replicas: List[str] = []


class FailoverCoordinator:
    """Watches the masters of one cluster view; promotes replicas on death."""

    def __init__(
        self,
        view: List[Tuple[int, int, str, int, str]],
        check_interval: float = 0.5,
        on_failover: Optional[Callable[[str, str], None]] = None,
        view_token: Optional[int] = None,
        known_nodes: Optional[List[str]] = None,
    ):
        self._masters: Dict[str, MonitoredMaster] = {}
        for lo, hi, host, port, nid in view:
            addr = f"{host}:{port}"
            self._masters[addr] = MonitoredMaster(addr, (lo, hi), nid)
        self.check_interval = check_interval
        self.on_failover = on_failover
        # leadership fencing token (HA mode): stamped on every SETVIEW so a
        # stale ex-leader's late writes are rejected server-side
        self.view_token = view_token
        # every address worth probing with ROLE when a dead master's replica
        # list is unknown — a SUCCESSOR coordinator (HA takeover) has no
        # poll history from before the death, so it must discover
        self.known_nodes = [a for a in (known_nodes or [])]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.failovers: List[Tuple[str, str]] = []  # (dead master, promoted)
        # dead masters with no promotable replica: their slot range is down
        # (CLUSTERDOWN) but NOT abandoned — the loop keeps pinging them and
        # retrying promotion, so a restarted master or a late replica
        # restores the range instead of leaving it orphaned forever
        self._pending: Dict[str, MonitoredMaster] = {}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "FailoverCoordinator":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rtpu-failover"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for m in list(self._masters.values()) + list(self._pending.values()):
            m.client.close()

    # -- the check loop (scheduleClusterChangeCheck analog) -------------------

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval):
            for m in list(self._masters.values()):
                self._check(m)
            for m in list(self._pending.values()):
                self._check_pending(m)

    def _check_pending(self, m: MonitoredMaster) -> None:
        try:
            back = m.client.execute("PING", timeout=2.0) in (b"PONG", "PONG")
        except Exception:  # noqa: BLE001 — still down
            back = False
        if back:
            # the master itself returned: resume monitoring, and re-push the
            # view — an intervening failover's SETVIEW was built while this
            # range was pending and may have reached nodes that missed it
            self._pending.pop(m.address, None)
            m.detector.on_ping_successful()
            self._masters[m.address] = m
            self._push_view()
            return
        # still dead: a replica may have come (back) up — retry promotion
        self._failover(m)

    def _view_flat(self) -> List:
        """Current slot view INCLUDING pending (down but unreplaced) ranges —
        dropping a pending range from SETVIEW would orphan its slots on every
        node even after the master returns."""
        flat: List = []
        for m in list(self._masters.values()) + list(self._pending.values()):
            h, p = m.address.rsplit(":", 1)
            flat += [m.slot_range[0], m.slot_range[1], h, int(p), m.node_id]
        return flat

    def _setview_args(self) -> List:
        flat = self._view_flat()
        if self.view_token is not None:
            return ["TOKEN", self.view_token, *flat]
        return flat

    def _push_view(self) -> None:
        flat = self._setview_args()
        for m in list(self._masters.values()):
            try:
                m.client.execute("CLUSTER", "SETVIEW", *flat, timeout=5.0)
            except Exception:  # noqa: BLE001 — node will catch up on next push
                pass

    def _check(self, m: MonitoredMaster) -> None:
        try:
            reply = m.client.execute("PING", timeout=2.0)
            ok = reply in (b"PONG", "PONG")
        except Exception:  # noqa: BLE001 — unreachable counts as a failed ping
            ok = False
        if ok:
            m.detector.on_ping_successful()
            try:
                reps = m.client.execute("REPLICAS", timeout=2.0)
                m.replicas = [r.decode() if isinstance(r, bytes) else r for r in reps]
            except Exception:  # noqa: BLE001
                pass
            return
        m.detector.on_ping_failed()
        if m.detector.is_node_failed():
            self._failover(m)

    # -- promotion ------------------------------------------------------------

    def _discover_replicas(self, master_addr: str) -> List[str]:
        """ROLE-probe every known node for promotion candidates — the
        successor-coordinator path: it never polled the master alive.

        Two classes of candidate, slaves first:
          * a node reporting ROLE slave OF the dead master;
          * an unmonitored node reporting ROLE MASTER whose promoted-from
            breadcrumb (ROLE's 4th element) NAMES the dead master — the
            signature of a HALF-FINISHED failover (the predecessor ran
            REPLICAOF NO ONE, died before SETVIEW).  The breadcrumb check
            matters: without it a RESTARTED stale master (empty data, also
            unmonitored) would get adopted for a range it never replicated.
            Adopting converges the predecessor's work; the promotion
            command is idempotent on an already-master."""
        slaves: List[str] = []
        orphan_masters: List[str] = []
        monitored = set(self._masters) | set(self._pending)
        for addr in self.known_nodes:
            a = addr.split("://", 1)[-1]
            if a == master_addr or a in monitored:
                continue
            c = None
            try:
                c = NodeClient(a, ping_interval=0, retry_attempts=0)
                role = c.execute("ROLE", timeout=2.0)
                if role and bytes(role[0]) == b"slave":
                    host = role[1].decode() if isinstance(role[1], bytes) else role[1]
                    if f"{host}:{int(role[2])}" == master_addr:
                        slaves.append(a)
                elif role and bytes(role[0]) == b"master" and len(role) > 3:
                    promoted_from = (
                        role[3].decode() if isinstance(role[3], bytes) else role[3]
                    )
                    if promoted_from == master_addr:
                        orphan_masters.append(a)
            except Exception:  # noqa: BLE001 — node down/probing best-effort
                continue
            finally:
                if c is not None:
                    c.close()
        return slaves + orphan_masters

    def _failover(self, dead: MonitoredMaster) -> None:
        self._masters.pop(dead.address, None)
        if not dead.replicas:
            dead.replicas = self._discover_replicas(dead.address)
        promoted: Optional[str] = None
        for candidate in dead.replicas:
            c = None
            try:
                c = NodeClient(candidate, ping_interval=0, retry_attempts=0)
                c.execute("REPLICAOF", "NO", "ONE", timeout=10.0)
                promoted = candidate
                break
            except Exception:  # noqa: BLE001 — try the next replica
                continue
            finally:
                if c is not None:
                    c.close()
        if promoted is None:
            # no live replica: slot range is down (CLUSTERDOWN) but stays on
            # the pending list so a node restart can recover it (above)
            self._pending[dead.address] = dead
            return
        self._pending.pop(dead.address, None)
        dead.client.close()
        host, port = promoted.rsplit(":", 1)
        nm = MonitoredMaster(promoted, dead.slot_range, dead.node_id)
        nm.replicas = [r for r in dead.replicas if r != promoted]
        self._masters[promoted] = nm
        # rewrite the view everywhere (SETVIEW is last-writer-wins); pending
        # ranges stay in the view so their slots aren't orphaned
        self._push_view()
        # surviving replicas of the dead master re-attach to the promoted one
        setview = self._setview_args()
        for r in nm.replicas:
            rc = None
            try:
                rc = NodeClient(r, ping_interval=0, retry_attempts=0)
                rc.execute("CLUSTER", "SETVIEW", *setview, timeout=5.0)
                rc.execute("REPLICAOF", host, int(port), timeout=120.0)
            except Exception:  # noqa: BLE001
                continue
            finally:
                if rc is not None:
                    rc.close()
        self.failovers.append((dead.address, promoted))
        if self.on_failover is not None:
            try:
                self.on_failover(dead.address, promoted)
            except Exception:  # noqa: BLE001 — user callback must not kill the loop
                pass


class HAFailoverCoordinator:
    """Coordinator HA (VERDICT r2 #7): run N of these; exactly one acts.

    Leadership rides the framework's own FencedLock over the cluster
    (reference analog: the sentinel layer tolerating sentinel death,
    connection/SentinelConnectionManager.java:210-430 — re-expressed with
    a lease instead of a quorum vote):
      * each instance loops trying the leader lock (client-side watchdog
        renews while alive; a crashed leader stops renewing and the lease
        lapses — RedissonBaseLock.java:127-189 discipline);
      * the winner gets a strictly monotonic FENCING token and runs a
        FailoverCoordinator stamping every SETVIEW with it; nodes reject
        lower-token views (registry CLUSTER SETVIEW TOKEN), so a paused
        ex-leader resuming after its lease lapsed cannot clobber its
        successor's topology;
      * standbys keep polling; promotion is idempotent, so the successor
        re-driving a half-finished failover converges.

    Known limitation (documented, like single-sentinel deployments): the
    leader lock lives on the cluster itself; if the shard owning the lock
    name is down, leadership cannot CHANGE until that range recovers — the
    incumbent keeps acting on its last-known lease.  Pin the lock to a
    well-replicated shard with a {hashtag} if that matters.
    """

    LOCK_NAME = "redisson:failover:leader"

    def __init__(
        self,
        view: List[Tuple[int, int, str, int, str]],
        seeds: List[str],
        check_interval: float = 0.5,
        lease: float = 3.0,
        on_failover: Optional[Callable[[str, str], None]] = None,
        lock_name: Optional[str] = None,
    ):
        self._view = list(view)
        self._seeds = list(seeds)
        self.check_interval = check_interval
        self.lease = lease
        self.on_failover = on_failover
        self.lock_name = lock_name or self.LOCK_NAME
        self._stop = threading.Event()
        self._release_on_stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inner: Optional[FailoverCoordinator] = None
        self._client = None
        self.is_leader = threading.Event()
        self.token: Optional[int] = None
        # failover history survives demotion (an operator reading .failovers
        # after a lease loss must still see what happened on our watch)
        self._failover_log: List[Tuple[str, str]] = []
        self._log_lock = threading.Lock()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "HAFailoverCoordinator":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rtpu-ha-failover"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Graceful stop: releases leadership so a standby takes over fast.
        The unlock happens ON the _run thread (synchronizer identity is
        uuid:threadId — a cross-thread unlock would be rejected as a
        non-owner, silently degrading stop() into kill())."""
        self._release_on_stop.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._teardown()

    def kill(self) -> None:
        """Crash simulation: abandon WITHOUT unlocking — the lease must
        lapse before a standby can take over (chaos-test hook)."""
        self._stop.set()
        if self._inner is not None:
            self._inner.stop()
            self._inner = None
        if self._client is not None:
            try:
                self._client.shutdown()
            except Exception:  # noqa: BLE001
                pass
            self._client = None
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _teardown(self) -> None:
        # NO unlock here: the synchronizer identity is uuid:threadId, so
        # only the _run thread can release (stop() routes the unlock there)
        if self._inner is not None:
            self._inner.stop()
            self._inner = None
        self.is_leader.clear()
        if self._client is not None:
            try:
                self._client.shutdown()
            except Exception:  # noqa: BLE001
                pass
            self._client = None

    # -- leadership loop -------------------------------------------------------

    def _make_client(self):
        from redisson_tpu.client.cluster import ClusterRedisson

        return ClusterRedisson(self._seeds, scan_interval=2.0, timeout=10.0)

    def _current_view(self) -> Optional[List[Tuple[int, int, str, int, str]]]:
        """The cluster's CURRENT slot view (CLUSTER SLOTS), or None when it
        cannot be fetched.  A successor leader MUST bootstrap from live
        state: monitoring a stale snapshot after a predecessor's completed
        failover would treat the promoted replica's range as still owned by
        the old (dead) master — and, armed with a newer fencing token,
        re-installing that stale map would make the pre-failover topology
        authoritative again.  So on failure the caller must NOT lead —
        better briefly leaderless than confidently wrong."""
        for _ in range(3):
            try:
                rows = self._client.execute("CLUSTER", "SLOTS", timeout=5.0)
                view = []
                for row in rows:
                    lo, hi, (host, port, nid) = int(row[0]), int(row[1]), row[2]
                    host = host.decode() if isinstance(host, bytes) else host
                    nid = nid.decode() if isinstance(nid, bytes) else nid
                    view.append((lo, hi, host, int(port), nid))
                if view:
                    return view
            except Exception:  # noqa: BLE001 — retry, then refuse to lead
                pass
            self._stop.wait(0.3)
        return None

    def _record_failover(self, dead: str, promoted: str) -> None:
        with self._log_lock:
            self._failover_log.append((dead, promoted))
        if self.on_failover is not None:
            try:
                self.on_failover(dead, promoted)
            except Exception:  # noqa: BLE001 — user callback must not kill us
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._client is None:
                    self._client = self._make_client()
                # acquire + fencing token in ONE atomic server-side step
                # (two steps would let a lapse-and-steal between them hand
                # two leaders the same token).  EXPLICIT short lease, not
                # the 30s client watchdog: a crashed leader stops renewing
                # and the lease lapses within `lease` seconds.
                token = self._client.objcall(
                    "get_fenced_lock", self.lock_name,
                    "try_lock_and_get_token", (self.lease / 2, self.lease), {},
                )
                if token is None:
                    continue
            except Exception:  # noqa: BLE001 — cluster briefly away; retry
                if self._client is not None:
                    try:
                        self._client.shutdown()
                    except Exception:  # noqa: BLE001
                        pass
                    self._client = None
                self._stop.wait(min(1.0, self.lease / 2))
                continue
            try:
                self.token = int(token)
                view = self._current_view()
                if view is None:
                    # can't see live topology: refuse to lead on a stale
                    # snapshot — release (same thread = same holder id) and
                    # return to standby
                    try:
                        self._client.objcall(
                            "get_fenced_lock", self.lock_name, "unlock", (), {}
                        )
                    except Exception:  # noqa: BLE001 — lease will lapse
                        pass
                    self._stop.wait(min(1.0, self.lease / 2))
                    continue
                self._inner = FailoverCoordinator(
                    view,
                    check_interval=self.check_interval,
                    on_failover=self._record_failover,
                    view_token=self.token,
                    known_nodes=self._seeds,
                ).start()
                self.is_leader.set()
                # hold leadership: renew at lease/3.  Demotion triggers on a
                # clean False (someone else holds it) OR when no renewal has
                # SUCCEEDED within a full lease — a partitioned leader whose
                # renew calls all raise must stand down, not act forever on
                # a lease that lapsed (its unfenced REPLICAOF commands would
                # otherwise race the successor's)
                last_ok = time.time()
                while not self._stop.wait(self.lease / 3):
                    try:
                        if not self._client.objcall(
                            "get_fenced_lock", self.lock_name,
                            "renew_lease", (self.lease,), {},
                        ):
                            break
                        last_ok = time.time()
                    except Exception:  # noqa: BLE001 — transient unless stale
                        if time.time() - last_ok > self.lease:
                            break
                if self._stop.is_set() and self._release_on_stop.is_set():
                    # graceful stop: unlock FROM THIS THREAD (the holder
                    # identity is per-thread) so a standby takes over fast
                    try:
                        self._client.objcall(
                            "get_fenced_lock", self.lock_name, "unlock", (), {}
                        )
                    except Exception:  # noqa: BLE001 — lease will lapse anyway
                        pass
            except Exception:  # noqa: BLE001 — leadership bootstrap failed:
                # drop the (possibly broken) client and return to standby;
                # the thread must NEVER die silently, or this instance
                # leaves the HA pool forever
                if self._client is not None:
                    try:
                        self._client.shutdown()
                    except Exception:  # noqa: BLE001
                        pass
                    self._client = None
                self._stop.wait(min(1.0, self.lease / 2))
            finally:
                self.is_leader.clear()
                if self._inner is not None:
                    self._inner.stop()
                    self._inner = None

    # -- introspection ---------------------------------------------------------

    @property
    def failovers(self) -> List[Tuple[str, str]]:
        """Failovers performed on THIS instance's watch — survives demotion."""
        with self._log_lock:
            return list(self._failover_log)
