"""Slot -> local-device placement: one server process owning the whole mesh.

The embedded engine already reshards 4->8->4 across 8 devices under traffic
(MULTICHIP_r05, ``parallel/``), but ``tpu-server`` served exactly ONE device:
every record's plane lived wherever jax's default device put it, every frame
serialized through one dispatch lane, and ``--prewarm`` compiled kernels for
device 0 only.  This module is the ownership layer that changes that: the
16384-slot table maps onto ``jax.local_devices()`` (contiguous ranges, the
same split discipline as ``cluster/topology.split_slots``), and each object's
banks are COMMITTED to the device that owns its slot — jax then runs every
kernel touching that record on that device, so frames routed to different
devices dispatch down different lanes (``core/ioplane.LaneSet``) and execute
concurrently.

Rebalancing is online and FENCED: a device move is just a slot handoff inside
one process, so it rides the same epoch discipline as the journaled slot
migrations (ISSUE 4) — ``fence()`` rejects a lower epoch with STALEEPOCH, a
journaled re-issue at the recorded epoch is idempotent, and the journaled
rebalance driver lives in ``server/migration.py`` (``rebalance_devices`` /
``resume_device_rebalances``) so kill-at-every-phase recovery reuses the
proven ``MigrationJournal`` machinery.

Placement is strictly opt-in (``Engine.enable_placement`` /
``tpu-server --devices``): with it off, nothing here runs and every record
keeps today's default-device behavior.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from redisson_tpu.utils.crc16 import MAX_SLOT, calc_slot


class PlacementStaleEpoch(RuntimeError):
    """A device move arrived with a fencing epoch BELOW the highest one the
    slot accepted — a stale coordinator's late write.  Message leads with
    STALEEPOCH so the wire projection matches the slot-migration fence."""

    def __init__(self, slot: int, accepted: int, got: int):
        super().__init__(
            f"STALEEPOCH slot {slot} device placement fenced at epoch "
            f"{accepted}; got {got}"
        )
        self.slot, self.accepted, self.got = slot, accepted, got


def _contiguous_owner_table(n_slots: int, n_devices: int) -> np.ndarray:
    """slot -> device index, contiguous ranges (the split_slots discipline:
    device i owns [i*S/D, (i+1)*S/D))."""
    return (np.arange(n_slots, dtype=np.int64) * n_devices // n_slots).astype(
        np.int32
    )


class SlotPlacement:
    """Consistent slot -> device assignment over the local device list.

    ``_owner`` is the authoritative routing table (which lane a frame's
    commands schedule onto, which device a NEW record's plane commits to).
    A record's arrays may briefly live on the PREVIOUS owner mid-move —
    kernels follow the committed plane, so correctness never depends on the
    table and the moving window only costs fused-run eligibility
    (``core/coalesce`` falls back to per-record dispatch on a mixed group).
    """

    def __init__(self, devices: Optional[Sequence[Any]] = None,
                 n_devices: Optional[int] = None):
        if devices is None:
            import jax

            devices = jax.local_devices()
        devices = list(devices)
        if n_devices is not None:
            if not 1 <= n_devices <= len(devices):
                raise ValueError(
                    f"n_devices {n_devices} outside 1..{len(devices)}"
                )
            devices = devices[:n_devices]
        if not devices:
            raise ValueError("placement needs at least one device")
        self.devices: List[Any] = devices
        self._lock = threading.Lock()
        self._owner = _contiguous_owner_table(MAX_SLOT, len(devices))
        # per-slot fencing epoch for device moves (the slot-migration
        # fencing discipline applied to intra-process handoffs)
        self._epochs: Dict[int, int] = {}
        self.moves = 0  # observability: completed slot handoffs

    # -- lookup ---------------------------------------------------------------

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def device_id_for_slot(self, slot: int) -> int:
        return int(self._owner[slot])

    def device_for_slot(self, slot: int):
        return self.devices[int(self._owner[slot])]

    def device_for_name(self, name: str):
        return self.device_for_slot(calc_slot(
            name if isinstance(name, bytes) else name.encode()
        ))

    def device_id_for_name(self, name: str) -> int:
        return self.device_id_for_slot(calc_slot(
            name if isinstance(name, bytes) else name.encode()
        ))

    def slot_counts(self) -> List[int]:
        """Slots owned per device (CLUSTER DEVICES / census gauge)."""
        with self._lock:
            counts = np.bincount(self._owner, minlength=self.n_devices)
        return [int(c) for c in counts]

    def owner_snapshot(self) -> np.ndarray:
        with self._lock:
            return self._owner.copy()

    def epoch_of(self, slot: int) -> int:
        with self._lock:
            return self._epochs.get(slot, 0)

    # -- fenced moves ---------------------------------------------------------

    def fence(self, slot: int, epoch: Optional[int]) -> None:
        """Accept-or-reject a device move's fencing epoch for one slot.
        Epoch-less moves (manual admin) pass unfenced; a lower epoch than
        the highest accepted is refused loudly (PlacementStaleEpoch)."""
        if epoch is None:
            return
        with self._lock:
            cur = self._epochs.get(slot, 0)
            if epoch < cur:
                raise PlacementStaleEpoch(slot, cur, epoch)
            self._epochs[slot] = epoch

    def assign(self, slot: int, dev_index: int,
               epoch: Optional[int] = None) -> bool:
        """Point `slot` at device `dev_index` (fenced).  Returns True iff
        the owner actually changed.  This updates ROUTING only — the record
        arrays move under their record locks in the rebalance driver
        (server/migration.rebalance_devices) or Engine.move_slot_records."""
        if not 0 <= dev_index < self.n_devices:
            raise ValueError(f"device index {dev_index} outside placement")
        self.fence(slot, epoch)
        with self._lock:
            changed = int(self._owner[slot]) != dev_index
            self._owner[slot] = dev_index
            if changed:
                self.moves += 1
        return changed

    def device_span(self, base: int, n: int) -> List[int]:
        """Target device ids for an n-member record CONSTELLATION anchored
        at device `base` (ISSUE 15: mesh-sharded embedding banks): the ring
        walk (base + i) % n_devices — distinct devices while n <= device
        count, wrapping evenly past it.  Callers (vector.pick_shard_record
        _names) then salt each member's hashtag until its slot lands on its
        span device, so the ordinary slot machinery owns every move."""
        from redisson_tpu.parallel.mesh import device_ring

        return device_ring(self.n_devices, base, n)

    def spread_plan(self, n_active: int) -> Dict[int, int]:
        """The 4->8->4 rebalance shape: target owner for every slot when
        only the first `n_active` devices serve.  Returns {slot: dev_index}
        for the slots whose owner CHANGES (the move set)."""
        if not 1 <= n_active <= self.n_devices:
            raise ValueError(
                f"n_active {n_active} outside 1..{self.n_devices}"
            )
        target = _contiguous_owner_table(MAX_SLOT, n_active)
        with self._lock:
            diff = np.nonzero(target != self._owner)[0]
            return {int(s): int(target[s]) for s in diff}

    # -- frame scheduling -----------------------------------------------------

    # Verbs whose frame entries may dispatch on per-device queues: single
    # batch-data commands whose ONLY cross-command ordering contract is
    # per-key (keys map to exactly one device, so per-device FIFO queues
    # preserve every observable ordering).  Everything else — admin,
    # transactions, pubsub, blocking verbs, multi-slot reads — is a barrier.
    PARALLEL_VERBS = frozenset(
        v.encode() for v in (
            "BF.RESERVE", "BF.ADD", "BF.MADD", "BF.EXISTS", "BF.MEXISTS",
            "BF.MADD64", "BF.MEXISTS64", "BF.INFO",
            "BFA.RESERVE", "BFA.MADD64", "BFA.MEXISTS64",
            "HLLA.RESERVE", "HLLA.MADD64", "HLLA.MERGEROWS",
            "HLLA.ESTIMATE", "HLLA.ESTPAIRS",
            "SETBIT", "GETBIT", "BITCOUNT", "BITOP",
            "SETBITS", "GETBITS", "SETBITSB", "GETBITSB",
            # PFCOUNT is NOT here: its key spec names only the first key,
            # so a multi-key union could shard on partial knowledge and
            # race a later queue's write — it barriers instead
            "PFADD", "PFADD64", "PFMERGE",
            "SET", "GET", "SETNX", "GETSET", "APPEND", "STRLEN",
            "INCR", "DECR", "INCRBY", "DECRBY",
        )
    )

    def device_index_for_command(self, cmd, owner=None) -> Optional[int]:
        """Owning device index of one whitelisted single-device command,
        else None (non-parallel verb, malformed, keyless, or keys spanning
        devices).  The shared eligibility test of plan_frame and the
        sequential path's per-command lane accounting.

        ``owner``: resolve against this owner-table SNAPSHOT instead of the
        live table — plan_frame passes one snapshot for the whole frame so
        a rebalance racing the planner cannot split same-key commands into
        different concurrently-dispatched buckets."""
        from redisson_tpu.net import commands as C

        if not (
            isinstance(cmd, list)
            and cmd
            and all(isinstance(a, (bytes, bytearray)) for a in cmd)
        ):
            return None
        verb = bytes(cmd[0]).upper()
        if verb not in self.PARALLEL_VERBS:
            return None
        try:
            keys = C.command_keys(verb.decode(), cmd[1:])
        except Exception:  # noqa: BLE001 — malformed: not laneable
            return None
        if not keys:
            return None
        table = self._owner if owner is None else owner
        ids = {
            int(table[calc_slot(
                k if isinstance(k, bytes) else str(k).encode()
            )])
            for k in keys
        }
        return next(iter(ids)) if len(ids) == 1 else None

    def plan_frame(self, commands: List[List[bytes]],
                   single_device_ok: bool = False):
        """Partition one pipelined frame into dispatch segments:

            ("sharded", {dev_index: [cmd_index, ...]})  — per-device queues
                                                          dispatch CONCURRENTLY
            ("serial", [cmd_index, ...])                — in-order barrier run

        Returns None when the frame has no cross-device parallelism to
        exploit (single device touched, or too small) — callers keep the
        plain sequential loop, byte-identical behavior.  Eligibility per
        command: whitelisted verb AND every key on ONE device (a cross-
        device multi-key command is a barrier; correctness never depends
        on the plan — ineligible commands simply serialize).

        ``single_device_ok`` returns a plan even when everything lands on
        ONE device — the bench A/B's 1-device leg (the server sets it while
        the CPU-replica occupancy model is armed), so both legs run the
        SAME dispatch code and differ only in lane count."""
        if (self.n_devices <= 1 and not single_device_ok) or len(commands) < 2:
            return None
        # ONE owner-table snapshot for the whole frame: a rebalance racing
        # the planner must not split same-key commands into different
        # concurrently-dispatched buckets (per-key order would break)
        owner = self.owner_snapshot()
        segments: List[Tuple[str, Any]] = []
        cur_sharded: Optional[Dict[int, List[int]]] = None
        cur_serial: Optional[List[int]] = None
        devs_touched: set = set()

        def flush_sharded():
            nonlocal cur_sharded
            if cur_sharded:
                segments.append(("sharded", cur_sharded))
            cur_sharded = None

        def flush_serial():
            nonlocal cur_serial
            if cur_serial:
                segments.append(("serial", cur_serial))
            cur_serial = None

        for i, cmd in enumerate(commands):
            if (
                isinstance(cmd, list) and cmd
                and isinstance(cmd[0], (bytes, bytearray))
                and bytes(cmd[0]).upper() == b"MULTI"
            ):
                # MULTI arms queueing MID-frame: every later command of the
                # frame must append to the transaction queue in frame order,
                # which concurrent per-device buckets cannot guarantee —
                # the whole frame stays on the sequential path
                return None
            dev = self.device_index_for_command(cmd, owner=owner)
            if dev is None:
                flush_sharded()
                if cur_serial is None:
                    cur_serial = []
                cur_serial.append(i)
            else:
                flush_serial()
                if cur_sharded is None:
                    cur_sharded = {}
                cur_sharded.setdefault(dev, []).append(i)
                devs_touched.add(dev)
        flush_sharded()
        flush_serial()
        if len(devs_touched) <= 1 and not single_device_ok:
            return None  # one lane: the sequential loop is already optimal
        if not devs_touched:
            return None  # nothing shardable at all: keep the plain loop
        return segments
