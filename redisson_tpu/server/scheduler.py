"""Deadline-aware window scheduler + per-tenant QoS (ISSUE 10 tentpole).

The overlap plane (core/ioplane, PR 3) hides the ~66ms computed-result fetch
floor *across* windows, but a single interactive tenant's p99 still eats one
whole floor, and one abusive bulk tenant can flood the worker pool and the
per-connection completion queues, starving everyone.  Inference serving
solved exactly this shape with continuous batching and admission control
(Orca's iteration-level scheduling, vLLM's admission/preemption discipline);
this module transfers that playbook onto the device-window pipeline:

  * **Deadline classes** — every parsed frame is classified ``interactive``
    or ``bulk`` before anything dispatches: explicitly via the
    ``CLIENT QOS CLASS <c> [TENANT <t>]`` connection verb, or by heuristic
    (small frames — few estimated device items — are interactive; big blob
    pipelines are bulk).
  * **Admission by class, not arrival** — interactive frames are admitted
    into the next device window first: they dispatch on a reserved slice of
    worker capacity, bulk frames fill the remaining capacity behind a
    bounded concurrency gate (``qos-bulk-slots``), so a bulk flood can
    never occupy every dispatch slot.  Interactive windows additionally
    close early in ``ioplane.FlushPipeline`` (deadline-triggered flush
    instead of pure size/arrival triggers).
  * **Per-tenant token buckets feeding the coalescer** — each tenant (the
    ``{hashtag}`` of the frame's keys, or the connection-declared tenant)
    owns a token bucket over estimated device items.  A frame whose tenant
    is over budget is LOAD-SHED with a RESP ``-BUSY`` error *before
    dispatch* — no queue residency, no partial kernel work — and a
    partially-covered frame sheds only its over-budget tail (coalesced runs
    never form across the shed boundary, core/coalesce.py).

Disarm with ``RTPU_NO_QOS=1`` / ``set_qos(False)`` / ``tpu-server
--no-qos``: the disarmed plane reproduces the historical arrival-order
dispatch exactly and results are bit-identical (the scheduler reorders
ADMISSION and capacity, never device work inside a connection; shedding is
opt-in via ``qos-tenant-rate`` and defaults off).

Contracts preserved (pinned by tests/test_qos_plane.py):
  * per-connection reply FIFO — shed replies are encoded in frame position,
    admitted commands dispatch in frame order, the writer-task completion
    queue is untouched;
  * at-most-once for possibly-applied add runs — a shed command NEVER
    reaches dispatch, and a run never spans a shed boundary, so no
    partially-applied coalesced add run is ever re-dispatched;
  * bit-identical results with the scheduler disarmed.

Observability (ISSUE 12): with the tracing plane armed
(``redisson_tpu/observe``), every frame's classification + tenant charge +
bulk-gate wait is recorded as its ``qos`` stage span (annotated
tenant/class/items/shed by ``server._serve_frame``) — ``TRACE GET ... BY
qos`` surfaces the frames that sat longest behind admission, and the
``stage.qos`` histogram rides the Prometheus exposition.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from redisson_tpu.core.ioplane import QosLedger

# -- global switch (same discipline as ioplane.set_overlap) -------------------

_qos = os.environ.get("RTPU_NO_QOS", "") not in ("1", "true", "yes")


def qos_enabled() -> bool:
    return _qos


def set_qos(on: bool) -> bool:
    """Flip the process-global QoS switch; returns the previous value
    (callers restore it — the A/B discipline of bench.py config 2q)."""
    global _qos
    prev = _qos
    _qos = bool(on)
    return prev


# -- device-item estimation ----------------------------------------------------

# blob verbs: (items per 8 payload bytes at blob arg index) — the occupancy
# unit the per-device lanes account and the unit tenant budgets are charged in
_BLOB8 = {b"BF.MADD64": 2, b"BF.MEXISTS64": 2, b"PFADD64": 2}
_BLOB8_AT3 = {b"BFA.MADD64": 3, b"BFA.MEXISTS64": 3, b"HLLA.MADD64": 3}
_BLOB4 = {b"SETBITSB": 2, b"GETBITSB": 2}
# KNN verbs (ISSUE 11): charged by their PARAMS vector payload — every 8
# payload bytes counts one device item, the same unit as the sketch blob
# verbs, so tenant budgets and lane ledgers see a stacked multi-query KNN
# frame as proportionally heavier than a single probe
_FT_KNN = frozenset((b"FT.SEARCH", b"FT.MSEARCH"))


def estimate_device_items(cmds: Sequence) -> int:
    """Rough op count a command list dispatches to one device — the
    occupancy unit lanes account, the CPU-replica occupancy model charges,
    and tenant token buckets spend.  Blob verbs count their batch elements;
    everything else counts 1.  (Moved here from server.py so the scheduler,
    the lane gate, and the bench all share ONE sizing rule.)"""
    total = 0
    for cmd in cmds:
        total += estimate_command_items(cmd)
    return total


def estimate_command_items(cmd) -> int:
    try:
        verb = bytes(cmd[0]).upper()
        if verb in _BLOB8:
            return max(1, len(cmd[2]) // 8)
        if verb in _BLOB8_AT3:
            return max(1, len(cmd[3]) // 8)
        if verb in _BLOB4:
            return max(1, len(cmd[2]) // 4)
        if verb in _FT_KNN:
            # the query-vector blob(s) ride PARAMS values: charge every
            # bulk byte argument (small option tokens stay under the bar)
            return max(1, sum(
                len(a) for a in cmd[2:]
                if isinstance(a, (bytes, bytearray)) and len(a) >= 64
            ) // 8)
        return 1
    except (IndexError, TypeError):
        return 1


# -- token bucket --------------------------------------------------------------


class TokenBucket:
    """Per-tenant budget over estimated device items.  ``rate <= 0`` means
    UNLIMITED (the default: shedding is opt-in, so an unconfigured server is
    bit-identical to the pre-QoS wire).  Not thread-safe on its own — the
    scheduler serializes access under its lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self.tokens = self.burst
        self.stamp: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self.stamp is None:
            self.stamp = now
        self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now

    def take(self, n: float, now: float) -> bool:
        """Spend `n` items if covered; an uncovered take spends NOTHING (the
        shed path must not double-punish the tenant's next frame)."""
        if self.rate <= 0:
            return True
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def level(self, now: float) -> float:
        """Current token level (refilled to `now`); unlimited buckets report
        their burst so gauges stay finite."""
        if self.rate <= 0:
            return self.burst
        self._refill(now)
        return self.tokens


class TenantState:
    __slots__ = ("bucket", "admitted_ops", "shed_ops", "shed_frames",
                 "weight")

    def __init__(self, bucket: TokenBucket, weight: float = 1.0):
        self.bucket = bucket
        self.admitted_ops = 0
        self.shed_ops = 0
        self.shed_frames = 0
        # service-class weight (ISSUE 19 satellite): gold=2.0/silver=1.0
        # style multiplier the fleet rebalance loop scales this tenant's
        # GLOBAL rate by; 1.0 (default) reproduces unweighted behavior
        self.weight = float(weight)


# -- admission -----------------------------------------------------------------


class Admission:
    """One frame's admission decision: its deadline class, tenant, estimated
    device items/bytes, and — when the tenant's bucket could not cover the
    whole frame — the per-command shed mask (True = shed, reply -BUSY, never
    dispatch)."""

    __slots__ = ("qos_class", "tenant", "items", "nbytes",
                 "shed_mask", "shed_count")

    def __init__(self, qos_class: str, tenant: str, items: int, nbytes: int,
                 shed_mask: Optional[List[bool]] = None, shed_count: int = 0):
        self.qos_class = qos_class
        self.tenant = tenant
        self.items = items
        self.nbytes = nbytes
        self.shed_mask = shed_mask
        self.shed_count = shed_count

    @property
    def interactive(self) -> bool:
        return self.qos_class == "interactive"


INTERACTIVE = "interactive"
BULK = "bulk"
CLASSES = (INTERACTIVE, BULK)


def _frame_nbytes(commands: Sequence) -> int:
    total = 0
    for cmd in commands:
        try:
            for a in cmd:
                if isinstance(a, (bytes, bytearray)):
                    total += len(a)
        except TypeError:
            continue
    return total


def tenant_of_frame(ctx, commands: Sequence) -> str:
    """Tenant of a frame: the connection-declared tenant (CLIENT QOS ...
    TENANT <t>) wins; otherwise the {hashtag} of the frame's first keyed
    command (the stacked-bank kernels are already tenant-segmented the same
    way — one slot column per filter); otherwise "default"."""
    t = getattr(ctx, "tenant", None)
    if t:
        return t
    for cmd in commands:
        try:
            key = cmd[1]
        except (IndexError, TypeError):
            continue
        if not isinstance(key, (bytes, bytearray)):
            continue
        b = bytes(key)
        i = b.find(b"{")
        if i >= 0:
            j = b.find(b"}", i + 1)
            if j > i + 1:
                return b[i + 1 : j].decode(errors="replace")
        return "default"  # first keyed command decides; no tag = default
    return "default"


class WindowScheduler:
    """The server's QoS policy object: classification, per-tenant budgets,
    admission (shed masks), and the in-flight ledger every layer's gauges
    read.  One per TpuServer; `armed` consults the process-global switch
    LIVE so ``set_qos(False)`` / ``RTPU_NO_QOS=1`` disarms running servers
    exactly like ``ioplane.set_overlap``."""

    def __init__(self, enabled: Optional[bool] = None, *,
                 tenant_rate: float = 0.0,
                 tenant_burst: Optional[float] = None,
                 interactive_max_items: int = 256,
                 interactive_deadline_ms: float = 0.0,
                 bulk_slots: int = 0,
                 bulk_subwindow_items: int = 0):
        self.enabled = qos_enabled() if enabled is None else bool(enabled)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = tenant_burst  # None = follow rate
        self.interactive_max_items = int(interactive_max_items)
        # flush-window deadline (0 = trigger off, the historical shape):
        # CONFIG SET qos-interactive-deadline-ms arms ioplane's deadline-
        # triggered window close (the server pushes the value into the
        # process-global FlushPipeline default AND every live lane pipeline)
        self.interactive_deadline_ms = float(interactive_deadline_ms)
        # bulk admission slots: how many bulk-class frames may be in dispatch
        # at once across ALL connections (0 = derive from the server's worker
        # count at wiring time: workers - 1, so one dispatch slot is always
        # reserved for interactive traffic)
        self.bulk_slots = int(bulk_slots)
        # preemptible sub-windows (ISSUE 18): target device items per bulk
        # sub-window — an oversized fused run splits into chunks of at most
        # this many items, with a lane preemption point between chunks
        # (0 = splitting off, the historical whole-window dispatch).  The
        # server pushes the value into the process-global
        # ioplane.set_bulk_subwindow_items so every lane's dispatch path
        # shares it.
        self.bulk_subwindow_items = int(bulk_subwindow_items)
        # penalty for a FULLY-refused frame: the offending connection's read
        # loop parks this long after its -BUSY replies flush, so a client
        # that spins on BUSY instead of backing off cannot convert the cheap
        # shed path into a parse-plane DoS.  Only the shed connection pays;
        # admitted work is never delayed (this is not queue residency — the
        # frame was already answered).
        self.shed_penalty_ms = 5.0
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantState] = {}
        self.ledger = QosLedger()
        self.shed_ops = 0
        self.shed_frames = 0

    # -- arming ---------------------------------------------------------------

    @property
    def armed(self) -> bool:
        return self.enabled and _qos

    # -- config surface (CONFIG GET/SET qos-*) --------------------------------

    def config_view(self) -> Dict[str, object]:
        return {
            "qos-enabled": int(self.enabled),
            "qos-tenant-rate": self.tenant_rate,
            "qos-tenant-burst": (
                self.tenant_burst if self.tenant_burst is not None else ""
            ),
            "qos-interactive-max-items": self.interactive_max_items,
            "qos-interactive-deadline-ms": self.interactive_deadline_ms,
            "qos-bulk-slots": self.bulk_slots,
            "qos-bulk-subwindow-items": self.bulk_subwindow_items,
            "qos-shed-penalty-ms": self.shed_penalty_ms,
        }

    def config_set(self, key: str, value: str) -> bool:
        if key == "qos-enabled":
            self.enabled = value not in ("0", "false", "no", "off")
            return True
        if key == "qos-tenant-rate":
            self.tenant_rate = float(value)
            self._reset_buckets()
            return True
        if key == "qos-tenant-burst":
            self.tenant_burst = float(value) if value else None
            self._reset_buckets()
            return True
        if key == "qos-interactive-max-items":
            self.interactive_max_items = int(value)
            return True
        if key == "qos-interactive-deadline-ms":
            self.interactive_deadline_ms = float(value)
            return True
        if key == "qos-bulk-slots":
            self.bulk_slots = int(value)
            return True
        if key == "qos-bulk-subwindow-items":
            self.bulk_subwindow_items = max(0, int(value))
            return True
        if key == "qos-shed-penalty-ms":
            self.shed_penalty_ms = float(value)
            return True
        return False

    def _reset_buckets(self) -> None:
        """Rate/burst reconfiguration re-mints every tenant's bucket (stats
        are preserved — only the budget changes)."""
        with self._lock:
            for ts in self._tenants.values():
                ts.bucket = TokenBucket(self.tenant_rate, self.tenant_burst)

    def set_tenant_rate(self, tenant: str, rate: float,
                        burst: Optional[float] = None) -> None:
        """Per-tenant budget override (the ``CLUSTER QOS REBALANCE``
        actuator and the test hook; the uniform ``qos-tenant-rate`` knob
        covers the common case).  An EXISTING bucket is retargeted in
        place — tokens are preserved (capped at the new burst), never
        re-minted: the fleet rebalance loop pushes every sweep, and a
        re-mint would hand the tenant a fresh burst per push, inflating
        its effective budget by burst/interval."""
        with self._lock:
            ts = self._tenants.get(tenant)
            if ts is None:
                self._tenants[tenant] = TenantState(TokenBucket(rate, burst))
                return
            b = ts.bucket
            b.rate = float(rate)
            b.burst = float(burst if burst is not None else max(rate, 1.0))
            if b.tokens > b.burst:
                b.tokens = b.burst

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Per-tenant service-class weight (``CLUSTER QOS REBALANCE ...
        WEIGHT``).  Orthogonal to the bucket: the weight only changes how
        the FLEET loop sizes this tenant's global rate, so setting it never
        re-mints or retargets tokens (the token-preserving contract of
        set_tenant_rate is untouched)."""
        if weight <= 0:
            raise ValueError("tenant weight must be positive")
        with self._lock:
            ts = self._tenants.get(tenant)
            if ts is None:
                ts = TenantState(
                    TokenBucket(self.tenant_rate, self.tenant_burst)
                )
                self._tenants[tenant] = ts
            ts.weight = float(weight)

    def tenant_weight(self, tenant: str) -> float:
        with self._lock:
            ts = self._tenants.get(tenant)
            return ts.weight if ts is not None else 1.0

    # -- classification -------------------------------------------------------

    def classify(self, ctx, commands: Sequence) -> Tuple[str, List[int], int]:
        """(qos_class, per-command items, total items).  The connection's
        declared class wins; the heuristic default is: small frames (total
        estimated device items <= qos-interactive-max-items) are
        interactive, everything else is bulk."""
        per = [estimate_command_items(c) for c in commands]
        total = sum(per)
        declared = getattr(ctx, "qos_class", None)
        if declared in CLASSES:
            return declared, per, total
        cls = INTERACTIVE if total <= self.interactive_max_items else BULK
        return cls, per, total

    # -- admission ------------------------------------------------------------

    def admit(self, ctx, commands: Sequence,
              now: Optional[float] = None) -> Admission:
        """Admit one parsed frame: classify, charge the tenant's bucket
        command by command IN FRAME ORDER, and shed the uncovered tail.
        Shedding is greedy-prefix per command (not all-or-nothing): the
        admitted prefix keeps its frame order, the shed suffix replies
        -BUSY without ever dispatching — so a coalesced add run can never
        be partially applied by admission (runs are additionally split at
        shed boundaries, core/coalesce.runs_within_admission)."""
        if now is None:
            now = time.monotonic()
        cls, per, total = self.classify(ctx, commands)
        tenant = tenant_of_frame(ctx, commands)
        nbytes = _frame_nbytes(commands)
        shed_mask: Optional[List[bool]] = None
        shed = 0
        with self._lock:
            ts = self._tenants.get(tenant)
            if ts is None:
                ts = self._tenants[tenant] = TenantState(
                    TokenBucket(self.tenant_rate, self.tenant_burst)
                )
            if ts.bucket.rate > 0:
                for i, n in enumerate(per):
                    if shed_mask is None and ts.bucket.take(n, now):
                        continue
                    # once a command sheds, the REST of the frame sheds too:
                    # admitting commands past a shed hole would reorder the
                    # tenant's effective stream relative to its replies
                    if shed_mask is None:
                        shed_mask = [False] * len(per)
                    shed_mask[i] = True
                    shed += 1
            admitted_items = total - sum(
                n for n, s in zip(per, shed_mask or []) if s
            )
            ts.admitted_ops += admitted_items
            if shed:
                ts.shed_ops += total - admitted_items
                ts.shed_frames += 1
                self.shed_ops += total - admitted_items
                self.shed_frames += 1
        return Admission(cls, tenant, admitted_items, nbytes,
                         shed_mask, shed)

    # -- in-flight accounting -------------------------------------------------

    def begin(self, adm: Admission) -> None:
        self.ledger.enter(adm.qos_class, adm.items, adm.nbytes)

    def end(self, adm: Admission) -> None:
        self.ledger.exit(adm.qos_class, adm.items, adm.nbytes)

    # -- observability --------------------------------------------------------

    def census(self) -> Dict[str, float]:
        """Drain-to-zero gauges + the shed counters, census/metrics shaped.
        The in-flight rows MUST return to 0 at quiesce (the soak's
        flat-census assertion guards the new accounting)."""
        out = self.ledger.census(prefix="qos")
        out["qos_shed_ops_total"] = float(self.shed_ops)
        out["qos_shed_frames_total"] = float(self.shed_frames)
        return out

    def tenant_table(
        self, now: Optional[float] = None,
    ) -> List[Tuple[str, float, int, int, int, float]]:
        """[(tenant, bucket_level, admitted_ops, shed_ops, shed_frames,
        weight)] — the CLUSTER QOS wire view."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            return [
                (name, ts.bucket.level(now), ts.admitted_ops,
                 ts.shed_ops, ts.shed_frames, ts.weight)
                for name, ts in sorted(self._tenants.items())
            ]

    def tenant_sheds(self) -> Dict[str, int]:
        with self._lock:
            return {n: ts.shed_ops for n, ts in self._tenants.items()}


def busy_error(tenant: str) -> str:
    """The load-shed reply: -BUSY, never queue residency (the vLLM
    admission-refusal discipline on a RESP wire).  Clients back off and
    retry; the error names the tenant so multi-tenant proxies can bill."""
    return (
        f"BUSY QoS budget exhausted for tenant '{tenant}'; "
        "retry after backoff"
    )
